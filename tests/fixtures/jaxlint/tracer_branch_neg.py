"""Fixture: static (shape/dtype/None) guards and device control flow
inside jit — all legal."""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def shape_guard(x):
    if x.ndim >= 2 and x.shape[0] > 1:  # shapes are trace-time static
        return jnp.sum(x, axis=0)
    return x


@jax.jit
def none_guard(x, hidden=None):
    if hidden is None:  # identity guards are static
        return x
    return x + hidden


@functools.partial(jax.jit, static_argnums=1)
def static_branch(x, mode):
    if mode == "double":  # static_argnums: a Python value, not a tracer
        return x * 2
    return x


@jax.jit
def device_select(x):
    return jnp.where(x > 0, x, -x)  # value-dependent, but traced
