"""Fixture: intentional per-iteration sync, suppressed with a reason."""

import jax


def make_step():
    return jax.jit(lambda p, b: (p, b.sum()))


def epoch_with_early_stop(params, batches, tol):
    step = make_step()
    for batch in batches:
        params, loss = step(params, batch)
        # jaxlint: disable=host-sync -- early-stop check needs the value each step
        if float(loss) < tol:
            break
    return params
