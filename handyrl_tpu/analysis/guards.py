"""Runtime guards: retrace, host-transfer, and resharding accounting.

The static rules in :mod:`.rules`/:mod:`.shardrules` prove what they
can from source; the guards here measure what only a running program
knows:

  * :class:`RetraceGuard` wraps jitted callables and counts retraces —
    the learner's update step must compile exactly once per run per
    mesh shape, and a shape-churn regression (uneven batches, a dtype
    flip) shows up as ``compiles > 1`` long before it shows up as a
    100x slowdown on a TPU profile.  Counting is host-side abstract
    signatures ((treedef, shape, dtype) per call — the part of the jit
    cache key shape churn perturbs), so it works for any callable and
    ignores the committed-ness variants that donated-buffer loops
    create in the real jit cache without recompiling.
  * :class:`HostTransferGuard` counts device->host transfers by
    interposing on the Python-level sync entry points
    (``jax.device_get``, ``np.asarray``, ``np.array``) while armed.
    C-level syncs (``.item()``, ``float()`` on an array) cannot be
    intercepted from Python — the static ``host-sync`` rule covers
    those paths instead.
  * :class:`ShardingContractGuard` wraps jitted callables and counts
    RESHARDING at the call boundary: the first call fixes the
    per-argument sharding contract (per abstract signature), and any
    later call whose leaf arrives laid out differently is an implicit
    reshard — XLA silently copies the array onto the expected layout
    before the program runs, defeating donation and doubling the
    argument's HBM.  The static ``implicit-reshard`` rule catches the
    cases provable from source; this guard catches the rest (shardings
    threaded through config and checkpoints).
  * :class:`StallWatchdog` samples the learner's control-plane loops
    (server loop, communicator reader/writer threads): each loop beats
    once per pass, and a loop silent past ``max_stall_seconds`` is a
    counted ``stall_event`` with its thread's stack dumped once — the
    runtime complement of commlint's ``unbounded-recv``/
    ``reply-mismatch`` rules, catching the wedges the analyzer could
    not prove (or that a suppression claimed were bounded).
  * :class:`NumericsGuard` wraps the update step and latches the
    per-leaf dtype treedef of its arguments at first call: a later
    call whose leaf arrives with a different concrete dtype is a
    counted ``numerics_contract_break`` (the runtime twin of
    numlint's ``dtype-split-brain``/``implicit-upcast`` rules), and a
    weak<->concrete flip is a counted ``weak_upcast`` (the runtime
    twin of ``weak-type-promotion`` — each flip is also a fresh jit
    cache entry).  It also counts nonfinite update steps: the step
    computes a cheap in-graph flag over the loss and grad global
    norm (see ``ops/update.py``), the learner feeds the fetched
    per-step flags to :meth:`NumericsGuard.note_step` at the epoch
    boundary (no extra host syncs), and ``max_nonfinite_steps > 0``
    turns the count into a hard :class:`NumericsError` budget.
  * :class:`LockOrderGuard` wraps the package's lock objects in
    timing/ordering proxies: per-epoch ``lock_contention_sec`` (wall
    time threads spent waiting on guarded locks) and
    ``lock_order_inversions`` (two locks observed acquired in both
    orders at runtime) — the runtime complement of racelint's
    ``lock-order-cycle``/``blocking-under-lock`` rules, catching the
    interleavings the analyzer could not reach (locks passed through
    config, dynamic handler sets).
  * :class:`ResourceLedger` samples the process's resource population
    once per epoch — ``/proc/self/fd`` count (and how many are
    sockets), ``threading.enumerate()`` count, and the shared-memory
    segments visible in ``/dev/shm`` — and reports ``fd_count`` /
    ``thread_count`` / ``shm_segments`` / ``resource_growth`` into
    the metrics jsonl: the runtime complement of leaklint's
    lifecycle rules, catching the leaks the analyzer could not prove
    (handles escaping into containers, C-level fds).  Growth is
    measured against a post-warmup baseline, so a weeks-long serving
    replica that slowly accretes fds is visible as a rising
    ``resource_growth`` curve long before the kernel's fd limit
    kills it; ``max_fd_growth > 0`` turns the budget into a hard
    :class:`ResourceError`.

All are near-zero-cost (an isinstance check / an integer bump per
event) and run armed in production: the learner feeds their per-epoch
deltas into the metrics jsonl, so a regression is visible on the same
plots as the loss curves.
"""

import sys
import threading
import time
import traceback

import jax
import numpy as np


class RetraceError(RuntimeError):
    """A guarded jit compiled more often than its budget allows."""


class HostTransferError(RuntimeError):
    """More device->host transfers than the armed budget allows."""


class ShardingContractError(RuntimeError):
    """More resharding copies at a jit boundary than the budget."""


class NumericsError(RuntimeError):
    """More nonfinite update steps than the armed budget allows."""


class _GuardedJit:
    """Callable proxy that counts retraces of one jitted fn.

    Counts distinct abstract call signatures — (treedef, shape, dtype)
    per leaf — which is exactly the part of the jit cache key that
    shape churn perturbs.  The jit's own ``_cache_size()`` is NOT used:
    it also keys on committed-ness/sharding, so a donated-buffer loop
    (whose second call feeds back the first call's committed outputs)
    legitimately grows that cache without any XLA recompile, and the
    guard must not report it as one.
    """

    # every call is fingerprinted for the first WARM_CALLS, then one
    # in SAMPLE_EVERY: the flatten-and-shape walk over params +
    # optimizer state + batch is ~tens of microseconds, which is real
    # money in a hot loop whose design goal is "the host passes three
    # scalars per step".  Persistent shape churn is still caught
    # within SAMPLE_EVERY steps; a single-call transient between
    # samples can slip through (documented trade).
    WARM_CALLS = 64
    SAMPLE_EVERY = 8

    def __init__(self, guard, fn, label=None):
        self._guard = guard
        self._fn = fn
        self._label = label or guard.name
        self._signatures = set()
        self._calls = 0

    def _signature(self, args, kwargs):
        leaves, treedef = jax.tree.flatten((args, kwargs))
        return treedef, tuple(
            (np.shape(leaf), getattr(leaf, "dtype", type(leaf)))
            for leaf in leaves
        )

    def __call__(self, *args, **kwargs):
        self._calls += 1
        if (self._calls <= self.WARM_CALLS
                or self._calls % self.SAMPLE_EVERY == 0):
            # signature BEFORE the call: donated args are dead after
            sig = self._signature(args, kwargs)
            if sig not in self._signatures:
                self._signatures.add(sig)
                # a NEW signature is (to within the sampling trade
                # above) a fresh compile: the guard's on_compile hook
                # fires here, BEFORE the call executes, because the
                # abstract lowering a cost-analysis harvest needs is
                # only safe while donated argument buffers are alive.
                # Injected rather than imported, like StallWatchdog's
                # on_stall: analysis stays standalone
                hook = self._guard.on_compile
                if hook is not None:
                    try:
                        hook(self._label, self._fn, args, kwargs)
                    except Exception as exc:  # must not kill the step
                        print("WARNING: on_compile hook failed "
                              f"({exc!r})")
        out = self._fn(*args, **kwargs)
        self._guard._after_call()
        return out

    @property
    def compiles(self) -> int:
        return len(self._signatures)

    def __getattr__(self, name):
        return getattr(self._fn, name)


class RetraceGuard:
    """Compile-count accounting over one or more jitted callables.

    ::

        guard = RetraceGuard(max_compiles=1, name="update_step")
        step = guard.wrap(make_update_step(...))
        ...
        guard.compiles        # total compilations so far
        guard.check()         # raises RetraceError over budget

    ``max_compiles=0`` disables the assertion (counting only).  The
    check also runs after every wrapped call, so a retrace surfaces at
    (or within a few steps of — see the sampling note on _GuardedJit)
    the step that caused it, not at the end of the run.

    ``allowance`` widens the budget for compiles the caller knows are
    legitimate — the learner sets it to the replay ring's growth
    count, so a designed T_max re-layout never trips the assertion.
    """

    def __init__(self, max_compiles: int = 0, name: str = "jit"):
        self.max_compiles = int(max_compiles or 0)
        # extra budget for compiles the caller KNOWS are legitimate
        # (e.g. a replay-ring growth re-lays its buffers and the fused
        # step must recompile once): the effective budget is
        # ``max_compiles + allowance``
        self.allowance = 0
        self.name = name
        self.calls = 0
        self._wrapped = []
        # called once per NEWLY seen abstract signature with
        # (label, fn, args, kwargs), BEFORE the call runs — the
        # telemetry cost model hooks its ``compiled.cost_analysis()``
        # harvest here.  Injected rather than imported (the
        # StallWatchdog.on_stall pattern): analysis stays standalone
        self.on_compile = None

    def wrap(self, fn, label=None):
        """Wrap a jitted callable; returns the counting proxy.
        ``label`` names the program for the on_compile hook (defaults
        to the guard's name)."""
        proxy = _GuardedJit(self, fn, label=label)
        self._wrapped.append(proxy)
        return proxy

    @property
    def compiles(self) -> int:
        return sum(proxy.compiles for proxy in self._wrapped)

    def _after_call(self):
        self.calls += 1
        self.check()

    def check(self):
        budget = self.max_compiles + self.allowance
        if self.max_compiles and self.compiles > budget:
            raise RetraceError(
                f"{self.name} compiled {self.compiles} times "
                f"(budget {budget}) over {self.calls} calls "
                f"— input shapes/dtypes are churning; pad batches to "
                f"fixed shapes or mark the varying argument static")


class _ShardedCall:
    """Callable proxy that checks one jitted fn's sharding contract.

    Each argument treedef carries a per-leaf contract that LATCHES on
    the first COMMITTED sharding seen at that leaf; a later committed
    leaf laid out differently is an implicit reshard — XLA copies it
    onto the compiled program's layout before running, and on donated
    arguments the copy defeats the donation.  Two deliberate skips
    keep the count honest:

      * uncommitted values (host numpy, fresh un-placed jnp results —
        ``committed`` is False) have no layout of their own; the jit's
        first placement of them — e.g. the freshly ``optimizer.init``-ed
        state on the learner's first step — is designed
        initialization, not a resharding copy.  On a single device
        everything stays uncommitted and there is nothing to reshard,
        so the guard is inert there by construction;
      * a NEW treedef is a different program (its own compile, its own
        contract), not a reshard of the old one — while a shape-only
        change (the replay ring's T_max growth) keeps the contract,
        and its re-laid buffers legitimately keep their shardings.

    Shardings are read BEFORE the call (donated buffers are dead
    after).  Limitation, documented: an input that arrives on the
    WRONG layout from its very first committed call latches that
    layout and stays quiet here — proving the intended layout from
    source is the static ``implicit-reshard`` rule's job.
    """

    WARM_CALLS = _GuardedJit.WARM_CALLS
    SAMPLE_EVERY = _GuardedJit.SAMPLE_EVERY

    def __init__(self, guard, fn):
        self._guard = guard
        self._fn = fn
        self._contracts = {}
        self._calls = 0
        self.copies = 0

    def _check(self, args, kwargs):
        leaves, treedef = jax.tree.flatten((args, kwargs))
        contract = self._contracts.get(treedef)
        if contract is None or len(contract) != len(leaves):
            contract = self._contracts[treedef] = [None] * len(leaves)
        mismatched = 0
        for i, leaf in enumerate(leaves):
            sharding = getattr(leaf, "sharding", None)
            if sharding is None \
                    or not getattr(leaf, "committed", False):
                continue
            if contract[i] is None:
                contract[i] = sharding
            elif contract[i] != sharding:
                mismatched += 1
        if mismatched:
            self._guard._note(mismatched, self)

    def __call__(self, *args, **kwargs):
        self._calls += 1
        if (self._calls <= self.WARM_CALLS
                or self._calls % self.SAMPLE_EVERY == 0):
            self._check(args, kwargs)
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


class ShardingContractGuard:
    """Resharding-copy accounting over one or more jitted callables.

    ::

        guard = ShardingContractGuard(name="update_step")
        step = guard.wrap(make_sharded_update_step(...))
        ...
        guard.copies          # resharding copies observed so far
        guard.snapshot()      # copies since the previous snapshot

    The learner arms one around the update step and reports the
    per-epoch delta as ``resharding_copies`` in the metrics jsonl: the
    steady-state value is 0, because params/optimizer state are
    donated back on their own shardings and batches arrive staged onto
    the batch sharding.  Any positive count means an input changed
    layout mid-run — a silent device-to-device copy per step, exactly
    the Podracer failure mode shardlint's ``implicit-reshard`` rule
    catches statically.  ``max_copies > 0`` turns the count into a
    hard assertion (:class:`ShardingContractError`) raised at the
    offending call.  Sampling matches :class:`RetraceGuard`: every
    call during warmup, then one in SAMPLE_EVERY.
    """

    def __init__(self, max_copies: int = 0, name: str = "jit"):
        self.max_copies = int(max_copies or 0)
        self.name = name
        self._last_snapshot = 0
        self._wrapped = []

    def wrap(self, fn):
        """Wrap a jitted callable; returns the checking proxy."""
        proxy = _ShardedCall(self, fn)
        self._wrapped.append(proxy)
        return proxy

    @property
    def copies(self) -> int:
        return sum(proxy.copies for proxy in self._wrapped)

    def _note(self, mismatched: int, proxy: "_ShardedCall"):
        proxy.copies += mismatched
        if self.max_copies and self.copies > self.max_copies:
            raise ShardingContractError(
                f"{self.name}: {self.copies} resharding copies "
                f"(budget {self.max_copies}) — an argument's sharding "
                f"changed mid-run, so XLA inserts a silent copy (and "
                f"defeats donation) on every call; re-stage the input "
                f"on the sharding the jit was built with")

    def snapshot(self) -> int:
        """Copies since the previous snapshot (per-epoch delta)."""
        delta = self.copies - self._last_snapshot
        self._last_snapshot = self.copies
        return delta


class _DtypeCall:
    """Callable proxy that checks one jitted fn's dtype contract.

    Each argument treedef latches a per-leaf ``(dtype, weak_type)``
    signature at first call.  A later call whose leaf arrives with a
    different *concrete* dtype is a contract break — the jit silently
    retraces (or upcasts) and the mixed-precision regime's declared
    boundary is gone.  A weak<->concrete flip (or a weak Python
    scalar changing type) is a weak upcast: cheaper, but each flip is
    its own jit cache entry and its own promotion hazard.  A NEW
    treedef is a different program and gets a fresh contract, exactly
    like :class:`_ShardedCall`; host-side leaves that are neither
    arrays nor Python scalars are skipped.  Signatures are read
    BEFORE the call (donated buffers are dead after) and sampled on
    the :class:`_GuardedJit` schedule.
    """

    WARM_CALLS = _GuardedJit.WARM_CALLS
    SAMPLE_EVERY = _GuardedJit.SAMPLE_EVERY

    def __init__(self, guard, fn):
        self._guard = guard
        self._fn = fn
        self._contracts = {}
        self._calls = 0
        self.contract_breaks = 0
        self.weak_upcasts = 0

    @staticmethod
    def _leaf_sig(leaf):
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None:
            return (str(dtype), bool(getattr(leaf, "weak_type", False)))
        if isinstance(leaf, (bool, int, float)):
            return (type(leaf).__name__, True)
        return None  # host-side leaf with no dtype story

    def _check(self, args, kwargs):
        leaves, treedef = jax.tree.flatten((args, kwargs))
        contract = self._contracts.get(treedef)
        if contract is None or len(contract) != len(leaves):
            contract = self._contracts[treedef] = [None] * len(leaves)
        breaks = upcasts = 0
        for i, leaf in enumerate(leaves):
            sig = self._leaf_sig(leaf)
            if sig is None:
                continue
            if contract[i] is None:
                contract[i] = sig
                continue
            if sig == contract[i]:
                continue
            (dtype0, weak0), (dtype1, weak1) = contract[i], sig
            if weak0 or weak1:
                upcasts += 1
            elif dtype0 != dtype1:
                breaks += 1
        if breaks or upcasts:
            self.contract_breaks += breaks
            self.weak_upcasts += upcasts

    def __call__(self, *args, **kwargs):
        self._calls += 1
        if (self._calls <= self.WARM_CALLS
                or self._calls % self.SAMPLE_EVERY == 0):
            self._check(args, kwargs)
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


class NumericsGuard:
    """Dtype-contract + nonfinite-step accounting for the update step.

    ::

        guard = NumericsGuard(max_nonfinite=0, name="update_step")
        step = guard.wrap(make_update_step(...))
        ...
        guard.note_step(m["nonfinite"])   # per step, at epoch fetch
        guard.snapshot()                  # per-epoch metric deltas

    Two independent counters ride one guard:

      * **dtype contract** — :meth:`wrap` proxies the jitted step
        through :class:`_DtypeCall`, which latches each argument
        leaf's ``(dtype, weak_type)`` at first call and counts later
        divergence (``numerics_contract_breaks`` for concrete flips,
        ``weak_upcasts`` for weak-type churn).  Steady state is 0/0:
        params and optimizer state are donated back unchanged and
        batches arrive staged on the pipeline's fixed dtypes.
      * **nonfinite steps** — the update step computes a scalar
        in-graph flag (loss or grad-global-norm nonfinite, see
        ``ops/update.py``) that rides the per-step metrics dict; the
        learner feeds the flags to :meth:`note_step` at the epoch
        boundary, after the ONE ``jax.device_get`` it already does —
        zero extra host traffic.  ``max_nonfinite > 0`` raises
        :class:`NumericsError` when the cumulative count exceeds the
        budget (the default 0 counts without asserting, matching the
        other guards).

    ``enabled=False`` makes the guard a true no-op: :meth:`wrap`
    returns its argument unchanged and every counter stays 0.
    """

    def __init__(self, max_nonfinite: int = 0, name: str = "jit",
                 enabled: bool = True):
        self.max_nonfinite = int(max_nonfinite or 0)
        self.name = name
        self.enabled = bool(enabled)
        self.nonfinite_steps = 0
        self._last_nonfinite = 0
        self._last_breaks = 0
        self._last_upcasts = 0
        self._wrapped = []

    def wrap(self, fn):
        """Wrap a jitted callable; returns the checking proxy (or
        ``fn`` itself when the guard is disabled)."""
        if not self.enabled:
            return fn
        proxy = _DtypeCall(self, fn)
        self._wrapped.append(proxy)
        return proxy

    @property
    def contract_breaks(self) -> int:
        return sum(p.contract_breaks for p in self._wrapped)

    @property
    def weak_upcasts(self) -> int:
        return sum(p.weak_upcasts for p in self._wrapped)

    def note_step(self, flag) -> bool:
        """Count one update step's nonfinite flag (0.0 clean, 1.0
        poisoned — at most one count per step by construction).
        Returns whether the step was nonfinite."""
        if not self.enabled:
            return False
        try:
            bad = float(flag) >= 0.5
        except (TypeError, ValueError):
            return False
        if bad:
            self.nonfinite_steps += 1
            if self.max_nonfinite \
                    and self.nonfinite_steps > self.max_nonfinite:
                raise NumericsError(
                    f"{self.name}: {self.nonfinite_steps} nonfinite "
                    f"update steps (budget {self.max_nonfinite}) — "
                    f"the loss or gradient went NaN/Inf; check the "
                    f"nonfinite-risk lint findings and the lr/clip "
                    f"settings before the parameters are unrecoverable")
        return bad

    def snapshot(self) -> dict:
        """Per-epoch deltas since the previous snapshot, keyed exactly
        as the metrics jsonl expects."""
        breaks, upcasts = self.contract_breaks, self.weak_upcasts
        out = {
            "nonfinite_steps": self.nonfinite_steps
            - self._last_nonfinite,
            "numerics_contract_breaks": breaks - self._last_breaks,
            "weak_upcasts": upcasts - self._last_upcasts,
        }
        self._last_nonfinite = self.nonfinite_steps
        self._last_breaks = breaks
        self._last_upcasts = upcasts
        return out

    def stats(self) -> dict:
        """Cumulative totals for the status endpoint."""
        return {"nonfinite_steps": self.nonfinite_steps,
                "numerics_contract_breaks": self.contract_breaks,
                "weak_upcasts": self.weak_upcasts,
                "max_nonfinite_steps": self.max_nonfinite}


class StallWatchdog:
    """Samples registered control-plane loops for silent wedges.

    ::

        dog = StallWatchdog(max_stall_seconds=60.0)
        dog.start()
        while serving:
            dog.beat("server")     # once per loop pass
            ...
        dog.stop()

    Each watched loop calls :meth:`beat` once per pass (a dict store —
    nanoseconds, safe from any thread).  A background sampler checks
    every ``max_stall_seconds / 4``: a loop whose last beat is older
    than the threshold transitions to STALLED — one counted
    ``stall_event``, plus a one-shot stack dump of the silent thread
    (via ``sys._current_frames``) so the log says *where* it is
    blocked, not just that it is.  A loop that beats again recovers
    and can stall again later (each episode counts once).

    The learner arms one over its server loop and the communicator's
    reader/writer threads and reports the per-epoch ``stall_events``
    delta in the metrics jsonl next to ``retrace_count`` /
    ``resharding_copies`` / the heartbeat stats; the steady-state
    value is 0 because every control-plane wait in the package is
    bounded (a timeout, a sweep, or a supervised peer — the commlint
    ``unbounded-recv`` contract).  Any positive count means a wedge
    the static analysis could not see: a blocked round trip whose
    suppression reason turned out to be wrong, a handler that stopped
    replying, a lock held across an epoch.

    The clock is injectable so expiry tests are exact; with an
    injected clock the sampler thread is usually left unstarted and
    :meth:`sample` driven manually.
    """

    def __init__(self, max_stall_seconds: float = 60.0,
                 clock=time.monotonic):
        self.max_stall = float(max_stall_seconds or 60.0)
        self.clock = clock
        self.stall_events = 0
        self._last_snapshot = 0
        self._loops = {}  # name -> [last_beat, stalled, thread_ident]
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        # called once per NEWLY stalled loop with (name, silent_sec):
        # the learner wires the telemetry flight-recorder dump here, so
        # a stall leaves its causal timeline behind, not just a stack.
        # Injected rather than imported: analysis stays standalone
        self.on_stall = None

    # -- liveness intake --------------------------------------------
    def beat(self, loop: str = "server"):
        """Prove one loop alive (call once per loop pass)."""
        now = self.clock()
        with self._lock:
            state = self._loops.get(loop)
            if state is None:
                self._loops[loop] = [now, False,
                                     threading.get_ident()]
            else:
                state[0] = now
                state[1] = False  # a beating loop has recovered
                state[2] = threading.get_ident()

    # -- sampling ----------------------------------------------------
    def sample(self, now=None) -> int:
        """One watchdog pass: returns how many loops NEWLY stalled."""
        if now is None:
            now = self.clock()
        newly = []
        with self._lock:
            for name, state in self._loops.items():
                if state[1] or now - state[0] <= self.max_stall:
                    continue
                state[1] = True
                self.stall_events += 1
                newly.append((name, now - state[0], state[2]))
        hook = self.on_stall
        for name, silent, ident in newly:
            self._dump(name, silent, ident)
            if hook is not None:
                try:
                    hook(name, silent)
                except Exception as exc:  # a dead hook must not kill
                    print(f"WARNING: on_stall hook failed ({exc!r})")
        return len(newly)

    def _dump(self, name, silent, ident):
        frame = sys._current_frames().get(ident)
        where = "".join(traceback.format_stack(frame)) if frame \
            else "  <thread gone>\n"
        print(f"WARNING: control-plane loop '{name}' silent for "
              f"{silent:.1f}s (> max_stall_seconds={self.max_stall}); "
              f"stack of the stalled thread:\n{where}", end="")

    def snapshot(self) -> int:
        """Stall events since the previous snapshot (per-epoch delta)."""
        with self._lock:
            delta = self.stall_events - self._last_snapshot
            self._last_snapshot = self.stall_events
            return delta

    # -- sampler thread ----------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        interval = max(0.5, self.max_stall / 4.0)
        while not self._stop.wait(interval):
            self.sample()

    def stop(self):
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)


class HostTransferGuard:
    """Context manager counting device->host transfers while armed.

    ::

        with HostTransferGuard() as guard:
            run_epoch()
        print(guard.transfers)

    Counts one transfer per ``jax.device_get`` call that touches a jax
    array and one per ``np.asarray``/``np.array`` call on a jax array.
    A long-lived guard can stay armed across epochs and report deltas
    via :meth:`snapshot`.  Not reentrant (it patches module-level
    entry points); arm one per process.
    """

    def __init__(self, max_transfers: int = 0):
        self.max_transfers = int(max_transfers or 0)
        self.transfers = 0
        self._last_snapshot = 0
        self._lock = threading.Lock()
        self._saved = None

    # -- counting ----------------------------------------------------
    @staticmethod
    def _contains_jax_array(value, budget: int = 64, depth: int = 3):
        """Bounded containment probe: visits at most ``budget`` nodes
        ``depth`` levels deep.  The guard is armed process-wide, so
        this must NOT walk arbitrary host data — ``np.array(big_list)``
        with a million floats costs a handful of isinstance checks
        here, not a full tree flatten.  Deeply-buried device arrays
        past the bound go uncounted (documented heuristic)."""
        if isinstance(value, jax.Array):
            return True
        if depth == 0 or budget <= 0:
            return False
        if isinstance(value, dict):
            items = value.values()
        elif isinstance(value, (list, tuple)):
            items = value
        else:
            return False
        for i, item in enumerate(items):
            if i >= budget:
                return False
            if HostTransferGuard._contains_jax_array(
                    item, budget // 4, depth - 1):
                return True
        return False

    def _note(self, value) -> None:
        if isinstance(value, np.ndarray):
            return  # fast path: host arrays dominate np.asarray traffic
        if not self._contains_jax_array(value):
            return
        with self._lock:
            self.transfers += 1
            if self.max_transfers and self.transfers > self.max_transfers:
                raise HostTransferError(
                    f"host-transfer budget exceeded: {self.transfers} "
                    f"device->host transfers (budget "
                    f"{self.max_transfers})")

    def snapshot(self) -> int:
        """Transfers since the previous snapshot (per-epoch delta)."""
        with self._lock:
            delta = self.transfers - self._last_snapshot
            self._last_snapshot = self.transfers
            return delta

    # -- arming ------------------------------------------------------
    def __enter__(self):
        if self._saved is not None:
            raise RuntimeError("HostTransferGuard is not reentrant")
        saved = {
            "device_get": jax.device_get,
            "asarray": np.asarray,
            "array": np.array,
        }

        # fully generic signatures: the originals accept their first
        # argument by keyword too (np.array(object=...), np.asarray(a=...),
        # jax.device_get(x=...)), and a wrapper that renames it would
        # crash any caller using the documented keyword form
        def device_get(*args, **kwargs):
            self._note(args[0] if args else kwargs.get("x"))
            return saved["device_get"](*args, **kwargs)

        def asarray(*args, **kwargs):
            self._note(args[0] if args else kwargs.get("a"))
            return saved["asarray"](*args, **kwargs)

        def array(*args, **kwargs):
            self._note(args[0] if args else kwargs.get("object"))
            return saved["array"](*args, **kwargs)

        jax.device_get = device_get
        np.asarray = asarray
        np.array = array
        self._saved = saved
        return self

    def __exit__(self, exc_type, exc, tb):
        saved, self._saved = self._saved, None
        if saved is not None:
            jax.device_get = saved["device_get"]
            np.asarray = saved["asarray"]
            np.array = saved["array"]
        return False


class _GuardedLock:
    """Proxy around one lock that reports waits and ordering to its
    :class:`LockOrderGuard`.  Drop-in for ``threading.Lock`` /
    ``RLock``: ``with``, ``acquire``/``release``, and anything else
    forwards to the wrapped lock."""

    def __init__(self, guard: "LockOrderGuard", inner, name: str):
        self._guard = guard
        self._inner = inner
        self._name = name

    def acquire(self, blocking=True, timeout=-1):
        clock = self._guard.clock
        t0 = clock()
        got = self._inner.acquire(blocking, timeout)
        waited = max(0.0, clock() - t0)
        if got:
            self._guard._note_acquired(self._name, waited)
        elif waited:
            self._guard._note_wait(waited)
        return got

    def release(self):
        self._inner.release()
        self._guard._note_released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


class LockOrderGuard:
    """Runtime lock-order/contention accounting for the control plane.

    Racelint's ``lock-order-cycle`` proves what it can from source;
    this guard watches the locks that actually run.  :meth:`wrap`
    replaces a lock with a :class:`_GuardedLock` proxy (and
    :meth:`arm` does so in place on an object attribute); every
    acquire then

      * accumulates the wall time the acquiring thread waited
        (``lock_contention_sec`` — uncontended acquires cost
        microseconds and contribute ~0);
      * records the per-thread held-set and, for each (held, new)
        pair, the first-seen acquisition direction; observing the
        *reverse* direction later is a counted
        ``lock_order_inversion`` — a latent ABBA deadlock that simply
        has not fired yet.

    Reentrant re-acquire of a lock already held by the thread records
    no pair (RLocks do that by design).  ``clock`` is injectable for
    tests.  :meth:`snapshot` returns per-epoch deltas for the metrics
    jsonl; :meth:`stats` the cumulative totals for the status
    endpoint.  Near-zero cost: two clock reads and a couple of dict
    ops per acquire, on locks that guard microsecond critical
    sections.
    """

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self.contention_sec = 0.0
        self.inversions = 0
        self._last_contention = 0.0
        self._last_inversions = 0
        self._names = []                  # wrap() order, for stats()
        self._pairs = {}                  # frozenset({a,b}) -> (a, b)
        self._meta = threading.Lock()     # guards the counters above
        self._held = threading.local()    # per-thread stack of names

    # -- wrapping -----------------------------------------------------
    def wrap(self, lock, name: str):
        """Wrap ``lock`` in a reporting proxy registered as ``name``."""
        if isinstance(lock, _GuardedLock):
            return lock
        with self._meta:
            if name not in self._names:
                self._names.append(name)
        return _GuardedLock(self, lock, name)

    def arm(self, obj, attr: str = "_lock", name=None) -> bool:
        """Replace ``obj.attr`` with its wrapped proxy in place.
        Returns False (and does nothing) when the object is None, the
        attribute is missing, or it is already wrapped — so the
        learner can arm every subsystem it *might* have without
        caring which are enabled this run."""
        if obj is None or not hasattr(obj, attr):
            return False
        lock = getattr(obj, attr)
        if lock is None or isinstance(lock, _GuardedLock):
            return False
        if name is None:
            name = f"{type(obj).__name__}.{attr}"
        setattr(obj, attr, self.wrap(lock, name))
        return True

    # -- proxy callbacks ----------------------------------------------
    def _stack(self):
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def _note_acquired(self, name: str, waited: float):
        stack = self._stack()
        reentrant = name in stack
        if not reentrant and stack:
            with self._meta:
                self.contention_sec += waited
                for held in stack:
                    pair = frozenset((held, name))
                    first = self._pairs.get(pair)
                    if first is None:
                        self._pairs[pair] = (held, name)
                    elif first != (held, name):
                        self.inversions += 1
        elif waited:
            self._note_wait(waited)
        stack.append(name)

    def _note_released(self, name: str):
        stack = self._stack()
        # pop the most recent occurrence: releases may be unnested
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                break

    def _note_wait(self, waited: float):
        with self._meta:
            self.contention_sec += waited

    # -- reporting ----------------------------------------------------
    def snapshot(self) -> dict:
        """Per-epoch deltas since the previous snapshot, keyed exactly
        as the metrics jsonl expects."""
        with self._meta:
            contention = self.contention_sec - self._last_contention
            inversions = self.inversions - self._last_inversions
            self._last_contention = self.contention_sec
            self._last_inversions = self.inversions
        return {"lock_contention_sec": round(contention, 6),
                "lock_order_inversions": inversions}

    def stats(self) -> dict:
        """Cumulative totals for the status endpoint."""
        with self._meta:
            return {"locks_guarded": len(self._names),
                    "lock_contention_sec": round(self.contention_sec, 6),
                    "lock_order_inversions": self.inversions}


class ResourceError(RuntimeError):
    pass


class ResourceLedger:
    """Per-epoch resource-population sampling (the leak soak meter).

    leaklint proves from source that every acquisition has an owner
    who releases it; this ledger measures the population that actually
    runs — because handles escape into containers, C extensions open
    fds Python never sees, and a suppression's "process-lifetime"
    claim can simply be wrong.  Each :meth:`snapshot` (the learner
    calls it once per epoch, next to the other guards) samples:

      * ``fd_count`` — entries in ``/proc/self/fd``;
      * ``thread_count`` — ``len(threading.enumerate())``;
      * ``shm_segments`` — ``psm_*`` segments in ``/dev/shm`` (the
        default names ``multiprocessing.shared_memory`` gives the
        rings and boards);
      * ``resource_growth`` — fds above the post-warmup baseline.

    The first ``warmup_epochs`` snapshots are bring-up (workers
    dialing in, rings mapping) and set the baseline at the end of the
    window; after that, growth is measured against the baseline so a
    slow accretion shows up as a rising ``resource_growth`` curve on
    the same plots as the loss.  ``max_fd_growth > 0`` makes the
    budget hard: a post-warmup snapshot whose growth exceeds it
    raises :class:`ResourceError` (default 0 = count and report,
    never raise — sampling must not be able to kill a healthy run).

    Sampling is three directory listings per EPOCH — noise next to a
    single update step.  On hosts without ``/proc`` the fd/socket
    samples degrade to 0 and the ledger still reports (the keys stay
    present so the metrics schema is stable).  The proc/shm paths are
    injectable so leak tests can point the ledger at a fixture tree.
    """

    def __init__(self, max_fd_growth: int = 0, warmup_epochs: int = 2,
                 proc_fd_dir: str = "/proc/self/fd",
                 shm_dir: str = "/dev/shm"):
        self.max_fd_growth = max(0, int(max_fd_growth or 0))
        self.warmup_epochs = max(0, int(warmup_epochs))
        self.proc_fd_dir = proc_fd_dir
        self.shm_dir = shm_dir
        self.epochs = 0
        self.baseline = None          # (fd, threads) post-warmup
        self.peak_growth = 0
        self.last = None              # most recent sample dict
        self._lock = threading.Lock()

    # -- sampling ----------------------------------------------------
    def sample(self) -> dict:
        """One population sample (no epoch bookkeeping)."""
        import os

        try:
            fds = os.listdir(self.proc_fd_dir)
        except OSError:
            fds = []
        sockets = 0
        for fd in fds:
            try:
                target = os.readlink(
                    os.path.join(self.proc_fd_dir, fd))
            except OSError:
                continue
            if target.startswith("socket:"):
                sockets += 1
        try:
            shm = sum(1 for name in os.listdir(self.shm_dir)
                      if name.startswith("psm_"))
        except OSError:
            shm = 0
        return {"fd_count": len(fds),
                "thread_count": len(threading.enumerate()),
                "shm_segments": shm,
                "socket_count": sockets}

    def snapshot(self) -> dict:
        """One epoch tick: sample, update the baseline/growth
        bookkeeping, and return the metrics-jsonl keys.  Raises
        :class:`ResourceError` only when ``max_fd_growth > 0`` and a
        post-warmup sample exceeds the budget."""
        sampled = self.sample()
        with self._lock:
            self.epochs += 1
            self.last = sampled
            if self.baseline is None \
                    and self.epochs > self.warmup_epochs:
                self.baseline = (sampled["fd_count"],
                                 sampled["thread_count"])
            growth = 0
            if self.baseline is not None:
                growth = max(0, sampled["fd_count"] - self.baseline[0])
                self.peak_growth = max(self.peak_growth, growth)
            budget = self.max_fd_growth
        record = {"fd_count": sampled["fd_count"],
                  "thread_count": sampled["thread_count"],
                  "shm_segments": sampled["shm_segments"],
                  "resource_growth": growth}
        if budget and growth > budget:
            raise ResourceError(
                f"fd count grew by {growth} over the post-warmup "
                f"baseline (> max_fd_growth={budget}): "
                f"{sampled['fd_count']} fds "
                f"({sampled['socket_count']} sockets), "
                f"{sampled['shm_segments']} shm segments — a resource "
                f"leak leaklint could not see; check the suppressions "
                f"and container-held handles")
        return record

    # -- reporting ----------------------------------------------------
    def stats(self) -> dict:
        """Cumulative totals for the status endpoint."""
        with self._lock:
            last = dict(self.last) if self.last else {}
            return {"fd_count": last.get("fd_count", 0),
                    "thread_count": last.get("thread_count", 0),
                    "shm_segments": last.get("shm_segments", 0),
                    "socket_count": last.get("socket_count", 0),
                    "baseline_fd": None if self.baseline is None
                    else self.baseline[0],
                    "peak_fd_growth": self.peak_growth,
                    "max_fd_growth": self.max_fd_growth,
                    "epochs_sampled": self.epochs}

    def delta_line(self, since: dict) -> str:
        """One-line human delta vs an earlier :meth:`sample` (bench
        rounds log this so leak regressions show in CI artifacts)."""
        now = self.sample()

        def arrow(key):
            a, b = since.get(key, 0), now.get(key, 0)
            sign = f"{b - a:+d}" if b != a else "±0"
            return f"{a}->{b} ({sign})"

        return (f"resources: fd {arrow('fd_count')}, "
                f"threads {arrow('thread_count')}, "
                f"shm {arrow('shm_segments')}")
