"""Fixture: PartitionSpec entries / collective axis names that the
constructed mesh never declares."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "tp")


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), AXES)


def batch_sharding(mesh):
    # "data" is not an axis of the mesh built above
    return NamedSharding(mesh, P("data"))


def loss_mean(x):
    # "model" is not a mesh axis either
    return jax.lax.pmean(x, "model")
