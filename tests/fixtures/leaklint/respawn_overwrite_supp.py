"""Suppressed: an intentional leak-on-respawn, explained."""

import socket


class Frontend:
    def __init__(self):
        self._listener = None

    def respawn(self):
        self._listener = socket.create_server(("", 9999))  # jaxlint: disable=respawn-overwrite -- the old listener is owned and closed by the accept thread it was handed to
