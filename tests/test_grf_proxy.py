"""GRF-scale workload (capability config #5): (72, 96, 16) SMM-sized
observations, long episodes, recurrent net with burn-in replay.

The drill env generates GRF-shaped traffic (handyrl_tpu/envs/grf_proxy
docstring); these tests pin the full training path at that geometry —
generation -> wire episodes -> device replay ring (uint8 storage) ->
burn-in batch -> DRC update step."""

import random

import numpy as np
import pytest

CFG = {
    "turn_based_training": False,   # simultaneous: seat-mode training
    "observation": False,
    "gamma": 0.993,                 # long-horizon discount
    "forward_steps": 8,
    "burn_in_steps": 4,
    "compress_steps": 8,
    "entropy_regularization": 0.1,
    "entropy_regularization_decay": 0.1,
    "lambda": 0.7,
    "policy_target": "UPGO",
    "value_target": "TD",
    "transfer_dtype": "uint8",
    "compute_dtype": "bfloat16",
}


def _episodes(count, max_steps=96, seed=5):
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.generation import Generator
    from handyrl_tpu.models import RandomModel, TPUModel

    random.seed(seed)
    env = make_env({"env": "GRFProxy", "max_steps": max_steps})
    env.reset()
    model = TPUModel(env.net())
    obs0 = env.observation(0)
    assert obs0.shape == (72, 96, 16)
    assert np.array_equal(obs0, obs0.astype(np.uint8))  # binary planes
    model.init_params(obs0, seed=seed)
    rollout = RandomModel(model, obs0)
    gen = Generator(env, CFG)
    players = env.players()
    job = {"player": players, "model_id": {p: 1 for p in players}}
    eps = []
    while len(eps) < count:
        ep = gen.generate({p: rollout for p in players}, job)
        if ep is not None:
            eps.append(ep)
    return env, model, eps


def test_net_carries_state_and_update_steps(tmp_path):
    """One fused device-replay update at the GRF geometry: ring stores
    uint8, gather dequantizes, the DRC hidden threads burn-in."""
    import jax
    import jax.numpy as jnp

    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer
    from handyrl_tpu.staging import DeviceReplay, make_replay_update_step

    env, model, eps = _episodes(3)
    replay = DeviceReplay(CFG, capacity=8, max_bytes=2 << 30)
    replay.offer(eps)
    replay.ingest()
    assert replay.size == 3
    assert replay.t_max >= max(e["steps"] for e in eps)

    optimizer = make_optimizer(1e-3)
    params = jax.tree.map(jnp.asarray, model.params)
    opt_state = optimizer.init(params)
    update = make_replay_update_step(
        replay, model, LossConfig.from_config(CFG), optimizer,
        "bfloat16", batch_size=4, seed=0)
    state = replay.device_state(0)
    params, opt_state, metrics, state = update(
        params, opt_state, replay.buffers, state)
    assert np.isfinite(float(metrics["total"]))
    assert int(state[2]) == 1  # device-side step counter advanced


def test_ring_budget_caps_at_grf_byte_cost():
    """At ~MB-scale episodes the byte budget must bite: a small
    device_replay_mb cap shrinks the ring instead of OOMing."""
    from handyrl_tpu.staging import DeviceReplay

    _, _, eps = _episodes(2, max_steps=64)
    replay = DeviceReplay(CFG, capacity=4096, max_bytes=64 << 20)
    replay.offer(eps)
    replay.ingest()
    # (72*96*16 uint8 + narrow lane-padded channels) * t_max ~= 14 MB
    # per slot -> 64 MiB holds only a handful of slots
    assert replay.capacity <= 8
    assert replay.size == 2
    batch = replay.sample(2)
    obs = batch["observation"]
    leaf = obs if not isinstance(obs, dict) else list(obs.values())[0]
    assert leaf.shape[-3:] == (72, 96, 16)


def test_scripted_chaser_beats_random():
    from handyrl_tpu.environment import make_env

    random.seed(3)
    env = make_env({"env": "GRFProxy", "max_steps": 400})
    wins = 0
    for _ in range(5):
        env.reset()
        while not env.terminal():
            env.step({0: env.rule_based_action(0),
                      1: random.choice(env.legal_actions(1))})
        wins += env.outcome()[0] > 0
    assert wins >= 4  # the chaser overwhelms a random walker