"""Learner-side fleet health: per-peer last-seen, rates, staleness.

Heartbeats are piggybacked on the existing request/reply control
plane: EVERY message a gather sends (job request, model fetch, episode
upload, explicit ``beat``) proves it alive, so the registry just
timestamps each peer on each message.  A gather that has had no reason
to talk for ``heartbeat_interval`` seconds sends an explicit
``("beat", stats)`` — meaning a wedged gather is indistinguishable
from silence, which is exactly the property ``sweep`` exploits: a peer
silent past ``heartbeat_timeout`` is STALE (one counted heartbeat
miss) and gets reported to the supervisor for eviction.

The registry is bookkeeping only — it never touches sockets or
processes.  The clock is injectable so expiry tests are exact.
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional


class _Peer:
    __slots__ = ("first_seen", "last_seen", "episodes", "beats",
                 "stale", "stats")

    def __init__(self, now: float):
        self.first_seen = now
        self.last_seen = now
        self.episodes = 0
        self.beats = 0
        self.stale = False
        self.stats: Dict[str, Any] = {}


class FleetRegistry:
    """Tracks every control-plane peer the learner has heard from.

    Peers are keyed by connection object (identity is the session:
    a respawned gather arrives on a NEW connection and is a new peer;
    its predecessor goes stale and is eventually forgotten).
    """

    # a peer stale for this many timeouts is forgotten entirely, so
    # unbounded worker churn cannot grow the registry forever
    FORGET_AFTER_TIMEOUTS = 3

    def __init__(self, heartbeat_timeout: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.clock = clock
        self.heartbeat_misses = 0  # total stale transitions, cumulative
        self.peak_size = 0
        self._peers: Dict[Any, _Peer] = {}
        self._drops: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- intake ------------------------------------------------------
    def observe(self, peer: Any, verb: Optional[str] = None,
                payload: Any = None, now: Optional[float] = None):
        """Timestamp a peer on any control-plane message; episode
        uploads also feed the rate estimate, explicit beats merge the
        gather's self-reported stats."""
        if now is None:
            now = self.clock()
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None:
                rec = self._peers[peer] = _Peer(now)
            rec.last_seen = now
            rec.stale = False  # a stale peer that speaks has recovered
            if verb == "episode":
                rec.episodes += len(payload) if isinstance(payload, list) \
                    else 1
            elif verb == "beat" and isinstance(payload, dict):
                rec.beats += 1
                rec.stats = dict(payload)

    def pardon(self, now: Optional[float] = None):
        """The LISTENER stalled (e.g. the learner spent seconds inside
        an epoch boundary): silence during that window says nothing
        about the peers, so refresh everyone instead of letting the
        next sweep mass-evict a healthy fleet."""
        if now is None:
            now = self.clock()
        with self._lock:
            for rec in self._peers.values():
                rec.last_seen = now

    def record_drops(self, drops: Dict[str, int]):
        """Latest communicator drop counters (QueueCommunicator
        ``drop_stats``): sends to dead peers and disconnect events."""
        with self._lock:
            self._drops = dict(drops)

    def forget(self, peer: Any):
        with self._lock:
            self._peers.pop(peer, None)

    def peers(self) -> List[Any]:
        with self._lock:
            return list(self._peers)

    # -- queries -----------------------------------------------------
    def _live_count(self, now: float) -> int:
        # called with the lock held
        return sum(1 for p in self._peers.values()
                   if now - p.last_seen <= self.heartbeat_timeout)

    def fleet_size(self, now: Optional[float] = None) -> int:
        if now is None:
            now = self.clock()
        with self._lock:
            return self._live_count(now)

    def sweep(self, now: Optional[float] = None) -> List[Any]:
        """Expire silent peers: returns the NEWLY stale ones (each a
        counted heartbeat miss) so the caller can evict their children;
        peers stale for several timeouts are forgotten entirely."""
        if now is None:
            now = self.clock()
        newly_stale = []
        with self._lock:
            forget_after = self.heartbeat_timeout \
                * self.FORGET_AFTER_TIMEOUTS
            for peer, rec in list(self._peers.items()):
                silent = now - rec.last_seen
                if silent > forget_after:
                    del self._peers[peer]
                elif silent > self.heartbeat_timeout and not rec.stale:
                    rec.stale = True
                    self.heartbeat_misses += 1
                    newly_stale.append(peer)
            # peak updates here, AFTER expiry/forget, not on observe:
            # during a respawn a dead-but-recent peer and its
            # replacement briefly coexist, and a peak latched in that
            # overlap would mislabel the healthy fleet as degraded
            # forever after
            self.peak_size = max(self.peak_size, self._live_count(now))
        return newly_stale

    def _eps_locked(self, now: float) -> float:
        # called with the lock held: one definition of the rate for
        # both the query and the snapshot
        total = 0.0
        for rec in self._peers.values():
            span = max(1e-6, now - rec.first_seen)
            total += rec.episodes / span
        return total

    def episodes_per_sec(self, now: Optional[float] = None) -> float:
        if now is None:
            now = self.clock()
        with self._lock:
            return self._eps_locked(now)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Per-epoch metrics record contribution (metrics.jsonl)."""
        if now is None:
            now = self.clock()
        with self._lock:
            fleet = self._live_count(now)
            # unknown_verbs rides the same drop_stats() dict but is a
            # protocol-skew signal, not a connection drop: surface it
            # as its own metric instead of folding it into conn_drops
            drops = sum(v for k, v in self._drops.items()
                        if k != "unknown_verbs")
            unknown = self._drops.get("unknown_verbs", 0)
            eps = self._eps_locked(now)
            # gather self-reports (best effort: carried by explicit
            # beats, so a gather busy enough to never beat reports 0)
            workers = sum(
                rec.stats.get("workers", 0)
                for rec in self._peers.values()
                if now - rec.last_seen <= self.heartbeat_timeout)
        return {
            "fleet_size": fleet,
            "fleet_workers": workers,
            "heartbeat_misses": self.heartbeat_misses,
            "conn_drops": drops,
            "unknown_verbs": unknown,
            "fleet_eps_per_sec": round(eps, 3),
        }
