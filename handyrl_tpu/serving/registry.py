"""Service registry + replica announcer: the pool's bulletin board.

The horizontal half of the serving tier (docs/serving.md "Pool
routing"): N independent learners each run their own SLO-bound
:class:`~.frontend.ServingFrontend`; to present them as ONE endpoint
the router needs a live map of who exists, what they can serve, and
how loaded they are.  This module generalizes two proven patterns:

  * the shm plane's **heartbeat/generation bulletin** (``ShmBoard``:
    a beat cadence plus an incarnation counter, so "silent" and
    "restarted" are distinguishable states) becomes a NETWORK
    bulletin — each replica ships a small advert dict over the
    existing framed-TCP protocol on the router-assigned cadence;
  * the control plane's **FleetRegistry sweep/expiry** (silence past
    ``heartbeat_timeout`` is a counted miss and an eviction) becomes
    the pool's membership rule — a silent replica is EVICTED from
    routing, never routed to and left to black-hole requests.

Advert wire format (one dict per ``register``/``beat`` payload; every
field optional but ``name``/``host``/``port`` — unknown fields ride
along untouched, so replicas can grow the advert without a registry
change):

  ==============  ====================================================
  field           meaning
  ==============  ====================================================
  ``name``        stable replica identity (generation is tracked per
                  name across evictions and re-registrations)
  ``host, port``  the replica frontend's dialable endpoint
  ``capacity``    the replica's ``serving.max_inflight``
  ``inflight``    currently-admitted requests (replica-reported)
  ``p99_ms``      the replica's sliding-window p99 (load signal)
  ``slo_breached``whether the replica is currently shedding on SLO
  ``epochs``      committed snapshot epochs this replica can serve —
                  the pin-routing advert (any replica can serve any
                  committed epoch via its ``model_resolver`` + LRU)
  ==============  ====================================================

:class:`ServiceRegistry` is bookkeeping only — it never touches
sockets or threads, and the clock is injectable so expiry/eviction
tests are exact (the FleetRegistry discipline).
:class:`ReplicaAnnouncer` is the replica-side thread that dials the
router and keeps the advert fresh; it re-registers (bumping the
registry's per-name generation) whenever the router forgot it.
"""

import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..connection import DEFAULT_MAX_FRAME_BYTES, open_socket_connection


class _Replica:
    __slots__ = ("advert", "first_seen", "last_seen", "generation",
                 "draining", "suspect", "inflight", "beats")

    def __init__(self, advert: Dict[str, Any], now: float,
                 generation: int):
        self.advert = dict(advert)
        self.first_seen = now
        self.last_seen = now
        self.generation = generation
        self.draining = False   # graceful goodbye: no new picks, ever
        self.suspect = False    # FailureWindow trip: cleared by a beat
        self.inflight = 0       # router-tracked in-flight forwards
        self.beats = 0


class ServiceRegistry:
    """Who is in the pool, what they advertise, who gets the request.

    Thread contract: every method takes the one internal lock; callers
    (the router's accept loop, its per-connection handlers, the status
    endpoint) never hold it across a network call — ``pick`` returns a
    name, and forwarding happens outside.
    """

    def __init__(self, heartbeat_timeout: float = 6.0,
                 clock: Callable[[], float] = time.monotonic):
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.clock = clock
        self._replicas: Dict[str, _Replica] = {}
        # generation memory survives eviction: a respawned replica
        # re-registering under its stable name gets a BUMPED number,
        # so "rejoined after a death" is observable (the ShmBoard /
        # frontend incarnation discipline, pool-wide)
        self._generations: Dict[str, int] = {}
        self.evictions = 0       # cumulative sweep expiries
        self.registrations = 0   # cumulative register calls
        self._lock = threading.Lock()

    # -- membership ---------------------------------------------------
    def register(self, name: str, advert: Dict[str, Any],
                 now: Optional[float] = None) -> int:
        """(Re-)register a replica; returns its assigned generation
        (0 on first sight of this name, +1 per re-registration)."""
        if now is None:
            now = self.clock()
        with self._lock:
            gen = self._generations.get(name)
            gen = 0 if gen is None else gen + 1
            self._generations[name] = gen
            self._replicas[name] = _Replica(advert, now, gen)
            self.registrations += 1
            return gen

    def beat(self, name: str, advert: Dict[str, Any],
             now: Optional[float] = None) -> bool:
        """Refresh a replica's advert; False when the name is unknown
        (evicted or never registered) — the sender must re-register.
        A suspect replica that beats has recovered (the FleetRegistry
        stale-peer-that-speaks rule); a DRAINING one stays draining —
        the goodbye was explicit, only a re-register undoes it."""
        if now is None:
            now = self.clock()
        with self._lock:
            rec = self._replicas.get(name)
            if rec is None:
                return False
            rec.last_seen = now
            rec.beats += 1
            rec.suspect = False
            rec.advert = dict(advert)
            return True

    def drain(self, name: str, suspect: bool = False):
        """Exclude a replica from new picks.  ``suspect=True`` is the
        router's FailureWindow verdict (recoverable: the next beat
        clears it); default is the replica's own graceful goodbye —
        in-flight forwards complete, nothing new routes there."""
        with self._lock:
            rec = self._replicas.get(name)
            if rec is None:
                return
            if suspect:
                rec.suspect = True
            else:
                rec.draining = True

    def sweep(self, now: Optional[float] = None) -> List[str]:
        """Evict replicas silent past ``heartbeat_timeout``; returns
        the newly evicted names.  Eviction is full removal — a dead
        host must not linger as a routable entry — but its generation
        memory survives for the respawn bump."""
        if now is None:
            now = self.clock()
        evicted = []
        with self._lock:
            for name, rec in list(self._replicas.items()):
                if now - rec.last_seen > self.heartbeat_timeout:
                    del self._replicas[name]
                    self.evictions += 1
                    evicted.append(name)
        return evicted

    def note_inflight(self, name: str, delta: int):
        """Router-side in-flight accounting per replica (the load
        signal between heartbeats — adverts lag by up to a cadence)."""
        with self._lock:
            rec = self._replicas.get(name)
            if rec is not None:
                rec.inflight = max(0, rec.inflight + delta)

    # -- routing ------------------------------------------------------
    @staticmethod
    def _advertises(rec: _Replica, pin: int) -> bool:
        epochs = rec.advert.get("epochs") or ()
        try:
            return int(pin) in {int(e) for e in epochs}
        except (TypeError, ValueError):
            return False

    def _routable(self, now: float) -> List[Tuple[str, _Replica]]:
        # called with the lock held: live, not draining, not suspect
        return [(name, rec) for name, rec in self._replicas.items()
                if now - rec.last_seen <= self.heartbeat_timeout
                and not rec.draining and not rec.suspect]

    def pick(self, seat: Any = None, pin: Optional[int] = None,
             exclude: Optional[set] = None,
             policy: str = "least_loaded",
             now: Optional[float] = None) -> Optional[str]:
        """One routing decision; None when nothing qualifies.

        * ``pin`` restricts candidates to replicas ADVERTISING that
          snapshot epoch — a pin re-routes on eviction instead of
          dying, because any replica that committed the epoch serves
          it through its resolver;
        * ``policy='hash'`` with a ``seat`` uses rendezvous hashing
          (highest-random-weight), so a seat keeps its replica across
          UNRELATED pool changes and only seats of a removed replica
          remap;
        * least-loaded scores ``(inflight + 1) * max(p99_ms, 1)`` —
          both the router's own in-flight view and the advertised
          load/latency spread traffic away from a hot replica.
        """
        if now is None:
            now = self.clock()
        with self._lock:
            cands = self._routable(now)
            if exclude:
                cands = [(n, r) for n, r in cands if n not in exclude]
            if pin is not None:
                cands = [(n, r) for n, r in cands
                         if self._advertises(r, pin)]
            if not cands:
                return None
            if policy == "hash" and seat is not None:
                def weight(item):
                    name = item[0]
                    digest = hashlib.md5(
                        f"{name}|{seat}".encode()).hexdigest()
                    return (int(digest, 16), name)
                return max(cands, key=weight)[0]

            def score(item):
                name, rec = item
                inflight = rec.inflight + int(
                    rec.advert.get("inflight", 0) or 0)
                p99 = float(rec.advert.get("p99_ms", 0.0) or 0.0)
                return ((inflight + 1) * max(p99, 1.0), name)
            return min(cands, key=score)[0]

    def endpoint(self, name: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            rec = self._replicas.get(name)
            if rec is None:
                return None
            host = rec.advert.get("host") or "127.0.0.1"
            try:
                return str(host), int(rec.advert.get("port", 0))
            except (TypeError, ValueError):
                return None

    # -- views --------------------------------------------------------
    def pool_size(self, now: Optional[float] = None) -> int:
        if now is None:
            now = self.clock()
        with self._lock:
            return len(self._routable(now))

    def generation(self, name: str) -> Optional[int]:
        with self._lock:
            rec = self._replicas.get(name)
            return None if rec is None else rec.generation

    def all_breached(self, now: Optional[float] = None) -> bool:
        """True when every routable replica advertises an SLO breach —
        the whole-pool signal behind the router's typed escalation
        (False on an empty pool: that is ``pool_down``, not SLO)."""
        if now is None:
            now = self.clock()
        with self._lock:
            cands = self._routable(now)
            return bool(cands) and all(
                rec.advert.get("slo_breached") for _, rec in cands)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Status-endpoint / healthz view: constant-time bookkeeping
        reads only — NO replica is dialed to answer this."""
        if now is None:
            now = self.clock()
        with self._lock:
            replicas = {}
            for name, rec in self._replicas.items():
                replicas[name] = {
                    "generation": rec.generation,
                    "age_sec": round(now - rec.last_seen, 3),
                    "draining": rec.draining,
                    "suspect": rec.suspect,
                    "inflight": rec.inflight,
                    "beats": rec.beats,
                    "advert": dict(rec.advert),
                }
            return {
                "pool_size": len(self._routable(now)),
                "heartbeat_timeout": self.heartbeat_timeout,
                "evictions": self.evictions,
                "registrations": self.registrations,
                "replicas": replicas,
            }


class ReplicaAnnouncer:
    """The replica-side heartbeat thread: dials the router, registers,
    then beats the advert on the router-assigned cadence.

    ``advert_fn`` is called on the announcer thread per message and
    must be cheap and thread-safe (the frontend's ``advert()`` reads
    under its own lock).  A dead router (or an eviction: the router
    answers a beat with an error) tears the connection down and the
    loop re-registers behind ``retry_interval`` — each re-register
    bumps the registry's per-name generation, which is exactly how a
    respawn is observed pool-wide.  ``kill()`` is the chaos hook: the
    announcer goes silent WITHOUT a goodbye, the way a crashed host
    does, so the sweep eviction path gets exercised; ``close()`` sends
    the graceful ``drain`` verb so in-flight traffic finishes while
    nothing new routes here.
    """

    def __init__(self, address: str, port: int, name: str,
                 advert_fn: Callable[[], Dict[str, Any]],
                 interval: float = 2.0, retry_interval: float = 1.0,
                 reply_timeout: float = 3.0, max_frame_bytes: int = 0):
        self.address = address
        self.port = int(port)
        self.name = name
        self.advert_fn = advert_fn
        self.interval = float(interval)
        self.retry_interval = float(retry_interval)
        self.reply_timeout = float(reply_timeout)
        self.max_frame_bytes = int(max_frame_bytes
                                   or DEFAULT_MAX_FRAME_BYTES)
        self.generation: Optional[int] = None  # router-assigned
        self.registrations = 0
        self._conn = None
        # guards the _conn swap: _sever runs on BOTH the announcer
        # thread (loop errors) and the owner (close/kill), and the two
        # must not interleave the read-modify-write
        self._conn_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _payload(self) -> Dict[str, Any]:
        return {"name": self.name, **(self.advert_fn() or {})}

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-announce")
        self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _sever(self):
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _run(self):
        while not self._stop.is_set():
            try:
                if self._conn is None:
                    self._conn = open_socket_connection(
                        self.address, self.port,
                        max_frame_bytes=self.max_frame_bytes)
                    # bounded round trips: the deadline turns a dead
                    # router into a timeout, never a parked announcer
                    self._conn.sock.settimeout(self.reply_timeout)
                    self._conn.send(("register", self._payload()))
                    ack = self._conn.recv()
                    if not (isinstance(ack, dict)
                            and ack.get("status") == "ok"):
                        raise ConnectionError(
                            f"register rejected: {ack!r}")
                    # the router owns the cadence: one beat rate for
                    # the whole pool, assigned in the register ack
                    self.interval = float(
                        ack.get("heartbeat_interval", self.interval))
                    self.generation = ack.get("generation")
                    self.registrations += 1
                if self._stop.wait(self.interval):
                    break
                self._conn.send(("beat", self._payload()))
                ack = self._conn.recv()
                if not (isinstance(ack, dict)
                        and ack.get("status") == "ok"):
                    # evicted while we thought we were registered (a
                    # long GC pause, a router restart): re-register
                    raise ConnectionError(f"beat rejected: {ack!r}")
            except Exception:
                self._sever()
                if self._stop.wait(self.retry_interval):
                    break
        self._sever()

    def drain(self):
        """Best-effort graceful goodbye (fire-and-forget, like the
        battle plane's ``quit``): the router stops picking this
        replica while its in-flight forwards complete."""
        conn = self._conn
        try:
            if conn is None:
                conn = open_socket_connection(
                    self.address, self.port,
                    max_frame_bytes=self.max_frame_bytes)
            conn.send(("drain", {"name": self.name}))
        except Exception:
            pass  # a gone router needs no goodbye
        finally:
            if conn is not None and conn is not self._conn:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self, drain: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if drain:
            self.drain()
        self._sever()

    def kill(self):
        """Chaos: go silent with no goodbye — the router must learn of
        the death from the missing heartbeats (sweep eviction), not
        from a courtesy the crashed host never sends."""
        self._stop.set()
        self._sever()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def respawn(self):
        """Relaunch after a kill: the fresh loop re-registers under
        the same name, so the registry's generation bump is the
        pool-visible proof of the respawn."""
        if self._thread is not None and self._thread.is_alive():
            return
        self.start()


__all__ = ["ServiceRegistry", "ReplicaAnnouncer"]
