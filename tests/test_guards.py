"""Runtime guard suite: RetraceGuard compile accounting (cache-size and
signature-fallback paths, budget enforcement), HostTransferGuard
transfer counting (device hits, host passes, budget, restoration),
ShardingContractGuard resharding accounting (contract capture, copy
counting, budget, snapshot deltas), and NumericsGuard dtype-contract +
nonfinite-step accounting (latch, break/upcast split, off-switch,
budget) — plus ResourceLedger population sampling (stable metric keys,
leak deltas for sockets and shm rings, the hard fd-growth budget, and
proc-less degradation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handyrl_tpu.analysis.guards import (
    HostTransferError,
    HostTransferGuard,
    NumericsError,
    NumericsGuard,
    ResourceError,
    ResourceLedger,
    RetraceError,
    RetraceGuard,
    ShardingContractError,
    ShardingContractGuard,
)


def test_retrace_guard_stable_shapes_compile_once():
    guard = RetraceGuard(name="step")
    step = guard.wrap(jax.jit(lambda x: x * 2))
    for _ in range(5):
        step(jnp.ones(4))
    assert guard.compiles == 1
    assert guard.calls == 5


def test_retrace_guard_counts_shape_churn():
    guard = RetraceGuard(name="step")
    step = guard.wrap(jax.jit(lambda x: x * 2))
    step(jnp.ones(4))
    step(jnp.ones(8))
    step(jnp.ones((2, 4)))
    assert guard.compiles == 3


def test_retrace_guard_budget_raises_at_the_offending_call():
    guard = RetraceGuard(max_compiles=1, name="step")
    step = guard.wrap(jax.jit(lambda x: x + 1))
    step(jnp.ones(4))
    with pytest.raises(RetraceError, match="update_step|step"):
        step(jnp.ones(5))


def test_retrace_guard_counts_any_callable():
    # signature counting needs no jit machinery: plain callables work
    guard = RetraceGuard(name="plain")
    fn = guard.wrap(lambda x, flag=False: x)
    fn(np.ones(3))
    fn(np.ones(3), flag=True)    # same shapes, new kwarg treedef
    fn(np.ones((3, 1)))
    assert guard.compiles == 3
    fn(np.ones(3))
    assert guard.compiles == 3   # seen before: no new "compile"


def test_retrace_guard_allowance_exempts_designed_recompiles():
    # the learner widens the budget by the replay ring's growth count:
    # a designed T_max re-layout must not trip the assertion
    guard = RetraceGuard(max_compiles=1, name="step")
    step = guard.wrap(jax.jit(lambda x: x * 2))
    step(jnp.ones(4))
    guard.allowance = 1  # one ring growth happened
    step(jnp.ones(8))    # the post-growth recompile: allowed
    assert guard.compiles == 2
    with pytest.raises(RetraceError):
        step(jnp.ones(16))  # a THIRD shape is real churn again


def test_retrace_guard_sampling_still_catches_persistent_churn():
    # after the warmup window the signature is only sampled, but a
    # persistent shape change is caught within SAMPLE_EVERY calls
    from handyrl_tpu.analysis.guards import _GuardedJit

    guard = RetraceGuard(name="step")
    step = guard.wrap(jax.jit(lambda x: x + 1))
    for _ in range(_GuardedJit.WARM_CALLS + 10):
        step(jnp.ones(4))
    assert guard.compiles == 1
    for _ in range(_GuardedJit.SAMPLE_EVERY):
        step(jnp.ones(8))  # churn begins past the warmup window
    assert guard.compiles == 2


def test_retrace_guard_sums_over_wrapped_fns():
    guard = RetraceGuard(name="pair")
    a = guard.wrap(jax.jit(lambda x: x + 1))
    b = guard.wrap(jax.jit(lambda x: x - 1))
    a(jnp.ones(2))
    b(jnp.ones(2))
    assert guard.compiles == 2


def test_host_transfer_guard_cheap_on_big_host_lists():
    # the probe is bounded: converting a large host list must not walk
    # every element (the guard is armed process-wide in the learner)
    import time

    big = list(range(2_000_000))
    with HostTransferGuard() as guard:
        t0 = time.perf_counter()
        np.array(big)
        probe_overhead = time.perf_counter() - t0
    assert guard.transfers == 0
    # conversion itself dominates; just pin that we didn't add a
    # python-level walk of all 2M elements (that costs ~100ms+)
    t0 = time.perf_counter()
    np.array(big)
    bare = time.perf_counter() - t0
    assert probe_overhead < bare * 3 + 0.05


def test_host_transfer_guard_counts_device_syncs():
    # jaxlint: disable=retrace-risk -- one-shot helper to mint a committed device array
    value = jax.jit(lambda x: x + 1)(jnp.ones(3))
    with HostTransferGuard() as guard:
        np.asarray(value)
        np.array(value)
        jax.device_get({"metrics": value})
        np.asarray(np.ones(3))      # host array: free
        np.array([1.0, 2.0])        # host list: free
    assert guard.transfers == 3


def test_host_transfer_guard_snapshot_deltas():
    value = jnp.ones(3)
    with HostTransferGuard() as guard:
        np.asarray(value)
        assert guard.snapshot() == 1
        np.asarray(value)
        np.asarray(value)
        assert guard.snapshot() == 2
        assert guard.snapshot() == 0


def test_host_transfer_guard_budget():
    value = jnp.ones(3)
    with pytest.raises(HostTransferError):
        with HostTransferGuard(max_transfers=1) as guard:
            np.asarray(value)
            np.asarray(value)
    # the patch must be unwound even when the budget raised
    assert np.asarray.__module__ == "numpy"


def test_host_transfer_guard_restores_entry_points():
    orig_asarray = np.asarray
    orig_array = np.array
    orig_get = jax.device_get
    with HostTransferGuard():
        assert np.asarray is not orig_asarray
    assert np.asarray is orig_asarray
    assert np.array is orig_array
    assert jax.device_get is orig_get


def test_host_transfer_guard_keeps_keyword_signatures():
    # the patched entry points must accept the originals' documented
    # keyword forms for their first argument
    value = jnp.ones(3)
    with HostTransferGuard() as guard:
        assert np.array(object=[1, 2]).tolist() == [1, 2]
        assert np.asarray(a=[3, 4]).tolist() == [3, 4]
        assert jax.device_get(x=value).shape == (3,)
    assert guard.transfers == 1  # only the device_get touched a jax array


def test_host_transfer_guard_not_reentrant():
    with HostTransferGuard() as guard:
        with pytest.raises(RuntimeError, match="reentrant"):
            guard.__enter__()


# -- ShardingContractGuard --------------------------------------------

def test_sharding_guard_stable_layout_counts_nothing():
    guard = ShardingContractGuard(name="step")
    step = guard.wrap(jax.jit(lambda x: x * 2))
    for _ in range(5):
        step(jnp.ones(4))
    assert guard.copies == 0
    assert guard.snapshot() == 0


def test_sharding_guard_counts_device_layout_change():
    # two CPU devices from the virtual 8-device mesh: placing the same
    # argument on a different device changes its SingleDeviceSharding,
    # which is exactly a resharding copy at the jit boundary
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs 2 virtual devices")
    guard = ShardingContractGuard(name="step")
    step = guard.wrap(jax.jit(lambda x: x + 1))
    step(jax.device_put(jnp.ones(4), devices[0]))
    step(jax.device_put(jnp.ones(4), devices[1]))
    assert guard.copies == 1
    assert guard.snapshot() == 1
    assert guard.snapshot() == 0


def test_sharding_guard_counts_named_sharding_change():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs 2 virtual devices")
    mesh = Mesh(np.asarray(devices[:2]), ("dp",))
    rep = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("dp"))
    guard = ShardingContractGuard(name="step")
    step = guard.wrap(jax.jit(lambda x: x.sum()))
    step(jax.device_put(jnp.ones(4), rep))
    step(jax.device_put(jnp.ones(4), dp))  # silent reshard
    assert guard.copies == 1


def test_sharding_guard_budget_raises_at_the_offending_call():
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs 2 virtual devices")
    guard = ShardingContractGuard(max_copies=0, name="step")
    assert guard.max_copies == 0  # 0 = count only, never raise
    strict = ShardingContractGuard(max_copies=1, name="update_step")
    step = strict.wrap(jax.jit(lambda x: x + 1))
    step(jax.device_put(jnp.ones(4), devices[0]))
    step(jax.device_put(jnp.ones(4), devices[1]))  # 1 copy: at budget
    with pytest.raises(ShardingContractError, match="update_step"):
        step(jax.device_put(jnp.ones(4), devices[1]))  # over budget


def test_sharding_guard_new_treedef_opens_fresh_contract():
    # a different argument STRUCTURE is a different program (its own
    # compile, its own contract) — not a resharding of the old one
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs 2 virtual devices")
    guard = ShardingContractGuard(name="step")
    step = guard.wrap(
        jax.jit(lambda t: jax.tree.map(lambda a: a + 1, t)))
    step({"a": jax.device_put(jnp.ones(4), devices[1])})
    step({"a": jax.device_put(jnp.ones(4), devices[1]),
          "b": jax.device_put(jnp.ones(4), devices[1])})
    assert guard.copies == 0


def test_sharding_guard_skips_hostside_leaves():
    # numpy arrays / python scalars have no .sharding: the jit's own
    # device_put places them per its contract, nothing to compare
    guard = ShardingContractGuard(name="step")
    step = guard.wrap(jax.jit(lambda x, lr: x * lr))
    step(np.ones(4), 0.5)
    step(np.ones(4), 0.25)
    assert guard.copies == 0


def test_sharding_guard_uncommitted_first_call_is_free():
    """The learner's first step feeds freshly optimizer.init-ed state:
    uncommitted arrays whose placement onto the mesh is designed
    initialization.  The contract must latch on the committed layout
    the donated outputs come back with — NOT on the uncommitted first
    call — or every subsequent step would count as a reshard (the
    exact e2e failure this guard's first design had)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs 4 virtual devices")
    mesh = Mesh(np.asarray(devices[:4]), ("dp",))
    rep = NamedSharding(mesh, P())
    guard = ShardingContractGuard(max_copies=1, name="update_step")
    step = guard.wrap(jax.jit(
        lambda s: s + 1, in_shardings=(rep,), out_shardings=rep,
        donate_argnums=(0,)))
    state = jnp.zeros(4, jnp.int32)       # uncommitted: free to place
    for _ in range(5):
        state = step(state)               # committed rep after call 1
    assert guard.copies == 0


def test_sharding_guard_sums_over_wrapped_fns():
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs 2 virtual devices")
    guard = ShardingContractGuard(name="pair")
    a = guard.wrap(jax.jit(lambda x: x + 1))
    b = guard.wrap(jax.jit(lambda x: x - 1))
    a(jax.device_put(jnp.ones(2), devices[0]))
    b(jax.device_put(jnp.ones(2), devices[0]))
    a(jax.device_put(jnp.ones(2), devices[1]))
    assert guard.copies == 1


# ---------------------------------------------------------------------
# StallWatchdog
# ---------------------------------------------------------------------

def test_stall_watchdog_counts_and_recovers():
    """A loop silent past the threshold is ONE stall event (not one per
    sample); beating again recovers it, and a later silence counts as a
    fresh episode."""
    from handyrl_tpu.analysis.guards import StallWatchdog

    t = [0.0]
    dog = StallWatchdog(max_stall_seconds=5.0, clock=lambda: t[0])
    dog.beat("server")
    t[0] = 3.0
    assert dog.sample() == 0          # within threshold
    t[0] = 6.0
    assert dog.sample() == 1          # newly stalled
    t[0] = 9.0
    assert dog.sample() == 0          # same episode: counted once
    dog.beat("server")                # recovery
    t[0] = 20.0
    assert dog.sample() == 1          # second episode
    assert dog.stall_events == 2


def test_stall_watchdog_snapshot_is_a_delta():
    from handyrl_tpu.analysis.guards import StallWatchdog

    t = [0.0]
    dog = StallWatchdog(max_stall_seconds=1.0, clock=lambda: t[0])
    dog.beat("send_loop")
    t[0] = 5.0
    dog.sample()
    assert dog.snapshot() == 1
    assert dog.snapshot() == 0        # per-epoch delta semantics


def test_stall_watchdog_tracks_loops_independently():
    from handyrl_tpu.analysis.guards import StallWatchdog

    t = [0.0]
    dog = StallWatchdog(max_stall_seconds=2.0, clock=lambda: t[0])
    dog.beat("server")
    dog.beat("recv_loop")
    t[0] = 1.5
    dog.beat("recv_loop")             # only the server goes silent
    t[0] = 3.0
    assert dog.sample() == 1
    assert dog.stall_events == 1


def test_stall_watchdog_dumps_the_stalled_stack(capsys):
    from handyrl_tpu.analysis.guards import StallWatchdog

    t = [0.0]
    dog = StallWatchdog(max_stall_seconds=1.0, clock=lambda: t[0])
    dog.beat("server")
    t[0] = 10.0
    dog.sample()
    out = capsys.readouterr().out
    assert "control-plane loop 'server' silent" in out
    assert "File " in out             # a real stack dump, not a shrug


def test_stall_watchdog_start_stop_idempotent():
    from handyrl_tpu.analysis.guards import StallWatchdog

    dog = StallWatchdog(max_stall_seconds=60.0)
    dog.start()
    dog.start()                       # second start is a no-op
    dog.beat("server")
    dog.stop()
    dog.stop()                        # second stop is a no-op
    assert dog.stall_events == 0


# ---------------------------------------------------------------------
# LockOrderGuard
# ---------------------------------------------------------------------

def test_lock_guard_counts_contention_with_injected_clock():
    import threading

    from handyrl_tpu.analysis.guards import LockOrderGuard

    # each acquire reads the clock twice (before/after): 1.5s of
    # "wait" on the first acquire, none on the rest
    times = iter([0.0, 1.5, 2.0, 2.0, 3.0, 3.0])
    guard = LockOrderGuard(clock=lambda: next(times))
    lock = guard.wrap(threading.Lock(), "A")
    with lock:
        pass
    with lock:
        pass
    with lock:
        pass
    snap = guard.snapshot()
    assert snap["lock_contention_sec"] == pytest.approx(1.5)
    assert snap["lock_order_inversions"] == 0


def test_lock_guard_detects_forced_order_inversion():
    """A then B fixes the direction; B then A later is a counted
    inversion — the latent ABBA deadlock that has not fired yet."""
    import threading

    from handyrl_tpu.analysis.guards import LockOrderGuard

    t = [0.0]
    guard = LockOrderGuard(clock=lambda: t[0])
    a = guard.wrap(threading.Lock(), "A")
    b = guard.wrap(threading.Lock(), "B")
    with a:
        with b:
            pass
    assert guard.inversions == 0
    with b:
        with a:
            pass
    assert guard.inversions == 1
    snap = guard.snapshot()
    assert snap["lock_order_inversions"] == 1
    assert guard.snapshot()["lock_order_inversions"] == 0  # delta


def test_lock_guard_reentrant_reacquire_records_no_pair():
    import threading

    from handyrl_tpu.analysis.guards import LockOrderGuard

    t = [0.0]
    guard = LockOrderGuard(clock=lambda: t[0])
    r = guard.wrap(threading.RLock(), "R")
    with r:
        with r:
            pass
    assert guard.inversions == 0
    assert guard.stats()["locks_guarded"] == 1


def test_lock_guard_arm_replaces_in_place_and_tolerates_absence():
    import threading

    from handyrl_tpu.analysis.guards import LockOrderGuard, _GuardedLock

    class Box:
        def __init__(self):
            self._lock = threading.Lock()

    guard = LockOrderGuard()
    box = Box()
    assert guard.arm(box, "_lock")
    assert isinstance(box._lock, _GuardedLock)
    assert not guard.arm(box, "_lock")       # already wrapped
    assert not guard.arm(box, "_missing")    # absent attribute
    assert not guard.arm(None, "_lock")      # absent subsystem
    with box._lock:                          # still a working lock
        assert box._lock.locked()
    assert not box._lock.locked()


def test_lock_guard_cross_thread_contention_real_clock():
    """Two real threads contending on one guarded lock: the waiter's
    blocked time lands in lock_contention_sec."""
    import threading
    import time as _time

    from handyrl_tpu.analysis.guards import LockOrderGuard

    guard = LockOrderGuard()
    lock = guard.wrap(threading.Lock(), "hot")
    entered = threading.Event()

    def holder():
        with lock:
            entered.set()
            _time.sleep(0.2)

    thread = threading.Thread(target=holder)
    thread.start()
    entered.wait(5)
    with lock:
        pass
    thread.join(5)
    assert guard.stats()["lock_contention_sec"] >= 0.1
    assert guard.stats()["lock_order_inversions"] == 0


# ---------------------------------------------------------------------
# NumericsGuard
# ---------------------------------------------------------------------

def test_numerics_guard_stable_dtypes_count_nothing():
    guard = NumericsGuard(name="step")
    step = guard.wrap(jax.jit(lambda t: jax.tree.map(
        lambda a: a + 1, t)))
    for _ in range(5):
        step({"w": jnp.ones(4, jnp.float32),
              "h": jnp.ones(4, jnp.bfloat16)})
    assert guard.contract_breaks == 0
    assert guard.weak_upcasts == 0


def test_numerics_guard_counts_injected_fp64_leaf():
    """A leaf arriving at a different concrete dtype than the latched
    contract is exactly one break per deviating call."""
    guard = NumericsGuard(name="step")
    step = guard.wrap(jax.jit(lambda t: jax.tree.map(
        lambda a: a + 1, t)))
    step({"w": jnp.ones(4, jnp.float32)})
    step({"w": np.ones(4, np.float64)})  # the split-brain leaf
    assert guard.contract_breaks == 1
    # the contract does NOT re-latch: a persistent flip keeps counting
    step({"w": np.ones(4, np.float64)})
    assert guard.contract_breaks == 2


def test_numerics_guard_weak_flip_is_an_upcast_not_a_break():
    guard = NumericsGuard(name="step")
    step = guard.wrap(jax.jit(lambda x: x * 2))
    step(jnp.ones(4, jnp.bfloat16))      # concrete bf16 latches
    step(0.5)                            # weak Python scalar flip
    assert guard.weak_upcasts == 1
    assert guard.contract_breaks == 0


def test_numerics_guard_new_treedef_opens_fresh_contract():
    guard = NumericsGuard(name="step")
    step = guard.wrap(jax.jit(lambda t: jax.tree.map(
        lambda a: a + 1, t)))
    step({"a": jnp.ones(4, jnp.float32)})
    step({"a": jnp.ones(4, jnp.float32),
          "b": jnp.ones(4, jnp.bfloat16)})  # new program, new contract
    assert guard.contract_breaks == 0


def test_numerics_guard_forced_nan_counts_exactly_once_per_step():
    """The in-graph flag (ops.update's `nonfinite` metric) is fed once
    per step at the epoch fetch: one NaN step is one count, finite
    steps count nothing, and the flag may be a device scalar."""
    guard = NumericsGuard(name="step")
    flag = jax.jit(
        lambda x: 1.0 - jnp.isfinite(x).astype(jnp.float32))
    bad = [guard.note_step(flag(x))
           for x in (1.0, float("nan"), 2.0)]
    assert bad == [False, True, False]
    assert guard.stats()["nonfinite_steps"] == 1


def test_numerics_guard_budget_raises_past_max_nonfinite():
    guard = NumericsGuard(max_nonfinite=1, name="update_step")
    guard.note_step(1.0)                 # at budget: count only
    with pytest.raises(NumericsError, match="update_step"):
        guard.note_step(1.0)             # over budget
    # max_nonfinite=0 means count-and-report, never raise
    lax = NumericsGuard(max_nonfinite=0, name="step")
    for _ in range(5):
        lax.note_step(1.0)
    assert lax.stats()["nonfinite_steps"] == 5


def test_numerics_guard_snapshot_is_a_delta():
    guard = NumericsGuard(name="step")
    step = guard.wrap(jax.jit(lambda x: x + 1))
    step(jnp.ones(4, jnp.float32))
    step(jnp.ones(4, jnp.bfloat16))
    guard.note_step(1.0)
    snap = guard.snapshot()
    assert snap == {"nonfinite_steps": 1,
                    "numerics_contract_breaks": 1,
                    "weak_upcasts": 0}
    assert guard.snapshot() == {"nonfinite_steps": 0,
                                "numerics_contract_breaks": 0,
                                "weak_upcasts": 0}


def test_numerics_guard_off_switch_is_a_true_noop():
    fn = jax.jit(lambda x: x + 1)
    guard = NumericsGuard(name="step", enabled=False)
    assert guard.wrap(fn) is fn          # identity, zero overhead
    assert guard.note_step(1.0) is False  # disabled: nothing counts
    assert guard.stats() == {"nonfinite_steps": 0,
                             "numerics_contract_breaks": 0,
                             "weak_upcasts": 0,
                             "max_nonfinite_steps": 0}


# -- ResourceLedger ----------------------------------------------------

def test_resource_ledger_snapshot_has_stable_keys():
    ledger = ResourceLedger(warmup_epochs=0)
    record = ledger.snapshot()
    assert set(record) == {"fd_count", "thread_count",
                           "shm_segments", "resource_growth"}
    assert record["fd_count"] > 0        # this process has open fds
    assert record["thread_count"] >= 1


def test_resource_ledger_leaked_socket_trips_the_delta():
    """A deliberately leaked socket shows up as fd growth over the
    post-warmup baseline — the soak meter the static rules cannot
    replace (handles escaping into containers)."""
    import socket

    ledger = ResourceLedger(warmup_epochs=1)
    ledger.snapshot()                    # warmup
    ledger.snapshot()                    # sets the baseline
    leaked = [socket.socket() for _ in range(4)]
    try:
        record = ledger.snapshot()
        assert record["resource_growth"] >= 4
        assert ledger.stats()["peak_fd_growth"] >= 4
    finally:
        for s in leaked:
            s.close()
    # releasing the leak brings growth back inside the budget
    assert ledger.snapshot()["resource_growth"] <= 1


def test_resource_ledger_leaked_ring_trips_shm_count():
    """A leaked ShmRing is visible in the /dev/shm segment sample."""
    from handyrl_tpu.pipeline.shm import ShmRing

    ledger = ResourceLedger(warmup_epochs=0)
    before = ledger.snapshot()["shm_segments"]
    ring = ShmRing.create(slots=2, slot_bytes=128)
    try:
        assert ledger.snapshot()["shm_segments"] == before + 1
    finally:
        ring.close()
    assert ledger.snapshot()["shm_segments"] == before


def test_resource_ledger_budget_raises_past_max_fd_growth():
    import socket

    ledger = ResourceLedger(max_fd_growth=2, warmup_epochs=0)
    ledger.snapshot()                    # baseline
    leaked = [socket.socket() for _ in range(4)]
    try:
        with pytest.raises(ResourceError):
            ledger.snapshot()
    finally:
        for s in leaked:
            s.close()


def test_resource_ledger_default_budget_never_raises():
    import socket

    ledger = ResourceLedger(warmup_epochs=0)
    ledger.snapshot()
    leaked = [socket.socket() for _ in range(8)]
    try:
        record = ledger.snapshot()       # counts, does not raise
        assert record["resource_growth"] >= 8
    finally:
        for s in leaked:
            s.close()


def test_resource_ledger_degrades_without_proc(tmp_path):
    """On hosts without /proc the keys stay present (schema stability)
    and the fd samples degrade to 0."""
    ledger = ResourceLedger(proc_fd_dir=str(tmp_path / "nope"),
                            shm_dir=str(tmp_path / "nope"))
    record = ledger.snapshot()
    assert record["fd_count"] == 0
    assert record["shm_segments"] == 0
    assert record["thread_count"] >= 1


def test_resource_ledger_delta_line_reports_movement():
    import socket

    ledger = ResourceLedger()
    base = ledger.sample()
    sock = socket.socket()
    try:
        line = ledger.delta_line(base)
    finally:
        sock.close()
    assert line.startswith("resources: fd ")
    assert "(+1)" in line
