"""Fixture: suppressed tracer branch (e.g. deliberately concretized
under jax.disable_jit in a debug harness)."""

import jax


@jax.jit
def debug_clip(x):
    # jaxlint: disable=tracer-branch -- only ever run under jax.disable_jit
    if x > 10:
        return x * 0
    return x
