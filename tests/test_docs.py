"""Docs <-> config consistency: docs/parameters.md must document every
config key and must not document keys that do not exist, so the page
cannot drift from handyrl_tpu/config.py."""

import dataclasses
import os
import re

from handyrl_tpu.anakin.config import AnakinConfig
from handyrl_tpu.config import TrainConfig, WorkerConfig
from handyrl_tpu.pipeline.config import PipelineConfig
from handyrl_tpu.resilience.chaos import ChaosConfig
from handyrl_tpu.serving.config import RouterConfig, ServingConfig
from handyrl_tpu.telemetry.costmodel import PerfConfig

DOCS = os.path.join(os.path.dirname(__file__), "..", "docs",
                    "parameters.md")


def _documented_keys():
    with open(DOCS) as f:
        text = f.read()
    # keys are documented as "* `name`, type = ..." or "* `name`" bullets
    return set(re.findall(r"^\s*\* `([a-z_]+)`", text, re.MULTILINE))


def _config_keys():
    keys = set()
    for field in dataclasses.fields(TrainConfig):
        if field.name == "env":
            continue  # internal merged-env slot, not a YAML key
        keys.add("lambda" if field.name == "lambda_" else field.name)
    for field in dataclasses.fields(WorkerConfig):
        keys.add(field.name)
    for field in dataclasses.fields(ChaosConfig):
        keys.add(field.name)  # the documented chaos.* sub-keys
    for field in dataclasses.fields(PipelineConfig):
        keys.add(field.name)  # the documented pipeline.* sub-keys
    for field in dataclasses.fields(AnakinConfig):
        keys.add(field.name)  # the documented anakin.* sub-keys
    for field in dataclasses.fields(ServingConfig):
        keys.add(field.name)  # the documented serving.* sub-keys
    for field in dataclasses.fields(RouterConfig):
        keys.add(field.name)  # the documented router.* sub-keys
    # PerfConfig is a plain class, not a dataclass: its KEYS tuple is
    # the validated perf.* key set
    keys.update(PerfConfig.KEYS)
    keys.update({"env", "opponent"})  # env_args.env + eval.opponent
    return keys


def test_every_config_key_is_documented():
    missing = _config_keys() - _documented_keys()
    assert not missing, f"undocumented config keys: {sorted(missing)}"


def test_no_phantom_keys_documented():
    phantom = _documented_keys() - _config_keys()
    assert not phantom, (
        f"docs/parameters.md documents non-existent keys: "
        f"{sorted(phantom)}")


def test_docs_exist():
    for name in ("api.md", "custom_environment.md",
                 "large_scale_training.md", "observability.md",
                 "parameters.md", "serving.md", "static_analysis.md"):
        path = os.path.join(os.path.dirname(DOCS), name)
        assert os.path.exists(path), f"missing doc {name}"


def test_static_analysis_doc_covers_every_rule():
    """docs/static_analysis.md documents each lint rule by id — ALL
    SIX registries (the suppression comments reference these names,
    so the page is the rule registries' public contract).  Mechanical,
    like the parameters check above: a new rule set cannot land
    undocumented."""
    from handyrl_tpu.analysis.commrules import COMM_RULES
    from handyrl_tpu.analysis.leakrules import LEAK_RULES
    from handyrl_tpu.analysis.numrules import NUM_RULES
    from handyrl_tpu.analysis.racerules import RACE_RULES
    from handyrl_tpu.analysis.rules import RULES
    from handyrl_tpu.analysis.shardrules import SHARD_RULES

    path = os.path.join(os.path.dirname(DOCS), "static_analysis.md")
    with open(path) as f:
        text = f.read()
    missing = [r
               for r in (list(RULES) + list(SHARD_RULES)
                         + list(COMM_RULES) + list(RACE_RULES)
                         + list(NUM_RULES) + list(LEAK_RULES))
               if f"`{r}`" not in text]
    assert not missing, f"rules undocumented in static_analysis.md: {missing}"


def test_list_rules_covers_every_registry():
    """`handyrl-jaxlint --list-rules` prints every registered rule of
    every family with its one-line doc, without needing the family
    flags — the CLI's discoverability contract."""
    import contextlib
    import io

    from handyrl_tpu.analysis.commrules import COMM_RULES
    from handyrl_tpu.analysis.jaxlint import main
    from handyrl_tpu.analysis.leakrules import LEAK_RULES
    from handyrl_tpu.analysis.numrules import NUM_RULES
    from handyrl_tpu.analysis.racerules import RACE_RULES
    from handyrl_tpu.analysis.rules import RULES
    from handyrl_tpu.analysis.shardrules import SHARD_RULES

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["--list-rules"]) == 0
    out = buf.getvalue()
    for registry in (RULES, SHARD_RULES, COMM_RULES, RACE_RULES,
                     NUM_RULES, LEAK_RULES):
        for rule_id, rule in registry.items():
            assert f"{rule_id}: {rule.summary}" in out, (
                f"--list-rules missing {rule_id} (or its summary)")
