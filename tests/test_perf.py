"""Perf attribution layer: cost model, self-time tree, ledger, report.

Covers the PR-20 contracts end to end without a training run:

  * PerfConfig validation + peak resolution (table vs overrides);
  * the RetraceGuard ``on_compile`` hook — fires once per NEW abstract
    signature, BEFORE the call, and hook failures never kill the step;
  * CostModel harvest against a real tiny jit on CPU (XLA's own
    cost_analysis numbers) and the epoch MFU/roofline reduction,
    including every verdict branch;
  * self_time_tree containment (nesting, threads, instants) and the
    untracked-residual identity over a metrics record's rounded values;
  * Attributor snapshots + the flight-recorder ``register_dump_extra``
    ride-along;
  * scripts/perf_ledger.py append/--check regression verdicts and
    scripts/attribution_report.py over a synthetic run directory.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import pytest

from handyrl_tpu import telemetry
from handyrl_tpu.analysis.guards import RetraceGuard
from handyrl_tpu.telemetry.attribution import (
    Attributor,
    self_time_tree,
    top_self,
    untracked_residual,
)
from handyrl_tpu.telemetry.costmodel import (
    DEVICE_PEAKS,
    PEAK_TFLOPS,
    CostModel,
    PerfConfig,
    mfu_extras,
    resolve_peaks,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                "..", "scripts"))

import attribution_report  # noqa: E402
import perf_ledger  # noqa: E402


# -- PerfConfig / peaks -------------------------------------------------

def test_perf_config_defaults_and_validation():
    cfg = PerfConfig.from_config({})
    assert cfg.peak_tflops == 0.0
    assert cfg.peak_hbm_gbs == 0.0
    assert cfg.cost_analysis is True
    with pytest.raises(ValueError, match="unknown perf keys"):
        PerfConfig.from_config({"peak_tflop": 1.0})
    with pytest.raises(ValueError, match="peak_tflops"):
        PerfConfig.from_config({"peak_tflops": -1.0})
    with pytest.raises(ValueError, match="peak_hbm_gbs"):
        PerfConfig.from_config({"peak_hbm_gbs": -5})


def test_resolve_peaks_table_override_and_unknown():
    # the table row wins when no override is set
    assert resolve_peaks(None, kind="TPU v4") == DEVICE_PEAKS["TPU v4"]
    # config overrides win over the table
    cfg = PerfConfig(peak_tflops=123.0, peak_hbm_gbs=456.0)
    assert resolve_peaks(cfg, kind="TPU v4") == (123.0, 456.0)
    # a partial override keeps the table's other column
    cfg = PerfConfig(peak_tflops=123.0)
    assert resolve_peaks(cfg, kind="TPU v4") == \
        (123.0, DEVICE_PEAKS["TPU v4"][1])
    # unknown kind, no override: nothing to claim
    assert resolve_peaks(None, kind="CPU") == (None, None)


def test_bench_view_is_column_one_of_the_table():
    assert PEAK_TFLOPS == {k: v[0] for k, v in DEVICE_PEAKS.items()}


def test_mfu_extras_matches_the_bench_reduction():
    out = mfu_extras(1e12, 2.0, kind="TPU v4")
    assert out["achieved_tflops_est"] == 2.0
    assert out["mfu_measured"] == round(2.0 / 275.0, 4)
    # unknown kind: MFU omitted, achieved still reported
    out = mfu_extras(1e12, 2.0, kind="CPU")
    assert "mfu_measured" not in out
    assert out["achieved_tflops_est"] == 2.0


# -- guard hook + harvest ----------------------------------------------

def test_guard_on_compile_fires_once_per_new_signature():
    guard = RetraceGuard(name="t")
    seen = []
    guard.on_compile = lambda label, fn, args, kwargs: \
        seen.append((label, args[0].shape))
    wrapped = guard.wrap(jax.jit(lambda x: x * 2), label="prog")
    x8, x16 = jnp.ones(8), jnp.ones(16)
    wrapped(x8)
    wrapped(x8)       # same signature: no second fire
    wrapped(x16)      # new signature: fires again
    assert seen == [("prog", (8,)), ("prog", (16,))]
    assert guard.compiles == 2


def test_guard_on_compile_failure_never_kills_the_step(capsys):
    guard = RetraceGuard(name="t")

    def bad_hook(label, fn, args, kwargs):
        raise RuntimeError("boom")

    guard.on_compile = bad_hook
    wrapped = guard.wrap(jax.jit(lambda x: x + 1))
    out = wrapped(jnp.ones(4))
    assert out.shape == (4,)
    assert "on_compile hook failed" in capsys.readouterr().out


def test_costmodel_harvests_real_xla_numbers_on_cpu():
    cm = CostModel(PerfConfig(), kind="cpu-test")
    fn = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((32, 32), jnp.float32)
    cm.on_compile("step", fn, (x,), {})
    prog = cm.program("step")
    assert prog is not None and prog["harvests"] == 1
    # a 32x32 matmul is ~2*32^3 flops; XLA's number includes the sum
    assert prog["flops"] >= 2 * 32 ** 3
    assert prog["bytes"] > 0
    assert cm.harvest_failures == 0


def test_costmodel_async_harvest_lands_off_thread():
    """The inference service's hook: avals snapshot synchronously, the
    compile runs on the drain worker — the caller never blocks on XLA
    (the blocking variant stalled the batching thread long enough that
    workers degraded to local inference in the chaos drill)."""
    cm = CostModel(PerfConfig(), kind="cpu-test")
    fn = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((32, 32), jnp.float32)
    cm.on_compile_async("infer", fn, (x,), {})
    deadline = time.time() + 30.0
    while cm.program("infer") is None and time.time() < deadline:
        time.sleep(0.01)
    prog = cm.program("infer")
    assert prog is not None and prog["flops"] >= 2 * 32 ** 3
    assert cm.harvest_failures == 0
    # the worker exits once the queue drains (once-per-signature
    # harvests must not hold a thread for the process lifetime)
    deadline = time.time() + 10.0
    while cm._worker is not None and time.time() < deadline:
        time.sleep(0.01)
    assert cm._worker is None


def test_costmodel_async_harvest_first_signature_wins():
    """The serving path re-traces one program per batch bucket; only
    the first bucket harvests (a per-bucket re-compile would contend
    for the core at arbitrary serving moments, e.g. mid-respawn)."""
    cm = CostModel(PerfConfig(), kind="cpu-test")
    fn = jax.jit(lambda x: (x @ x).sum())
    cm.on_compile_async("infer", fn, (jnp.ones((16, 16)),), {})
    deadline = time.time() + 30.0
    while cm.program("infer") is None and time.time() < deadline:
        time.sleep(0.01)
    first = cm.program("infer")
    assert first is not None
    cm.on_compile_async("infer", fn, (jnp.ones((64, 64)),), {})
    deadline = time.time() + 5.0
    while cm._worker is not None and time.time() < deadline:
        time.sleep(0.01)
    assert cm.program("infer") == first     # second bucket skipped


def test_costmodel_async_harvest_failure_counts_never_raises():
    cm = CostModel(PerfConfig(), kind="cpu-test")
    cm.on_compile_async("infer", object(), (), {})   # no .lower at all
    deadline = time.time() + 10.0
    while cm.harvest_failures == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert cm.harvest_failures == 1
    assert cm.program("infer") is None


def test_costmodel_harvest_failure_counts_never_raises():
    cm = CostModel(PerfConfig(), kind="cpu-test")
    cm.on_compile("step", object(), (), {})    # no .lower at all
    assert cm.program("step") is None
    assert cm.harvest_failures == 1


def test_costmodel_harvest_off_by_config():
    cm = CostModel(PerfConfig(cost_analysis=False), kind="cpu-test")
    cm.on_compile("step", jax.jit(lambda x: x), (jnp.ones(4),), {})
    assert cm.program("step") is None
    assert cm.harvest_failures == 0


def test_costmodel_keeps_latest_signature_numbers():
    cm = CostModel(PerfConfig(), kind="cpu-test")
    fn = jax.jit(lambda x: (x @ x).sum())
    cm.on_compile("step", fn, (jnp.ones((16, 16)),), {})
    small = cm.program("step")["flops"]
    cm.on_compile("step", fn, (jnp.ones((64, 64)),), {})
    prog = cm.program("step")
    assert prog["flops"] > small        # re-laid geometry replaces
    assert prog["harvests"] == 2


# -- epoch reduction ---------------------------------------------------

def _programmed(flops, hbm_bytes, peak_tflops=0.0, peak_gbs=0.0):
    cm = CostModel(PerfConfig(peak_tflops=peak_tflops,
                              peak_hbm_gbs=peak_gbs), kind="cpu-test")
    with cm._lock:
        cm._programs["step"] = {
            "flops": flops, "bytes": hbm_bytes, "harvests": 1}
    return cm


def test_epoch_metrics_schema_is_stable_when_unknowable():
    cm = CostModel(PerfConfig(), kind="cpu-test")
    out = cm.epoch_metrics("step", 1.0, 10)
    assert out == {"mfu": None, "achieved_tflops": None,
                   "arithmetic_intensity": None,
                   "roofline_verdict": "unknown"}
    # harvested program but no peak row: achieved yes, mfu no
    cm = _programmed(2e12, 1e9)
    out = cm.epoch_metrics("step", 2.0, 10)
    assert out["achieved_tflops"] == pytest.approx(10.0)
    assert out["mfu"] is None
    assert out["roofline_verdict"] == "unknown"


def test_epoch_metrics_mfu_and_roofline_math():
    # ridge = 100 TFLOP/s / 1000 GB/s * 1e3 = 100 flops/byte
    cm = _programmed(2e12, 1e9, peak_tflops=100.0, peak_gbs=1000.0)
    out = cm.epoch_metrics("step", 2.0, 10)
    # achieved = 2e12 * 10 / 2.0 / 1e12 = 10 TFLOP/s -> mfu 0.1
    assert out["achieved_tflops"] == pytest.approx(10.0)
    assert out["mfu"] == pytest.approx(0.1)
    # intensity 2e12/1e9 = 2000 flops/byte >= ridge -> compute-bound
    assert out["arithmetic_intensity"] == pytest.approx(2000.0)
    assert out["roofline_verdict"] == "compute-bound"

    cm = _programmed(1e10, 1e9, peak_tflops=100.0, peak_gbs=1000.0)
    out = cm.epoch_metrics("step", 2.0, 10)
    # intensity 10 flops/byte < ridge 100 -> memory-bound
    assert out["roofline_verdict"] == "memory-bound"
    # zero device time / steps: rates unknowable, intensity still known
    out = cm.epoch_metrics("step", 0.0, 0)
    assert out["achieved_tflops"] is None and out["mfu"] is None
    assert out["arithmetic_intensity"] == pytest.approx(10.0)


def test_costmodel_stats_shape():
    cm = _programmed(1.0, 1.0, peak_tflops=9.0, peak_gbs=9.0)
    stats = cm.stats()
    assert stats["device_kind"] == "cpu-test"
    assert stats["peak_tflops"] == 9.0
    assert stats["programs"]["step"]["harvests"] == 1
    assert stats["cost_analysis"] is True
    assert stats["harvest_failures"] == 0


# -- self-time tree ----------------------------------------------------

def _span(name, ts, dur, role="learner", pid=1, tid=1):
    return {"name": name, "ts": ts, "dur": dur,
            "role": role, "pid": pid, "tid": tid}


def test_self_time_tree_subtracts_nested_children():
    tree = self_time_tree([
        _span("epoch", 0.0, 10.0),
        _span("update", 1.0, 4.0),
        _span("device", 2.0, 2.0),     # nested inside update
        _span("save", 6.0, 3.0),       # sibling of update
    ])
    assert tree["learner/epoch"]["self_sec"] == pytest.approx(3.0)
    assert tree["learner/update"]["self_sec"] == pytest.approx(2.0)
    assert tree["learner/device"]["self_sec"] == pytest.approx(2.0)
    assert tree["learner/save"]["self_sec"] == pytest.approx(3.0)
    # total time is never reduced by children
    assert tree["learner/epoch"]["total_sec"] == pytest.approx(10.0)


def test_self_time_tree_threads_never_nest_across():
    tree = self_time_tree([
        _span("a", 0.0, 10.0, tid=1),
        _span("b", 1.0, 5.0, tid=2),   # other thread: NOT a child
    ])
    assert tree["learner/a"]["self_sec"] == pytest.approx(10.0)
    assert tree["learner/b"]["self_sec"] == pytest.approx(5.0)


def test_self_time_tree_aggregates_counts_and_instants():
    tree = self_time_tree([
        _span("step", 0.0, 1.0),
        _span("step", 2.0, 1.0),
        _span("mark", 0.5, 0.0),       # instant event, zero time
        {"ts": 3.0, "dur": 1.0},       # nameless: skipped
    ])
    assert tree["learner/step"]["count"] == 2
    assert tree["learner/step"]["total_sec"] == pytest.approx(2.0)
    assert tree["learner/mark"] == {
        "count": 1, "total_sec": 0.0, "self_sec": 0.0}
    assert len(tree) == 2


def test_top_self_orders_by_self_time_then_name():
    tree = self_time_tree([
        _span("big", 0.0, 5.0),
        _span("tie_a", 6.0, 1.0),
        _span("tie_b", 8.0, 1.0),
    ])
    assert top_self(tree, 2) == [["learner/big", 5.0],
                                 ["learner/tie_a", 1.0]]


def test_untracked_residual_identity_over_rounded_values():
    record = {
        "epoch_wall_sec": 2.0,
        "profile_update_sec": 0.7,
        "profile_batch_wait_sec": 0.2,
        "profile_ingest_sec": 0.1,
        "batch_wait_sec": 99.0,        # not a profile_* key: ignored
        "profile_note": "x",           # non-numeric: ignored
    }
    residual = untracked_residual(record)
    assert residual == pytest.approx(1.0)
    # the emitted identity reconciles exactly, by construction
    tracked = sum(v for k, v in record.items()
                  if k.startswith("profile_") and k.endswith("_sec"))
    assert tracked + residual == pytest.approx(
        record["epoch_wall_sec"], abs=1e-9)
    # negative residual (thread-window skew) is representable
    assert untracked_residual(
        {"epoch_wall_sec": 1.0, "profile_update_sec": 1.2}) == \
        pytest.approx(-0.2)
    assert untracked_residual({}) == 0.0


# -- Attributor + dump extras ------------------------------------------

def _ticker(start=0.0, step=1.0):
    t = {"now": start}

    def clock():
        t["now"] += step
        return t["now"]

    return clock


def test_attributor_folds_only_this_epochs_spans():
    telemetry.configure(enabled=True, clock=_ticker())
    attributor = Attributor(top_n=3)
    with telemetry.trace_span("epoch0_work"):
        pass
    snap = attributor.note_epoch({"epoch": 0, "epoch_wall_sec": 5.0})
    assert snap["epoch"] == 0
    assert "learner/epoch0_work" not in snap or True  # role is pid-...
    assert snap["spans"] == 1 and len(snap["tree"]) == 1
    with telemetry.trace_span("epoch1_work"):
        pass
    snap = attributor.note_epoch({"epoch": 1, "epoch_wall_sec": 5.0})
    # the epoch-0 span is older than the mark: excluded from epoch 1
    assert [k.split("/")[1] for k, _ in snap["top_self"]] == \
        ["epoch1_work"]
    assert attributor.epochs == 2
    assert attributor.last is snap


def test_attributor_is_noop_when_telemetry_off():
    telemetry.configure(enabled=False)
    attributor = Attributor()
    assert attributor.note_epoch({"epoch": 0}) is None
    assert attributor.last is None and attributor.epochs == 0


def test_attribution_rides_flight_recorder_dumps(tmp_path):
    telemetry.configure(enabled=True, log_dir=str(tmp_path),
                        role="learner", primary=True)
    attributor = Attributor()
    telemetry.register_dump_extra(
        "attribution", lambda: attributor.last)
    with telemetry.trace_span("work"):
        pass
    attributor.note_epoch({"epoch": 3, "epoch_wall_sec": 1.0,
                           "untracked_residual_sec": 0.25})
    path = telemetry.dump("test")
    doc = json.loads(open(path).read())
    assert doc["attribution"]["epoch"] == 3
    assert doc["attribution"]["untracked_residual_sec"] == 0.25
    assert "learner/work" in doc["attribution"]["tree"]


def test_register_dump_extra_rejects_reserved_names():
    telemetry.configure(enabled=True)
    with pytest.raises(ValueError, match="reserved"):
        telemetry.register_dump_extra("spans", lambda: 1)


def test_failing_dump_extra_never_blocks_the_dump(tmp_path):
    telemetry.configure(enabled=True, log_dir=str(tmp_path),
                        role="learner", primary=True)

    def bad():
        raise RuntimeError("boom")

    telemetry.register_dump_extra("flaky", bad)
    path = telemetry.dump("test")
    doc = json.loads(open(path).read())
    assert doc["reason"] == "test" and "flaky" not in doc


# -- perf ledger -------------------------------------------------------

def _ledger_with(tmp_path, source, values, key="steps_per_sec"):
    path = str(tmp_path / "ledger.jsonl")
    for i, value in enumerate(values):
        perf_ledger.append_entry(path, source, {key: value}, ts=i)
    return path


def test_ledger_append_from_bench_json_and_check_green(tmp_path, capsys):
    bench = tmp_path / "bench_pipeline.json"
    bench.write_text(json.dumps({
        "metric": "pipeline_e2e_speedup", "value": 1.4,
        "unit": "ratio", "learner_steps_per_sec_e2e_pipelined": 20.0}))
    ledger = str(tmp_path / "ledger.jsonl")
    rc = perf_ledger.main([str(bench), "--ledger", ledger, "--ts", "1"])
    assert rc == 0
    entry = json.loads(open(ledger).read())
    assert entry["source"] == "pipeline_e2e_speedup"
    assert entry["metrics"] == {
        "value": 1.4, "learner_steps_per_sec_e2e_pipelined": 20.0}
    # < min-prior history: trivially green
    assert perf_ledger.main(["--check", "--ledger", ledger]) == 0
    assert "no regressions" in capsys.readouterr().out


def test_ledger_check_fails_on_throughput_regression(tmp_path, capsys):
    ledger = _ledger_with(tmp_path, "bench",
                          [10.0, 10.2, 9.8, 10.1, 5.0])
    rc = perf_ledger.main(["--check", "--ledger", ledger,
                           "--tolerance", "0.25"])
    out = capsys.readouterr().out
    assert rc == 1 and "REGRESS" in out
    # the same drop inside tolerance passes
    ledger2 = _ledger_with(tmp_path / "b", "bench",
                           [10.0, 10.2, 9.8, 10.1, 9.0])
    assert perf_ledger.main(["--check", "--ledger", ledger2]) == 0


def test_ledger_check_directions(tmp_path):
    # lower-is-better: recovery_sec rising fails
    ledger = _ledger_with(tmp_path, "chaos", [1.0, 1.1, 0.9, 3.0],
                          key="chaos_recovery_sec")
    assert perf_ledger.main(["--check", "--ledger", ledger]) == 1
    # higher value of a lower-is-better metric in the PAST is fine
    ledger2 = _ledger_with(tmp_path / "b", "chaos",
                           [3.0, 1.1, 0.9, 1.0],
                           key="chaos_recovery_sec")
    assert perf_ledger.main(["--check", "--ledger", ledger2]) == 0
    # unregistered metric names are archived but never gate
    ledger3 = _ledger_with(tmp_path / "c", "misc",
                           [1.0, 1.0, 1.0, 99.0], key="mystery_number")
    assert perf_ledger.main(["--check", "--ledger", ledger3]) == 0


def test_ledger_summarizes_run_directories(tmp_path):
    run = tmp_path / "run"
    run.mkdir()
    records = []
    for epoch in range(4):
        records.append({
            "epoch": epoch, "steps": 100 * (epoch + 1),
            "epoch_wall_sec": 10.0, "mfu": 0.1 + epoch * 0.01,
            "batch_wait_sec": 2.0, "untracked_residual_sec": 1.0})
    (run / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in records))
    source, metrics = perf_ledger.load_source(str(run))
    assert source == "run"
    # 300 steps over 3 post-first-epoch walls of 10s
    assert metrics["steps_per_sec"] == pytest.approx(10.0)
    assert metrics["mfu"] == pytest.approx(0.115)
    assert metrics["batch_wait_share"] == pytest.approx(0.2)
    assert metrics["residual_share"] == pytest.approx(0.1)


# -- attribution report ------------------------------------------------

def _write_run(tmp_path, shift=0.0):
    run = tmp_path
    run.mkdir(exist_ok=True)
    header = {"meta": {"pid": 1, "role": "learner"}}
    spans = [
        _span("trainer.update", 1.0, 4.0 + shift),
        _span("trainer.batch_wait", 0.2, 0.5),
        _span("gather.recv", 0.5, 1.0, role="gather-0", pid=2),
    ]
    with open(run / "spans-1.jsonl", "w") as f:
        f.write(json.dumps(header) + "\n")
        for rec in spans:
            f.write(json.dumps(rec) + "\n")
    with open(run / "metrics.jsonl", "w") as f:
        for epoch in range(3):
            f.write(json.dumps({
                "epoch": epoch, "epoch_wall_sec": 10.0,
                "mfu": 0.1, "achieved_tflops": 25.0,
                "roofline_verdict": "memory-bound",
                "batch_wait_sec": 2.0,
                "untracked_residual_sec": 0.5}) + "\n")
    return str(run)


def test_attribution_report_builds_and_renders(tmp_path, capsys):
    run = _write_run(tmp_path / "run")
    out = tmp_path / "report.json"
    rc = attribution_report.main([run, "--json", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "top self-time spans" in text
    assert "learner/trainer.update" in text
    doc = json.loads(out.read_text())
    assert doc["epochs"] == 3 and doc["spans"] == 3
    assert doc["medians"]["mfu"] == pytest.approx(0.1)
    assert doc["medians"]["batch_wait_share"] == pytest.approx(0.2)
    assert doc["tree"]["gather-0/gather.recv"]["self_sec"] == \
        pytest.approx(1.0)


def test_attribution_report_baseline_diff(tmp_path, capsys):
    run = _write_run(tmp_path / "run", shift=2.0)
    base = _write_run(tmp_path / "base", shift=0.0)
    rc = attribution_report.main([run, "--baseline", base])
    assert rc == 0
    text = capsys.readouterr().out
    assert "self-time delta vs baseline" in text
    # trainer.update grew by the injected 2s and tops the movers
    assert "+2.0000s" in text
