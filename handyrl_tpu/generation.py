"""Self-play episode generation — the actor-side hot loop.

Produces the framework's episode wire format (capability parity with
/root/reference/handyrl/generation.py): per-step "moment" dicts keyed
by channel then player, bz2-pickled in blocks of ``compress_steps``,
plus the final outcome and the job args that produced the episode.
The moment schema is protocol — the batch maker consumes it.

Two rollout engines share that wire format:

  * ``Generator`` — one episode at a time, one inference per
    participant per step.  Mirrors the reference hot loop
    (/root/reference/handyrl/generation.py:31-73) and remains the
    fallback for heterogeneous-model jobs.
  * ``RolloutPool`` — the production engine: K episodes advance in
    lockstep and every step issues ONE batched ``(K*P)``-row CPU
    forward covering all seats of all episodes.  The reference (and
    ``Generator``) dispatch one batch-1 forward per seat per step,
    which drowns small nets in dispatch overhead; batching across
    seats and episodes amortizes it ~K*P-fold.  Evaluation jobs ride
    the same batch (greedy trained seats vs host-side scripted
    opponents), so eval matches never stall the pool.

Runs in CPU actor processes; ``models`` are TPUModel/RandomModel
instances whose batched ``inference_batch`` is a CPU-jitted forward.
"""

import bz2
import pickle

import numpy as np

from . import telemetry
from .agent import ILLEGAL, RandomAgent, sample_action

MOMENT_KEYS = (
    "observation", "selected_prob", "action_mask", "action",
    "value", "reward", "return",
)


def fill_discounted_returns(moments, players, gamma):
    """Discounted return per player, one vectorized backward pass:
    R[t] = r[t] + gamma * R[t+1] over a (T, P) reward matrix."""
    rewards = np.asarray(
        [[m["reward"][p] or 0.0 for p in players] for m in moments],
        dtype=np.float64)
    acc = np.zeros(len(players))
    for t in range(len(moments) - 1, -1, -1):
        acc = rewards[t] + gamma * acc
        returns = moments[t]["return"]
        for i, p in enumerate(players):
            returns[p] = acc[i]


def pack_episode(moments, outcome, job_args, compress_steps,
                 compress=True):
    """Wire format: job args + step count + outcome + moment blocks.

    Blocks are bz2-compressed pickle on the control plane (the legacy
    socket transport pays per byte); the shm trajectory path passes
    ``compress=False`` for raw pickle blocks — shared-memory bandwidth
    is free and the bz2 CPU cost is the actor loop's.  Consumers sniff
    the stream magic per block (batch.load_block), so the two formats
    mix freely in one replay buffer."""
    def block(lo):
        blob = pickle.dumps(moments[lo: lo + compress_steps])
        return bz2.compress(blob) if compress else blob

    return {
        "args": job_args,
        "steps": len(moments),
        "outcome": outcome,
        "moment": [block(lo)
                   for lo in range(0, len(moments), compress_steps)],
    }


def blank_moment(players):
    return {key: {p: None for p in players} for key in MOMENT_KEYS}


def generation_participants(env, trained_players, observation_flag):
    """Players that run inference this step: everyone on turn, plus
    observers — except trained off-turn players when the config does
    not keep their RNN state warm (``observation`` flag)."""
    on_turn = env.turns()
    watching = []
    for p in env.observers():
        if p in on_turn:
            continue
        if p in trained_players and not observation_flag:
            continue
        watching.append(p)
    return on_turn, watching


def record_action(moment, player, policy, legal):
    """Sample an action from masked ``policy`` and record the behavior
    probability + action mask into the moment (IS bookkeeping)."""
    action, probs = sample_action(policy, legal)
    mask = np.full_like(policy, ILLEGAL)
    mask[legal] = 0.0
    moment["action"][player] = action
    moment["selected_prob"][player] = float(probs[action])
    moment["action_mask"][player] = mask


class Seat:
    """One player's acting state inside a single episode."""

    __slots__ = ("player", "model", "hidden")

    def __init__(self, player, model):
        self.player = player
        self.model = model
        self.hidden = model.init_hidden()

    def think(self, obs):
        """Run inference, carrying the recurrent state forward."""
        outputs = self.model.inference(obs, self.hidden)
        self.hidden = outputs.pop("hidden", None)
        return outputs


class Generator:
    """Plays full self-play episodes one at a time (fallback path)."""

    def __init__(self, env, args):
        self.env = env
        self.args = args

    # -- one step ----------------------------------------------------
    def _step(self, seats, trained_players):
        """Advance the env by one move; returns the recorded moment or
        None if the env reports an error."""
        moment = blank_moment(self.env.players())
        on_turn, watching = generation_participants(
            self.env, trained_players, self.args["observation"])

        for player in list(on_turn) + watching:
            seat = seats[player]
            obs = self.env.observation(player)
            outputs = seat.think(obs)
            moment["observation"][player] = obs

            value = outputs.get("value")
            if value is not None:
                moment["value"][player] = np.ravel(
                    np.asarray(value, np.float32))

            if player in on_turn:
                record_action(moment, player, outputs["policy"],
                              self.env.legal_actions(player))

        if self.env.step(moment["action"]):
            return None

        rewards = self.env.reward()
        for p in self.env.players():
            moment["reward"][p] = rewards.get(p)
        moment["turn"] = on_turn
        return moment

    # -- entry points ------------------------------------------------
    def generate(self, models, args):
        """Play one episode; returns the packed episode, or None when
        the env signals a reset/step failure."""
        if self.env.reset():
            return None
        seats = {p: Seat(p, models[p]) for p in self.env.players()}
        trained_players = args["player"]

        moments = []
        while not self.env.terminal():
            moment = self._step(seats, trained_players)
            if moment is None:
                return None
            moments.append(moment)
        if not moments:
            return None

        fill_discounted_returns(
            moments, self.env.players(), self.args["gamma"])
        return pack_episode(moments, self.env.outcome(), args,
                            self.args["compress_steps"],
                            compress=self.args.get(
                                "episode_compress", True))

    def execute(self, models, args):
        episode = self.generate(models, args)
        if episode is None:
            print("None episode in generation!")
        return episode


# ---------------------------------------------------------------------
# lockstep rollout pool (the production actor engine)
# ---------------------------------------------------------------------

class _Slot:
    """One in-flight job inside the pool."""

    __slots__ = ("job", "mode", "moments", "trained", "agents",
                 "opponent", "on_turn", "parts", "pending", "model",
                 "trace", "t0")

    def __init__(self, job, mode):
        self.job = job
        self.mode = mode            # "g" generation | "e" evaluation
        self.moments = []
        self.trained = list(job["player"])
        self.agents = {}            # eval: host-side opponent agents
        self.opponent = None        # eval: opponent name for the result
        self.on_turn = ()
        self.parts = ()
        self.pending = {}           # player -> obs staged this step
        self.model = None           # eval: the snapshot this match uses
        self.trace = telemetry.maybe_trace()  # sampled episode context
        self.t0 = telemetry.span_begin()      # rollout span start


class RolloutPool:
    """K concurrent episodes advanced in lockstep, one batched forward
    per step.

    All neural seats across all slots share ONE model (the learner's
    newest snapshot — generation jobs always assign the same epoch to
    every trained seat, see Learner._assign_job).  When a job carrying
    a newer snapshot enters a slot mid-flight, the whole pool switches
    to it: the behavior probabilities recorded per step are whatever
    policy actually produced the action, so importance-sampling
    corrections stay exact.  Each finished episode records the epoch
    that actually completed it (``final_model_epoch``) so stats
    attribution stays truthful even for mixed-policy episodes; any
    future league/mixed-snapshot scheduler must not assume the job's
    ``model_id`` label describes every step.

    Recurrent nets keep a stacked hidden state of shape ``(K*P, ...)``;
    rows advance only for the seats that actually observed this step
    (the same semantics as per-seat ``Seat.think``), and a slot's rows
    are zeroed when a new episode enters it.
    """

    def __init__(self, envs, args):
        self.envs = list(envs)
        self.args = args
        self.players = self.envs[0].players()
        self.P = len(self.players)
        self.K = len(self.envs)
        self.N = self.K * self.P
        self.model = None
        self.model_epoch = -1       # epoch label of the installed model
        self.hidden = None
        self.slots = [None] * self.K
        self._free = list(range(self.K))
        self._obs_leaves = None     # flat (N, ...) numpy buffers
        self._obs_treedef = None
        self._opponents = None      # eval opponent pool, resolved once

    def _opponent_pool(self):
        if self._opponents is None:
            from .evaluation import configured_opponents

            self._opponents = configured_opponents(self.args)
        return self._opponents

    # -- admission ----------------------------------------------------
    def has_free_slot(self):
        return bool(self._free)

    @staticmethod
    def accepts(job):
        """Pool-compatible jobs: every neural seat runs one shared
        model.  Generation jobs with mixed snapshots (league play) fall
        back to the sequential Generator."""
        ids = {i for i in job["model_id"].values() if i >= 0}
        return job["role"] in ("g", "e") and len(ids) == 1

    def assign(self, job, models):
        """Enter a job into a free slot; returns the finished-payload
        tuple immediately if the env fails to reset."""
        k = self._free.pop()
        env = self.envs[k]
        slot = _Slot(job, job["role"])
        neural = next(m for m in models.values() if m is not None)
        self._set_model(neural)
        self.model_epoch = max(job["model_id"].values())

        if slot.mode == "e":
            import random as _random

            from .evaluation import build_agent

            # eval matches are pinned to the snapshot they were
            # scheduled with: if the pool later swaps to a newer one,
            # this slot finishes on per-row solo inference (unlike
            # generation, eval results carry no behavior probabilities
            # that could correct for a mid-match policy change)
            slot.model = neural
            slot.opponent = _random.choice(self._opponent_pool())
            for p, m in models.items():
                if m is None:
                    agent = (build_agent(slot.opponent, env)
                             or RandomAgent())
                    slot.agents[p] = agent

        if env.reset():
            self._free.append(k)
            verb = "episode" if slot.mode == "g" else "result"
            print("None episode in generation!" if slot.mode == "g"
                  else "None episode in evaluation!")
            return [(verb, None)]

        for agent in slot.agents.values():
            agent.reset(env)
        self._reset_hidden_rows(k)
        self.slots[k] = slot
        return []

    def _set_model(self, model):
        if model is self.model:
            return
        prev = self.model
        self.model = model
        # keep recurrent state across a params-only swap; rebuild when
        # the hidden structure changes (e.g. RandomModel -> real net).
        # Host-side copies: the pool scatters rows in place.
        if prev is None or not _same_hidden_structure(prev, model):
            import jax

            hidden = model.init_hidden([self.N])
            self.hidden = (None if hidden is None else jax.tree.map(
                lambda a: np.array(a), hidden))

    def _reset_hidden_rows(self, k):
        if self.hidden is None:
            return
        lo, hi = k * self.P, (k + 1) * self.P
        import jax

        for leaf in jax.tree.leaves(self.hidden):
            leaf[lo:hi] = 0

    # -- the lockstep step ---------------------------------------------
    def _write_obs(self, row, obs):
        import jax

        leaves = jax.tree.leaves(obs)
        if self._obs_leaves is None:
            self._obs_treedef = jax.tree.structure(obs)
            self._obs_leaves = [
                np.zeros((self.N,) + np.shape(a), np.asarray(a).dtype)
                for a in leaves
            ]
        for buf, leaf in zip(self._obs_leaves, leaves):
            buf[row] = leaf

    def _gather_rows(self):
        """Collect the (row, slot, player) triples that need inference
        this step and stage their observations into the batch buffer."""
        rows = []
        for k, slot in enumerate(self.slots):
            if slot is None:
                continue
            env = self.envs[k]
            if slot.mode == "g":
                on_turn, watching = generation_participants(
                    env, slot.trained, self.args["observation"])
                parts = list(on_turn) + watching
            else:
                on_turn = env.turns()
                watching = [p for p in env.observers()
                            if p not in on_turn]
                parts = [p for p in slot.trained
                         if p in on_turn
                         or (p in watching and self.args["observation"])]
            slot.on_turn = on_turn
            slot.parts = parts
            slot.pending = {}
            stale = slot.mode == "e" and slot.model is not self.model
            for p in parts:
                row = k * self.P + self.players.index(p)
                obs = env.observation(p)
                slot.pending[p] = obs
                if stale:
                    continue  # pinned snapshot: solo inference instead
                self._write_obs(row, obs)
                rows.append((row, k, p))
        return rows

    def _forward(self, rows):
        import jax

        obs = jax.tree.unflatten(self._obs_treedef, self._obs_leaves)
        if self.hidden is None and getattr(
                self.model, "supports_rows", False):
            # served inference (pipeline.ServedModel): ship only the
            # rows that observed this step — the N-row staging buffer
            # stays host-side and outputs scatter back N-shaped
            idx = np.fromiter((r for r, _, _ in rows), dtype=np.int64)
            outputs = self.model.inference_batch(obs, None, rows=idx)
        else:
            outputs = self.model.inference_batch(obs, self.hidden)
        new_hidden = outputs.pop("hidden", None)
        if self.hidden is not None and new_hidden is not None:
            idx = np.fromiter((r for r, _, _ in rows), dtype=np.int64)
            for old, new in zip(jax.tree.leaves(self.hidden),
                                jax.tree.leaves(new_hidden)):
                old[idx] = np.asarray(new)[idx]
        return outputs

    def _finish(self, k, slot, payload_ok):
        self.slots[k] = None
        self._free.append(k)
        env = self.envs[k]
        self._close_span(slot)
        if slot.mode == "g":
            if not payload_ok or not slot.moments:
                print("None episode in generation!")
                return ("episode", None)
            fill_discounted_returns(
                slot.moments, env.players(), self.args["gamma"])
            episode = pack_episode(
                slot.moments, env.outcome(), slot.job,
                self.args["compress_steps"],
                compress=self.args.get("episode_compress", True))
            # the pool may have swapped to a newer snapshot mid-episode
            # (IS-exact — recorded probs are the acting policy's), so
            # the honest generation-stats label is the epoch that
            # actually finished the episode, not the one that scheduled
            # it.  Consumers fall back to the job label when absent
            # (sequential Generator episodes are single-policy).
            episode["final_model_epoch"] = self.model_epoch
            # telemetry stamps: the learner reduces gen_model_epoch
            # into the per-epoch policy_lag_* metrics, and the trace
            # context lets the exported trace follow this episode
            # worker -> gather -> learner across processes
            episode["gen_model_epoch"] = self.model_epoch
            if slot.trace is not None:
                episode["trace"] = slot.trace
            return ("episode", episode)
        if not payload_ok:
            print("None episode in evaluation!")
            return ("result", None)
        result = {"args": slot.job, "result": env.outcome(),
                  "opponent": slot.opponent}
        if slot.trace is not None:
            result["trace"] = slot.trace
        return ("result", result)

    def _close_span(self, slot):
        """Record the slot's rollout span under its own context."""
        telemetry.set_trace(slot.trace)
        telemetry.span_end("episode.rollout", slot.t0, mode=slot.mode,
                           steps=len(slot.moments))
        telemetry.clear_trace()

    def _advance_generation(self, k, slot, outputs):
        env = self.envs[k]
        moment = blank_moment(env.players())
        for p in slot.parts:
            row = k * self.P + self.players.index(p)
            moment["observation"][p] = slot.pending[p]
            value = outputs.get("value")
            if value is not None:
                moment["value"][p] = np.ravel(
                    np.asarray(value[row], np.float32))
            if p in slot.on_turn:
                record_action(moment, p, np.asarray(outputs["policy"][row]),
                              env.legal_actions(p))
        if env.step(moment["action"]):
            return self._finish(k, slot, payload_ok=False)
        rewards = env.reward()
        for p in env.players():
            moment["reward"][p] = rewards.get(p)
        moment["turn"] = slot.on_turn
        slot.moments.append(moment)
        if env.terminal():
            return self._finish(k, slot, payload_ok=True)
        return None

    def _solo_think(self, row, model, obs):
        """Single-state inference for a pinned eval seat, reading and
        writing its hidden row directly (Seat.think semantics)."""
        import jax

        hrow = (None if self.hidden is None else
                jax.tree.map(lambda leaf: leaf[row], self.hidden))
        out = model.inference(obs, hrow)
        hid = out.pop("hidden", None)
        if self.hidden is not None and hid is not None:
            for leaf, new in zip(jax.tree.leaves(self.hidden),
                                 jax.tree.leaves(hid)):
                leaf[row] = np.asarray(new)
        return out

    def _advance_evaluation(self, k, slot, outputs):
        env = self.envs[k]
        stale = slot.model is not self.model
        policies = {}
        for p in slot.parts:
            row = k * self.P + self.players.index(p)
            if stale:
                policies[p] = self._solo_think(
                    row, slot.model, slot.pending[p])["policy"]
            else:
                policies[p] = np.asarray(outputs["policy"][row])
        actions = {}
        for p in slot.on_turn:
            if p in slot.agents:
                actions[p] = slot.agents[p].action(env, p)
            elif p in policies:
                # trained eval seats play greedily (reference Agent
                # default temperature 0, evaluation.py Evaluator._seat)
                action, _ = sample_action(
                    policies[p], env.legal_actions(p), temperature=0)
                actions[p] = action
        if env.step(actions):
            return self._finish(k, slot, payload_ok=False)
        if env.terminal():
            return self._finish(k, slot, payload_ok=True)
        return None

    def step(self):
        """Advance every in-flight episode by one move.  Returns the
        list of finished ``(verb, payload)`` tuples."""
        if all(slot is None for slot in self.slots):
            return []
        rows = self._gather_rows()
        # rows can be empty with only eval slots whose opponents are on
        # turn (host agents need no inference) — still advance the envs
        outputs = self._forward(rows) if rows else {}
        finished = []
        for k in range(self.K):
            slot = self.slots[k]
            if slot is None:
                continue
            advance = (self._advance_generation if slot.mode == "g"
                       else self._advance_evaluation)
            done = advance(k, slot, outputs)
            if done is not None:
                finished.append(done)
        return finished


def _same_hidden_structure(a, b):
    import jax

    ha = a.init_hidden([1]) if hasattr(a, "init_hidden") else None
    hb = b.init_hidden([1]) if hasattr(b, "init_hidden") else None
    return jax.tree.structure(ha) == jax.tree.structure(hb)
