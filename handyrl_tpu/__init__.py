"""handyrl_tpu — a TPU-native distributed RL framework.

A ground-up JAX/XLA re-design of the capabilities of HandyRL
(reference: /root/reference): an IMPALA-style learner/actor system for
competitive multi-player games, with policy-gradient training and
off-policy corrections (Monte-Carlo, TD(lambda), V-Trace, UPGO).

Design stance (TPU-first, not a port):
  * the learner is a single jitted ``update_step`` — RL targets are
    reverse ``lax.scan``s, the RNN time loop is a ``lax.scan``, and all
    multi-player/turn masking is static-shape mask algebra;
  * device parallelism is a ``jax.sharding.Mesh`` with data-parallel
    batch sharding and XLA-inserted ICI collectives (the reference uses
    single-process ``nn.DataParallel``: /root/reference/handyrl/train.py:341);
  * actors remain CPU processes (games are Python) speaking a
    framed-message control plane, shipping compressed trajectories into
    a host-side replay buffer that feeds a device prefetch queue.
"""

__version__ = "0.1.0"
