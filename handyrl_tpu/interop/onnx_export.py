"""Export flax policy nets to ``.onnx`` by translating their jaxpr.

Capability parity with the reference's
``scripts/make_onnx_model.py`` (torch.onnx.export of the trained net):
the exported artifact runs the policy OUTSIDE the framework — Kaggle
kernels, onnxruntime servers, or this repo's own numpy runner
(onnx_run.py, used by ``--eval model.onnx``).

TPU-native twist: there is no tracer to write — jaxpr IS the traced
graph.  ``jax.make_jaxpr`` flattens the net (params close over as
consts -> ONNX initializers; the DRC recurrence unrolls into pure
conv/elementwise ops with hidden state as explicit graph I/O, so no
ONNX LSTM op is needed), and each primitive maps to standard ONNX
ops.  Convolutions are emitted NCHW with the kernel constant-folded to
OIHW, so the file is conventional for third-party runtimes.

Exports are fixed-batch (default 1 — the actor-side inference shape,
same path the reference's OnnxModel uses for evaluation).
"""

import numpy as np

from .onnx_proto import (
    ATTR_FLOAT,
    ATTR_INT,
    ATTR_INTS,
    ATTR_STRING,
    ATTR_TENSOR,
    DT_BOOL,
    DT_FLOAT,
    DT_INT32,
    DT_INT64,
    encode,
)

_NP_TO_DT = {
    np.dtype(np.float32): DT_FLOAT,
    np.dtype(np.int32): DT_INT32,
    np.dtype(np.int64): DT_INT64,
    np.dtype(np.bool_): DT_BOOL,
}


def numpy_to_tensor(arr: np.ndarray, name: str) -> dict:
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _NP_TO_DT:
        arr = arr.astype(np.float32)
    return {
        "name": name,
        "dims": list(arr.shape),
        "data_type": _NP_TO_DT[arr.dtype],
        "raw_data": arr.tobytes(),
    }


def _attr(name, value):
    if isinstance(value, bool) or isinstance(value, (int, np.integer)):
        return {"name": name, "type": ATTR_INT, "i": int(value)}
    if isinstance(value, float):
        return {"name": name, "type": ATTR_FLOAT, "f": value}
    if isinstance(value, str):
        return {"name": name, "type": ATTR_STRING, "s": value.encode()}
    if isinstance(value, np.ndarray):
        return {"name": name, "type": ATTR_TENSOR,
                "t": numpy_to_tensor(value, name)}
    if isinstance(value, (list, tuple)):
        return {"name": name, "type": ATTR_INTS,
                "ints": [int(v) for v in value]}
    raise TypeError(f"attribute {name}: {type(value)}")


def _value_info(name, shape, elem=DT_FLOAT):
    return {"name": name, "type": {"tensor_type": {
        "elem_type": elem,
        "shape": {"dim": [{"dim_value": int(d)} for d in shape]},
    }}}


class _Builder:
    """Accumulates nodes/initializers while walking a jaxpr."""

    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.env = {}          # jaxpr Var -> tensor name
        self.folded = {}       # jaxpr Var -> numpy const (param leaves)
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers.append(
            numpy_to_tensor(np.asarray(arr), name))
        return name

    def node(self, op, inputs, n_out=1, out=None, **attrs):
        outputs = out if out is not None else [
            self.fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append({
            "op_type": op,
            "input": list(inputs),
            "output": list(outputs),
            "attribute": [_attr(k, v) for k, v in attrs.items()
                          if v is not None],
        })
        return outputs[0] if len(outputs) == 1 else outputs

    def read(self, atom):
        """jaxpr atom -> tensor name (Literals become initializers)."""
        import jax

        if isinstance(atom, jax.extend.core.Literal):
            return self.const(np.asarray(atom.val), "lit")
        return self.env[atom]


_UNARY = {
    "neg": "Neg", "exp": "Exp", "log": "Log", "tanh": "Tanh",
    "logistic": "Sigmoid", "sqrt": "Sqrt", "abs": "Abs",
    "stop_gradient": "Identity", "copy": "Identity",
    "floor": "Floor", "not": "Not",
}

# call-like primitives that are safe to inline as straight-line code.
# lax.scan/while/cond also carry inner jaxprs but have LOOP semantics —
# they must hit the NotImplementedError path, not silent mis-inlining.
_INLINE_CALLS = {
    "pjit", "jit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat", "checkpoint",
    "custom_jvp_call_jaxpr",
}
_BINARY = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow",
    "ge": "GreaterOrEqual", "gt": "Greater",
    "le": "LessOrEqual", "lt": "Less", "eq": "Equal",
    "and": "And", "or": "Or", "xor": "Xor",
}


def _emit_conv(b, eqn, lhs, rhs_atom):
    p = eqn.params
    dn = p["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec = dn.lhs_spec, dn.rhs_spec, dn.out_spec
    if any(d != 1 for d in p["lhs_dilation"]):
        raise NotImplementedError("transposed conv not supported")
    # operand -> NCHW
    perm_in = (lhs_spec[0], lhs_spec[1]) + tuple(lhs_spec[2:])
    x = b.node("Transpose", [lhs], perm=perm_in)
    # kernel -> OIHW; params are consts, so fold the transpose
    import jax

    kperm = (rhs_spec[0], rhs_spec[1]) + tuple(rhs_spec[2:])
    if isinstance(rhs_atom, jax.extend.core.Literal):
        w = b.const(np.transpose(np.asarray(rhs_atom.val), kperm), "w")
    elif rhs_atom in b.folded:
        w = b.const(np.transpose(b.folded[rhs_atom], kperm), "w")
    else:
        w = b.node("Transpose", [b.env[rhs_atom]], perm=kperm)
    pads = list(p["padding"])  # [(lo, hi)] per spatial dim
    conv = b.node(
        "Conv", [x, w],
        strides=list(p["window_strides"]),
        dilations=list(p["rhs_dilation"]),
        group=int(p["feature_group_count"]),
        pads=[lo for lo, _ in pads] + [hi for _, hi in pads],
    )
    # NCHW -> original out layout: out_spec says where (N, C, *s) go
    inv = np.argsort((out_spec[0], out_spec[1]) + tuple(out_spec[2:]))
    return b.node("Transpose", [conv], perm=[int(i) for i in inv])


def _emit_dot(b, eqn, names):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs_av, rhs_av = (v.aval for v in eqn.invars)
    if lb or rb:
        raise NotImplementedError("batched dot_general")
    if (len(lc) != 1 or len(rc) != 1
            or lc[0] != lhs_av.ndim - 1 or rc[0] != 0):
        raise NotImplementedError(
            f"dot_general layout {eqn.params['dimension_numbers']}")
    return b.node("MatMul", names)


def _emit_broadcast(b, eqn, x):
    shape = [int(d) for d in eqn.params["shape"]]
    bdims = eqn.params["broadcast_dimensions"]
    in_shape = eqn.invars[0].aval.shape
    staged = [1] * len(shape)
    for i, d in enumerate(bdims):
        staged[d] = int(in_shape[i])
    r = b.node("Reshape", [x, b.const(np.asarray(staged, np.int64))])
    return b.node("Expand", [r, b.const(np.asarray(shape, np.int64))])


def _emit_eqn(b, eqn):
    import jax

    p = eqn.primitive.name
    names = [b.read(v) for v in eqn.invars]

    # call-like primitives: inline the inner jaxpr
    inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
    if inner is not None and p not in _INLINE_CALLS:
        raise NotImplementedError(
            f"jaxpr primitive {p!r} carries an inner jaxpr with "
            f"non-inline semantics (loops/conditionals); unroll it in "
            f"the model (python loop) to export")
    if inner is not None:
        if hasattr(inner, "jaxpr"):  # ClosedJaxpr
            const_names = [b.const(np.asarray(c), "c")
                           for c in inner.consts]
            inner = inner.jaxpr
        else:
            const_names = []
        for var, cname in zip(inner.constvars, const_names):
            b.env[var] = cname
        for var, name in zip(inner.invars, names):
            b.env[var] = name
        for ieqn in inner.eqns:
            _emit_eqn(b, ieqn)
        for outer_v, inner_v in zip(eqn.outvars, inner.outvars):
            b.env[outer_v] = b.read(inner_v)
        return

    if p in _UNARY:
        out = b.node(_UNARY[p], names)
    elif p in _BINARY:
        out = b.node(_BINARY[p], names)
    elif p == "rsqrt":
        out = b.node("Reciprocal", [b.node("Sqrt", names)])
    elif p == "square":
        out = b.node("Mul", [names[0], names[0]])
    elif p == "is_finite":
        out = b.node("Not", [b.node("Or", [
            b.node("IsNaN", [names[0]]),
            b.node("IsInf", [names[0]]),
        ])])
    elif p == "cbrt":
        out = b.node("Pow", [names[0], b.const(np.float32(1 / 3))])
    elif p == "integer_pow":
        exp = b.const(np.float32(eqn.params["y"]))
        out = b.node("Pow", [names[0], exp])
    elif p == "conv_general_dilated":
        out = _emit_conv(b, eqn, names[0], eqn.invars[1])
    elif p == "dot_general":
        out = _emit_dot(b, eqn, names)
    elif p == "reduce_sum":
        # axes-as-input since opset 13
        axes = b.const(np.asarray(eqn.params["axes"], np.int64))
        out = b.node("ReduceSum", [names[0], axes], keepdims=0)
    elif p in ("reduce_max", "reduce_min"):
        # axes stay an ATTRIBUTE until opset 18; we declare 17
        op = "ReduceMax" if p == "reduce_max" else "ReduceMin"
        out = b.node(op, [names[0]],
                     axes=list(eqn.params["axes"]), keepdims=0)
    elif p == "broadcast_in_dim":
        out = _emit_broadcast(b, eqn, names[0])
    elif p == "reshape":
        shape = b.const(np.asarray(eqn.params["new_sizes"], np.int64))
        out = b.node("Reshape", [names[0], shape])
    elif p == "squeeze":
        shape = b.const(
            np.asarray(eqn.outvars[0].aval.shape, np.int64))
        out = b.node("Reshape", [names[0], shape])
    elif p == "expand_dims":
        shape = b.const(
            np.asarray(eqn.outvars[0].aval.shape, np.int64))
        out = b.node("Reshape", [names[0], shape])
    elif p == "transpose":
        out = b.node("Transpose", names,
                     perm=list(eqn.params["permutation"]))
    elif p == "concatenate":
        out = b.node("Concat", names,
                     axis=int(eqn.params["dimension"]))
    elif p == "slice":
        if eqn.params.get("strides") is None:
            strides = [1] * len(eqn.params["start_indices"])
        else:
            strides = list(eqn.params["strides"])
        out = b.node("Slice", [
            names[0],
            b.const(np.asarray(eqn.params["start_indices"], np.int64)),
            b.const(np.asarray(eqn.params["limit_indices"], np.int64)),
            b.const(np.arange(len(strides), dtype=np.int64)),
            b.const(np.asarray(strides, np.int64)),
        ])
    elif p == "pad":
        cfg = eqn.params["padding_config"]
        if any(i != 0 for _, _, i in cfg) or \
                any(lo < 0 or hi < 0 for lo, hi, _ in cfg):
            raise NotImplementedError(
                "interior/negative padding not supported")
        pads = [lo for lo, _, _ in cfg] + [hi for _, hi, _ in cfg]
        out = b.node("Pad", [
            names[0],
            b.const(np.asarray(pads, np.int64)),
            names[1],  # pad value operand
        ], mode="constant")
    elif p == "convert_element_type":
        dt = _NP_TO_DT.get(np.dtype(eqn.params["new_dtype"]), DT_FLOAT)
        out = b.node("Cast", [names[0]], to=dt)
    elif p == "select_n":
        if len(names) != 3:
            raise NotImplementedError("select_n with >2 cases")
        # select_n(pred, on_false, on_true)
        out = b.node("Where", [names[0], names[2], names[1]])
    elif p == "split":
        sizes = list(eqn.params["sizes"])
        outs = b.node("Split", [
            names[0], b.const(np.asarray(sizes, np.int64))],
            n_out=len(sizes), axis=int(eqn.params["axis"]))
        outs = outs if isinstance(outs, list) else [outs]
        for var, name in zip(eqn.outvars, outs):
            b.env[var] = name
        return
    elif p == "iota":
        # static: fold to an initializer
        shape = eqn.params["shape"]
        dim = eqn.params["dimension"]
        arr = np.broadcast_to(
            np.arange(shape[dim]).reshape(
                [-1 if i == dim else 1 for i in range(len(shape))]),
            shape).astype(eqn.outvars[0].aval.dtype)
        out = b.const(arr, "iota")
    else:
        raise NotImplementedError(
            f"jaxpr primitive {p!r} has no ONNX mapping "
            f"(eqn: {eqn})")
    b.env[eqn.outvars[0]] = out


def export_onnx(model, obs_example, path, batch_size=1):
    """Write ``model`` (a TPUModel) to ``path`` as ONNX.

    ``obs_example`` is one unbatched environment observation (defines
    input shapes).  Hidden state (if the net is recurrent) becomes
    explicit ``hidden_i`` inputs / ``hidden_out_i`` outputs, matching
    the reference's OnnxModel discovery protocol.
    """
    import jax

    params = model.params
    module = model.module
    obs_b = jax.tree.map(
        lambda a: np.broadcast_to(
            np.asarray(a, np.float32), (batch_size,) + np.shape(a)
        ).copy(),
        obs_example)
    hidden = model.init_hidden([batch_size])

    def fn(obs, hidden):
        out = dict(module.apply({"params": params}, obs, hidden))
        hid = out.pop("hidden", None)
        return out, hid

    closed = jax.make_jaxpr(fn)(obs_b, hidden)
    out_shape = jax.eval_shape(fn, obs_b, hidden)
    out_leaves_named = []
    out_dict, out_hidden = out_shape
    # names for flat outputs: dict keys in jax's flatten order (sorted)
    for key in sorted(out_dict):
        n = len(jax.tree.leaves(out_dict[key]))
        if n == 1:
            out_leaves_named.append(key)
        else:
            out_leaves_named.extend(f"{key}_{i}" for i in range(n))
    n_hidden_out = len(jax.tree.leaves(out_hidden))
    out_leaves_named.extend(
        f"hidden_out_{i}" for i in range(n_hidden_out))

    b = _Builder()
    jaxpr = closed.jaxpr
    for var, const in zip(jaxpr.constvars, closed.consts):
        arr = np.asarray(const)
        b.env[var] = b.const(arr, "param")
        b.folded[var] = arr  # lets conv fold kernel transposes

    obs_leaves = jax.tree.leaves(obs_b)
    hidden_leaves = jax.tree.leaves(hidden)
    input_infos = []
    for i, (var, leaf) in enumerate(zip(
            jaxpr.invars, obs_leaves + hidden_leaves)):
        name = (f"input_{i}" if i < len(obs_leaves)
                else f"hidden_{i - len(obs_leaves)}")
        b.env[var] = name
        input_infos.append(_value_info(name, np.shape(leaf)))

    for eqn in jaxpr.eqns:
        _emit_eqn(b, eqn)

    output_infos = []
    for name, var in zip(out_leaves_named, jaxpr.outvars):
        src = b.read(var)
        b.node("Identity", [src], out=[name])
        output_infos.append(_value_info(name, var.aval.shape))

    graph = {
        "name": "handyrl_tpu",
        "node": b.nodes,
        "initializer": b.initializers,
        "input": input_infos,
        "output": output_infos,
    }
    onnx_model = {
        "ir_version": 8,
        "producer_name": "handyrl-tpu",
        "producer_version": "1.0",
        "opset_import": [{"domain": "", "version": 17}],
        "graph": graph,
    }
    with open(path, "wb") as f:
        f.write(encode(onnx_model, "Model"))
    return path
