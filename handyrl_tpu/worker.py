"""Actor-side runtime: workers, gather fan-in, local & remote clusters.

Capability parity with the reference actor plane
(/root/reference/handyrl/worker.py): CPU worker processes run
self-play or evaluation jobs; a small tree of Gather processes batches
their traffic so the learner serves O(gathers) connections instead of
O(workers); remote machines join elastically through a one-shot entry
handshake.

The wire protocol is shared with the learner and is therefore fixed:
request tuples ``(verb, payload)`` with verbs ``args`` / ``model`` /
``episode`` / ``result`` (payload may be a list for batched requests),
job-args dicts ``{role, player, model_id}``, and the two well-known
ports below.  Everything else — model caching, job prefetch, upload
batching — is organized framework-side here.

TPU-native specifics: every child process pins JAX to the CPU backend
(``force_cpu_jax``) — actor inference is a CPU-jitted forward; the TPU
belongs to the learner's update step alone.  Processes are spawned,
not forked, because PJRT clients do not survive fork.

Ports (same numbers as the reference so operational docs carry over):
  9999 — entry: one-shot handshake assigning worker-id blocks
  9998 — worker: persistent gather connections
"""

import copy
import pickle
import queue
import random
import threading
import time
from collections import OrderedDict, deque
from socket import gethostname

from .connection import (
    QueueCommunicator,
    _mp,
    accept_socket_connections,
    force_cpu_jax,
    open_multiprocessing_connections,
    open_socket_connection,
    send_recv,
)

ENTRY_PORT = 9999
WORKER_PORT = 9998

_PEER_GONE = (ConnectionResetError, BrokenPipeError, EOFError, OSError)


class ModelCache:
    """Resolves model ids to actor-side models, fetching snapshots from
    the learner on miss.

    Id conventions (protocol): ``id < 0`` is an empty opponent slot,
    ``id == 0`` is the uniform-random stand-in, positive ids are
    learner epochs.  A small LRU keeps the newest epoch plus recent
    old-epoch opponents (league/past-self play) warm, and when a new
    epoch arrives with the same net structure the previous instance is
    re-pointed at the new params — preserving its compiled inference
    function across epochs instead of re-jitting every 200 episodes.
    """

    CAPACITY = 3  # newest epoch + a couple of league opponents

    def __init__(self, conn, env):
        self._conn = conn
        self._env = env
        self._cache = OrderedDict()  # model_id -> model (LRU order)
        self._newest_id = -1

    def _adopt(self, model):
        """Warm the new epoch's model with the previous newest
        instance's compiled inference function.  Params are passed as
        jit *arguments*, so the trace is weight-independent; the cached
        instance itself is left untouched (it may still serve its own
        epoch in the same resolve call)."""
        prev = self._cache.get(self._newest_id)
        if prev is None or not hasattr(prev, "module"):
            return model
        try:
            if prev.module == model.module:
                model._jitted = prev._jitted
        except Exception:
            pass
        return model

    def _fetch(self, model_id):
        from .models import RandomModel

        blob = send_recv(self._conn, ("model", model_id))
        model = pickle.loads(blob)
        if model_id == 0:
            self._env.reset()
            obs = self._env.observation(self._env.players()[0])
            model = RandomModel(model, obs)
        elif model_id > self._newest_id:
            model = self._adopt(model)
        return model

    def resolve(self, model_ids):
        """Return {model_id: model} covering every id in the list."""
        resolved = {}
        for model_id in set(model_ids):
            if model_id < 0:
                resolved[model_id] = None
                continue
            if model_id in self._cache:
                self._cache.move_to_end(model_id)
                resolved[model_id] = self._cache[model_id]
                continue
            model = self._fetch(model_id)
            self._cache[model_id] = model
            self._newest_id = max(self._newest_id, model_id)
            while len(self._cache) > self.CAPACITY:
                self._cache.popitem(last=False)
            resolved[model_id] = model
        return resolved


class Worker:
    """One actor process: pull jobs, resolve their models, roll out
    episodes and evaluation matches, push the results back.

    With ``lockstep_episodes > 1`` (the default) jobs run through a
    RolloutPool: K episodes advance together and each step issues one
    batched CPU forward across every seat, instead of one batch-1
    dispatch per seat per step.  Jobs the pool cannot take (mixed
    model snapshots) fall back to the sequential path."""

    def __init__(self, args, conn, wid):
        print(f"opened worker {wid}")
        self.worker_id = wid
        self.args = args
        self.conn = conn
        random.seed(args["seed"] + wid)

        from .environment import make_env
        from .evaluation import Evaluator
        from .generation import Generator, RolloutPool

        self.env = make_env({**args["env"], "id": wid})
        self.models = ModelCache(conn, self.env)
        generator = Generator(self.env, self.args)
        evaluator = Evaluator(self.env, self.args)
        # role -> (runner, reply verb): the job protocol's two roles
        self.roles = {
            "g": (generator.execute, "episode"),
            "e": (evaluator.execute, "result"),
        }
        lockstep = int(self.args.get("lockstep_episodes", 1) or 1)
        self.pool = None
        if lockstep > 1:
            # the pool gets its own envs: self.env backs the sequential
            # fallback and the ModelCache (which resets it)
            envs = [make_env({**args["env"], "id": wid})
                    for _ in range(lockstep)]
            self.pool = RolloutPool(envs, self.args)

    def __del__(self):
        print(f"closed worker {self.worker_id}")

    def _resolve(self, job):
        id_by_player = job.get("model_id", {})
        resolved = self.models.resolve(list(id_by_player.values()))
        return {p: resolved[mid] for p, mid in id_by_player.items()}

    def _run_job(self, job):
        models = self._resolve(job)
        runner, reply_verb = self.roles[job["role"]]
        send_recv(self.conn, (reply_verb, runner(models, job)))

    def _run_lockstep(self):
        pool = self.pool
        while True:
            while pool.has_free_slot():
                job = send_recv(self.conn, ("args", None))
                if job is None:
                    # learner is done assigning; finish what's in
                    # flight (the sequential path always ships its
                    # current episode — so does the pool)
                    self._drain_pool()
                    return
                if not pool.accepts(job):
                    self._run_job(job)
                    continue
                for verb, payload in pool.assign(job, self._resolve(job)):
                    send_recv(self.conn, (verb, payload))
            for verb, payload in pool.step():
                send_recv(self.conn, (verb, payload))

    def _drain_pool(self):
        """Step the pool without assigning new jobs until every
        in-flight episode finishes, shipping each one upstream."""
        pool = self.pool
        while any(slot is not None for slot in pool.slots):
            for verb, payload in pool.step():
                send_recv(self.conn, (verb, payload))

    def run(self):
        try:
            if self.pool is not None:
                self._run_lockstep()
                return
            while True:
                job = send_recv(self.conn, ("args", None))
                if job is None:
                    return
                self._run_job(job)
        except _PEER_GONE:
            pass  # learner/gather went away: exit quietly


def _spawn_worker(conn, args, wid):
    force_cpu_jax()
    Worker(args, conn, wid).run()


class Gather(QueueCommunicator):
    """Fan-in proxy between ~16 workers and the learner.

    Three behaviors, one per verb class: job requests are served from a
    prefetched block, model requests from an id-keyed cache, and
    episode/result uploads are acked immediately and shipped upstream
    in batches.  This keeps learner round-trips proportional to the
    number of gathers (capability parity with the reference gather).
    """

    CACHED_VERBS = ("model",)
    CACHE_CAPACITY = 4  # per verb; epochs advance, so old keys go cold
    FLUSH_AGE = 0.5  # seconds an upload may wait for batch-mates

    def __init__(self, args, conn, gather_id):
        print(f"started gather {gather_id}")
        self.gather_id = gather_id
        self.learner_conn = conn
        self.job_queue = deque()
        self.reply_cache = {
            verb: OrderedDict() for verb in self.CACHED_VERBS}
        self.pending_uploads = {}
        self.pending_count = 0
        self.first_pending_t = 0.0

        worker_conns = self._spawn_workers(args, gather_id)
        super().__init__(worker_conns)
        self.block_size = 1 + len(worker_conns) // 4

    @staticmethod
    def _spawn_workers(args, gather_id):
        wcfg = args["worker"]
        n_total, n_gathers = wcfg["num_parallel"], wcfg["num_gathers"]
        count = n_total // n_gathers + int(gather_id < n_total % n_gathers)
        base = wcfg.get("base_worker_id", 0)

        def worker_args(index):
            # interleave ids across gathers so id blocks stay balanced
            return args, base + index * n_gathers + gather_id

        return open_multiprocessing_connections(
            count, _spawn_worker, worker_args)

    def _ask_learner(self, request):
        self.learner_conn.send(request)
        return self.learner_conn.recv()

    def _serve_job(self, conn):
        if not self.job_queue:
            self.job_queue.extend(
                self._ask_learner(("args", [None] * self.block_size)))
        self.send(conn, self.job_queue.popleft())

    def _serve_cached(self, conn, verb, key):
        cache = self.reply_cache[verb]
        if key in cache:
            cache.move_to_end(key)
        else:
            cache[key] = self._ask_learner((verb, key))
            while len(cache) > self.CACHE_CAPACITY:
                cache.popitem(last=False)
        self.send(conn, cache[key])

    def _stage_upload(self, conn, verb, payload):
        self.send(conn, None)  # ack now, ship later
        if self.pending_count == 0:
            self.first_pending_t = time.perf_counter()
        self.pending_uploads.setdefault(verb, []).append(payload)
        self.pending_count += 1
        if self.pending_count >= self.block_size:
            self.flush_uploads()

    def flush_uploads(self):
        for verb, payloads in self.pending_uploads.items():
            self._ask_learner((verb, payloads))
        self.pending_uploads = {}
        self.pending_count = 0

    def _flush_if_stale(self):
        """Age-based flush: at low episode rates (big envs, few
        workers per gather) a finished episode must not sit behind the
        count trigger indefinitely — ship whatever is pending once the
        oldest upload has waited FLUSH_AGE."""
        if (self.pending_count
                and time.perf_counter() - self.first_pending_t
                >= self.FLUSH_AGE):
            self.flush_uploads()

    def run(self):
        while self.connection_count() > 0:
            try:
                conn, (verb, payload) = self.recv(timeout=0.3)
            except queue.Empty:
                self._flush_if_stale()
                continue
            if verb == "args":
                self._serve_job(conn)
            elif verb in self.reply_cache:
                self._serve_cached(conn, verb, payload)
            else:
                self._stage_upload(conn, verb, payload)
            self._flush_if_stale()
        if self.pending_count:
            self.flush_uploads()  # don't drop episodes at shutdown


def gather_loop(args, conn, gather_id):
    force_cpu_jax()
    gather = Gather(args, conn, gather_id)
    try:
        gather.run()
    except _PEER_GONE:
        pass  # learner went away: exit quietly


def _default_num_gathers(num_parallel):
    return 1 + max(0, num_parallel - 1) // 16


class WorkerCluster(QueueCommunicator):
    """Local actor pool: gather processes connected over pipes."""

    def __init__(self, args):
        super().__init__()
        self.args = args

    def run(self):
        wcfg = self.args["worker"]
        wcfg.setdefault(
            "num_gathers", _default_num_gathers(wcfg["num_parallel"]))
        for gather_id in range(wcfg["num_gathers"]):
            ours, theirs = _mp.Pipe(duplex=True)
            # gathers spawn worker children, so they cannot be daemonic;
            # they exit on their own once every worker disconnects
            _mp.Process(
                target=gather_loop, args=(self.args, theirs, gather_id)
            ).start()
            theirs.close()
            self.add_connection(ours)


class WorkerServer(QueueCommunicator):
    """Learner-side acceptor for remote worker machines.

    Two listener threads: the entry port hands out worker-id blocks
    plus the merged config, and the worker port accepts persistent
    gather connections into the communicator — so machines may join at
    any time during training (elastic scale-out)."""

    def __init__(self, args):
        super().__init__()
        self.args = args
        self.total_worker_count = 0

    def _admit(self, conn):
        """Entry handshake: reserve an id block, reply merged config."""
        remote_cfg = conn.recv()
        print(f"accepted connection from {remote_cfg['address']}")
        remote_cfg["base_worker_id"] = self.total_worker_count
        self.total_worker_count += remote_cfg["num_parallel"]
        merged = copy.deepcopy(self.args)
        merged["worker"] = remote_cfg
        conn.send(merged)
        conn.close()

    def _entry_server(self):
        print(f"started entry server {ENTRY_PORT}")
        for conn in accept_socket_connections(port=ENTRY_PORT):
            if conn is not None:
                self._admit(conn)

    def _worker_server(self):
        print(f"started worker server {WORKER_PORT}")
        for conn in accept_socket_connections(port=WORKER_PORT):
            if conn is not None:
                self.add_connection(conn)

    def run(self):
        threading.Thread(target=self._entry_server, daemon=True).start()
        threading.Thread(target=self._worker_server, daemon=True).start()


def entry(worker_args):
    """Remote machine -> learner handshake; returns the merged config."""
    conn = open_socket_connection(worker_args["server_address"], ENTRY_PORT)
    conn.send(worker_args)
    merged = conn.recv()
    conn.close()
    return merged


class RemoteWorkerCluster:
    """Worker-machine runtime: handshake on the entry port, then local
    gathers each dialing the learner's worker port."""

    def __init__(self, args):
        args["address"] = gethostname()
        args.setdefault(
            "num_gathers", _default_num_gathers(args["num_parallel"]))
        self.args = args

    def run(self):
        merged = entry(self.args)
        print(merged)
        from .environment import prepare_env

        prepare_env(merged["env"])
        procs = []
        try:
            for gather_id in range(self.args["num_gathers"]):
                conn = open_socket_connection(
                    self.args["server_address"], WORKER_PORT)
                proc = _mp.Process(
                    target=gather_loop, args=(merged, conn, gather_id))
                proc.start()
                conn.close()
                procs.append(proc)
            while True:
                time.sleep(100)
        finally:
            # also reached on a partial launch failure: gathers are
            # non-daemonic and must not be orphaned
            for proc in procs:
                proc.terminate()


def worker_main(args, argv):
    worker_args = args["worker_args"]
    if len(argv) >= 1:
        worker_args["num_parallel"] = int(argv[0])
        worker_args.pop("num_gathers", None)
    RemoteWorkerCluster(args=worker_args).run()
