"""PR 20 smoke drive: two-epoch TicTacToe train with the perf
attribution layer armed, recorded under runs/pr20_perf_smoke/.

Asserts the acceptance lines directly: every metrics record carries
mfu / achieved_tflops / arithmetic_intensity / roofline_verdict (real
numbers under the perf.* peak overrides — CPU has no DEVICE_PEAKS row)
and an untracked_residual_sec that reconciles epoch_wall_sec EXACTLY
against the profile_*_sec spans.  The status snapshot lands in
status.json with its `perf` section (program registry + last
attribution tree); the run dir then feeds scripts/attribution_report.py
and scripts/perf_ledger.py --check, and the plots (including the new
*_perf.png panel) render via scripts/plot_metrics.py.

A second, telemetry-off leg re-measures the PR 5 overhead budget
(<= 5% on e2e wall time) now that the attributor and residual
accounting ride the epoch path — results in overhead.txt.
"""

import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, REPO)


def build_args(telemetry=True, metrics_path="metrics.jsonl"):
    return {
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "turn_based_training": True,
            "observation": False,
            "gamma": 0.8,
            "forward_steps": 4,
            "burn_in_steps": 0,
            "compress_steps": 4,
            "entropy_regularization": 0.1,
            "entropy_regularization_decay": 0.1,
            "update_episodes": 15,
            "batch_size": 4,
            "minimum_episodes": 10,
            "maximum_episodes": 200,
            "epochs": 2,
            "num_batchers": 1,
            "eval_rate": 0.1,
            "worker": {"num_parallel": 2},
            "lambda": 0.7,
            "policy_target": "VTRACE",
            "value_target": "VTRACE",
            "seed": 1,
            "telemetry": telemetry,
            # CPU has no DEVICE_PEAKS row; the overrides are how a CPU
            # run gets real mfu/roofline numbers (docs/parameters.md)
            "perf": {"peak_tflops": 1.0, "peak_hbm_gbs": 100.0},
            "metrics_path": metrics_path,
        },
        "worker_args": {"num_parallel": 2, "server_address": ""},
    }


def train(args):
    from handyrl_tpu.learner import Learner

    learner = Learner(args)
    learner.run()
    assert learner.model_epoch == 2
    return learner


def overhead_leg():
    """Subprocess leg: same config, telemetry OFF, print wall time."""
    t0 = time.time()
    train(build_args(telemetry=False, metrics_path="metrics_off.jsonl"))
    print(f"OFF_WALL {time.time() - t0:.2f}")


def main():
    os.chdir(HERE)

    t0 = time.time()
    learner = train(build_args())
    on_wall = time.time() - t0

    with open("status.json", "w") as f:
        json.dump(learner._status_snapshot(), f, indent=2,
                  sort_keys=True)

    with open("metrics.jsonl") as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert len(records) == 2, records
    for r in records:
        assert isinstance(r["mfu"], float) and r["mfu"] > 0.0, r
        assert r["achieved_tflops"] > 0.0, r
        assert r["arithmetic_intensity"] > 0.0, r
        assert r["roofline_verdict"] in ("compute-bound",
                                         "memory-bound"), r
        # the residual contract: the record's own rounded values
        # reconcile the epoch wall EXACTLY (to the 1e-6 rounding grain)
        tracked = sum(v for k, v in r.items()
                      if k.startswith("profile_") and k.endswith("_sec"))
        assert abs(r["untracked_residual_sec"]
                   - (r["epoch_wall_sec"] - tracked)) < 1e-6, r

    with open("status.json") as f:
        status = json.load(f)
    perf = status["perf"]
    # the guarded step program (replay_step under the device-replay
    # default) and the pipeline's inference_batch both harvest
    assert any(p["flops"] > 0 for p in perf["programs"].values())
    assert perf["attribution"] is not None
    assert perf["attribution"]["untracked_residual_sec"] is not None

    # -- telemetry-off leg: PR 5 overhead budget re-measure ----------
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--overhead-leg"],
        capture_output=True, text=True, cwd=HERE, check=True)
    off_wall = None
    for line in out.stdout.splitlines():
        if line.startswith("OFF_WALL "):
            off_wall = float(line.split()[1])
    assert off_wall is not None, out.stdout
    delta = (on_wall - off_wall) / off_wall * 100.0
    with open("overhead.txt", "w") as f:
        f.write(
            "Telemetry overhead re-measure with the perf attribution\n"
            "layer armed (acceptance: <= 5% on e2e train wall time —\n"
            "the PR 5 budget now also covers the cost-analysis harvest,\n"
            "the per-epoch roofline reduction, and the attribution\n"
            "tree build).\n\n"
            "Same config (2 epochs TicTacToe, 2 workers), one run each\n"
            "way on the same host:\n\n"
            f"  telemetry: true   {on_wall:.1f} s\n"
            f"  telemetry: false  {off_wall:.1f} s\n\n"
            f"Delta: {delta:+.1f}%\n")
    os.remove("metrics_off.jsonl")

    print("smoke OK:",
          {k: [r[k] for r in records]
           for k in ("mfu", "achieved_tflops", "roofline_verdict",
                     "untracked_residual_sec")},
          f"overhead {delta:+.1f}%")


if __name__ == "__main__":
    if "--overhead-leg" in sys.argv:
        overhead_leg()
    else:
        main()
