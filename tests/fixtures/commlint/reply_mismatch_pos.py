"""Positive: a request/reply verb whose handler can skip the reply —
the sender's blocking recv would wedge forever."""


def send_recv(conn, sdata):
    conn.send(sdata)
    return conn.recv(timeout=5)


def client(conn):
    return send_recv(conn, ("fetch", "key"))


def record(payload):
    pass


def server(hub):
    while True:
        conn, (verb, payload) = hub.recv(timeout=0.3)
        if verb == "fetch":     # handler never replies -> wedge
            record(payload)
            continue
        hub.send(conn, None)
