"""Positive: two thread roots both run _bump, whose unlocked += can
interleave LOAD/ADD/STORE and lose an increment — the inflight-cap
bug class."""

import threading


class Meter:
    def __init__(self):
        self.inflight = 0

    def start(self):
        threading.Thread(target=self._drain, daemon=True).start()
        threading.Thread(target=self._pump, daemon=True).start()

    def _drain(self):
        while True:
            self._bump()

    def _pump(self):
        while True:
            self._bump()

    def _bump(self):
        self.inflight += 1  # runs on both threads, no lock
