"""Inference-time agents for evaluation and match play.

Parity with /root/reference/handyrl/agent.py:13-112: random, rule-based
(delegating to ``env.rule_based_action``), greedy/soft neural agents,
and a mean-ensemble over multiple models.
"""

import random

import numpy as np

from .utils.tree import softmax_np


class RandomAgent:
    def reset(self, env, show=False):
        pass

    def action(self, env, player, show=False):
        return random.choice(env.legal_actions(player))

    def observe(self, env, player, show=False):
        return [0.0]


class RuleBasedAgent(RandomAgent):
    def __init__(self, key=None):
        self.key = key

    def action(self, env, player, show=False):
        if hasattr(env, "rule_based_action"):
            return env.rule_based_action(player, key=self.key)
        return random.choice(env.legal_actions(player))


def print_outputs(env, prob, v):
    if hasattr(env, "print_outputs"):
        env.print_outputs(prob, v)
    else:
        if v is not None:
            print("v = %f" % v)
        if prob is not None:
            print("p = %s" % (prob * 1000).astype(int))


class Agent:
    """Neural agent: argmax at temperature 0, else softmax sampling."""

    def __init__(self, model, temperature=0.0, observation=True):
        self.model = model
        self.hidden = None
        self.temperature = temperature
        self.observation = observation

    def reset(self, env, show=False):
        self.hidden = self.model.init_hidden()

    def plan(self, obs):
        outputs = self.model.inference(obs, self.hidden)
        self.hidden = outputs.pop("hidden", None)
        return outputs

    def action(self, env, player, show=False):
        obs = env.observation(player)
        outputs = self.plan(obs)
        logits = outputs["policy"]
        v = outputs.get("value", None)
        legal = env.legal_actions(player)
        mask = np.ones_like(logits)
        mask[legal] = 0.0
        logits = logits - mask * 1e32

        if show:
            print_outputs(env, softmax_np(logits), v)

        if self.temperature == 0:
            return max(legal, key=lambda a: logits[a])
        probs = softmax_np(logits / self.temperature)
        return random.choices(np.arange(len(logits)), weights=probs)[0]

    def observe(self, env, player, show=False):
        v = None
        if self.observation:
            outputs = self.plan(env.observation(player))
            v = outputs.get("value", None)
            if show:
                print_outputs(env, None, v)
        return v


class EnsembleAgent(Agent):
    def reset(self, env, show=False):
        self.hidden = [model.init_hidden() for model in self.model]

    def plan(self, obs):
        outputs = {}
        for i, model in enumerate(self.model):
            out = model.inference(obs, self.hidden[i])
            for k, v in out.items():
                if k == "hidden":
                    self.hidden[i] = v
                else:
                    outputs.setdefault(k, []).append(v)
        return {k: np.mean(v, axis=0) for k, v in outputs.items()}


class SoftAgent(Agent):
    def __init__(self, model):
        super().__init__(model, temperature=1.0)
