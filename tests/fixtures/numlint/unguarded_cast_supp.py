"""SUPP: the quantization loss is the point (reward sign), with a
reason."""
import numpy as np


def ship(pipe, frame):
    # jaxlint: disable=unguarded-cast -- frames are integral 0..255 upstream, the cast is exact
    q = frame.astype(np.uint8)
    pipe.send(q)
