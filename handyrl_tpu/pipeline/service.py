"""Batched inference service: the learner-side half of the pipeline.

One server thread owns a snapshot of the model and answers obs->action
requests from every attached rollout worker: requests accumulate
across workers inside a **wait-or-timeout batching window**
(``pipeline.batch_window`` seconds after the first pending request, or
until ``pipeline.max_batch`` rows are staged, whichever first), then
ONE jitted ``inference_batch`` forward covers all of them and replies
scatter back over each worker's reply ring.  This replaces the
per-worker ``ModelWrapper.inference`` hot path (Sebulba, Podracer
arXiv:2104.06272; SEED-style centralized inference, IMPALA) — actor
processes become env-stepping loops that enqueue observations and
block on actions.

Snapshot **hot swap**: the learner hands every new epoch's model to
``set_model``; the loop adopts it between batches, re-pointing the
compiled forward at the new params (the trace is weight-independent,
so no recompile) — in-flight requests are never dropped, they are
simply answered by whichever snapshot is installed when their batch
dispatches (importance corrections stay exact: workers record the
behavior probabilities the reply actually carried).

Batch shapes bucket to powers of two (floor 8, ceiling ``max_batch``)
so XLA compiles a handful of variants instead of one per request mix.

Liveness is a heartbeat stamp on a shared ``ShmBoard``: workers watch
its age and fall back to local CPU inference when the service goes
silent (death is a supervised, chaos-injectable fault — the learner
respawns the thread and workers return on their own once the beat
resumes).

Telemetry: every dispatch records an ``infer.batch`` span (rows,
window wait), and ``epoch_stats`` reduces the epoch's dispatches into
``infer_batch_size_{mean,p95}`` / ``infer_queue_wait_sec`` /
``shm_ring_full_count`` for metrics.jsonl (docs/observability.md).

**Two planes, one window** (docs/serving.md): besides the shm rings,
``submit`` queues NETWORK-plane requests (the serving frontend's
handler threads call it) into the same batching window — a remote
client's rows and a colocated worker's rows ride one bucket-padded
jitted forward.  A network request may carry an **epoch pin**:
``_routed`` resolves it through ``model_resolver`` (set by the
learner) so league/opponent-pool snapshots are first-class serving
targets — pinned seats get the snapshot they asked for instead of an
error or the live model, and since params are jit *arguments* a routed
snapshot shares the live model's compiled forward (no recompile).

**GSPMD dispatch** (ROADMAP item 2): with a ``mesh`` the service owns
ONE jitted forward built with ``in_shardings``/``out_shardings`` from
:func:`parallel.mesh.inference_shardings` — params laid out by the
learner's tp/fsdp rules (nets too big for one chip become servable),
the observation batch split over ``dp`` rows, outputs scattered back
on ``dp``.  Params stay jit *arguments*: each snapshot (live or
routed) is ``device_put`` onto the param shardings ONCE and cached on
the model object, so hot-swap and multi-model routing never pay a
per-request reshard.  The dispatch rides the same guard contract as
the update step: a :class:`analysis.guards.ShardingContractGuard`
counts resharding copies (``infer_resharding_copies`` in
metrics.jsonl, steady state 0) and a RetraceGuard counts compiles
(``infer_compiles`` — exactly one per batch-bucket geometry, however
many snapshots serve through it).  A single-device mesh (or no mesh)
collapses to the unsharded layout bit-identically; batch buckets stay
powers of two with a floor >= dp so every dispatch divides the data
axis.
"""

import threading
import time
from collections import deque

from .. import telemetry
from .shm import (
    ShmBoard,
    ShmRing,
    dumps,
    loads_view,
    unpack_request,
)


class _Client:
    """One attached worker: its three rings + request schema."""

    __slots__ = ("cid", "req", "rsp", "traj", "leaf_specs", "example",
                 "rows_max", "treedef", "req_stuck_since",
                 "traj_stuck_since", "last_seen", "drop_warned")

    def __init__(self, cid, req, rsp, traj, leaf_specs, example,
                 rows_max):
        self.cid = cid
        self.req = req
        self.rsp = rsp
        self.traj = traj
        self.leaf_specs = [(tuple(s), str(d)) for s, d in leaf_specs]
        self.example = example
        self.rows_max = rows_max
        self.treedef = None          # resolved lazily (jax import)
        self.req_stuck_since = None  # torn-write reclaim bookkeeping
        self.traj_stuck_since = None
        self.last_seen = 0.0         # last request/trajectory activity
        self.drop_warned = False     # reply-drop warning, once per client

    def deliver(self, seq, epoch, part) -> bool:
        """Hand one answered request back over the reply ring.  The
        network-plane seat (serving frontend) implements the same
        method by waking its handler thread — dispatch is polymorphic
        over the two planes."""
        if part is None:
            return True  # shm requests are never epoch-pinned
        return self.rsp.push(dumps((seq, epoch, part)))


def _bucket(n, cap, floor=8):
    """Pad target for an n-row batch: next power of two, floor
    ``floor`` (8, or the mesh dp size when larger), ceiling ``cap`` —
    a handful of compiled shapes total, every one divisible by dp."""
    b = floor
    while b < n:
        b <<= 1
    return min(b, cap)


class InferenceService:
    """The batched inference server (one per learner process).

    Thread contract: ``attach``/``set_model``/``inject_kill``/``stats``
    may be called from the learner's server thread; the batching loop
    runs on the service's own thread; ``drain_trajectories`` belongs to
    the learner server thread (it is the trajectory rings' single
    consumer).  ``clock``/``sleep`` are injectable so the batching
    window is unit-testable without wall time.
    """

    TORN_GRACE = 30.0  # seconds a mid-write slot may stall before reclaim
    # a client silent on BOTH rings this long is presumed dead (its
    # worker crashed or degraded to pure-local) and its rings are
    # reclaimed; a live worker that gets reaped by mistake degrades
    # itself to local inference on the next reply timeout — degraded,
    # never wrong
    CLIENT_IDLE_REAP = 600.0
    GRAVE_GRACE = 10.0  # close only after in-flight snapshots expire

    def __init__(self, model, cfg, epoch=0, clock=time.monotonic,
                 sleep=time.sleep, chaos=None, mesh=None, fsdp=False,
                 max_reshard=0):
        import random

        from ..analysis.guards import RetraceGuard, ShardingContractGuard
        from ..resilience.chaos import maybe_chaos_board

        self.cfg = cfg
        # GSPMD dispatch (module docstring): the learner passes its
        # training mesh so one sharded program serves all planes.  The
        # pow2 bucket floor must divide by dp so every dispatch splits
        # the data axis evenly — a dp the buckets cannot honor disarms
        # the mesh LOUDLY (unsharded dispatch, never a trace error)
        self._mesh = None
        self._fsdp = bool(fsdp)
        self._bucket_floor = 8
        if mesh is not None:
            dp = int(mesh.shape["dp"]) or 1
            floor = self._bucket_floor
            if dp > floor and dp & (dp - 1) == 0:
                floor = dp  # pow2 dp above the floor: raise the floor
            # every bucket value the dispatch can produce — the pow2
            # ladder from the floor, clamped at max_batch — must
            # divide by dp (oversized chunks pad to a full pow2)
            if (floor % dp == 0 and int(cfg.max_batch) % dp == 0
                    and floor <= int(cfg.max_batch)):
                self._mesh = mesh
                self._bucket_floor = floor
            else:
                print(f"WARNING: inference mesh disarmed: dp={dp} "
                      f"does not divide the pow2 batch buckets "
                      f"(floor {floor}, max_batch {cfg.max_batch}); "
                      f"inference dispatch runs unsharded")
        # guard contract, same as the update step's: compiles counted
        # per abstract geometry (one per batch bucket, NOT per
        # snapshot), resharding copies at the call boundary budgeted
        # at copies == 0 steady state (max_reshard > 0 hard-asserts)
        self.retrace_guard = RetraceGuard(name="inference_batch")
        self.shard_guard = ShardingContractGuard(
            max_copies=int(max_reshard or 0), name="inference_batch")
        self._fwd = None           # the service-owned guarded jit
        self._fwd_module = None    # the module it was traced for
        self._infer_sh = None      # InferenceShardings when mesh-armed
        self.clock = clock
        self.sleep = sleep
        self._lock = threading.Lock()
        self._clients = {}
        self._next_cid = 0
        self._model = model
        self._epoch = int(epoch)
        self._pending_model = None
        # shm chaos (resilience.ChaosRing/ChaosBoard): this side
        # produces replies and consumes requests/trajectories, and its
        # heartbeat can be withheld/backdated — all seeded off the one
        # chaos RNG discipline so drills replay exactly
        self._chaos = chaos if (chaos is not None
                                and (chaos.shm_faults_enabled
                                     or chaos.shm_beat_faults_enabled)
                                ) else None
        self._chaos_rng = (
            random.Random((chaos.seed << 20) ^ 0xB0A2)
            if self._chaos is not None else None)
        self.board = maybe_chaos_board(
            ShmBoard.create(), self._chaos, rng=self._chaos_rng)
        self._thread = None
        self._stop = False
        self._kill = False           # chaos: die WITHOUT a parting beat
        # network plane (handyrl_tpu.serving): frontend handler
        # threads queue requests here via submit(); _collect drains
        # them into the same batching window as the shm rings.  The
        # queue belongs to this OBJECT, not the loop thread, so
        # requests queued across a chaos kill are served by the
        # respawned incarnation instead of dying with the thread
        self._net_pending = deque()
        # epoch pin -> model, set by the learner (multi-model routing:
        # league/opponent-pool snapshots as serving targets); None
        # makes every non-live pin unroutable (typed error upstream)
        self.model_resolver = None
        self.net_requests = 0        # cumulative network-plane frames
        # counters — epoch accumulators reset by epoch_stats()
        self._batch_rows = []
        self._queue_wait = 0.0
        self._requests_epoch = 0
        self._warm = []              # client ids awaiting a jit warmup
        self.batches = 0             # cumulative dispatches
        self.requests = 0            # cumulative request frames served
        self.rows_served = 0         # cumulative obs rows answered
        self.reclaimed = 0           # torn slots skipped (dead writers)
        self.corrupt = 0             # undecodable slots skipped
        self.reply_drops = 0         # replies refused by a full/small ring
        self.reaped = 0              # idle clients reclaimed
        self._grave = []             # (deadline, client) pending close

    # -- control-plane face (learner server thread) --------------------
    def attach(self, spec):
        """Allocate a client slot + rings for one worker's handshake
        (verb ``"shm"``); returns the attach descriptor the worker
        maps, or raises on a malformed spec (the learner's handler
        answers None for refusals — remote peers, shutdown)."""
        leaf_specs = spec["leaves"]
        rows_max = max(1, int(spec.get("rows_max", 1)))
        import numpy as np

        row_bytes = sum(
            int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
            for shape, dtype in leaf_specs)
        need = 16 + 2 * rows_max * max(1, row_bytes)
        slot = max(int(self.cfg.slot_bytes), need)
        from ..resilience.chaos import maybe_chaos_ring

        with self._lock:
            cid = self._next_cid
            self._next_cid += 1

            def ring(*a):
                # service-side chaos endpoint: reply pushes can tear/
                # truncate/refuse, request/trajectory pops can stall
                return maybe_chaos_ring(
                    ShmRing.create(*a), self._chaos, rng=self._chaos_rng)

            client = _Client(
                cid,
                req=ring(self.cfg.ring_slots, slot),
                rsp=ring(self.cfg.ring_slots, slot),
                traj=ring(self.cfg.traj_slots,
                          int(self.cfg.traj_slot_mb) << 20),
                leaf_specs=leaf_specs,
                example=spec["example"],
                rows_max=rows_max,
            )
            client.last_seen = self.clock()
            self._clients[cid] = client
            # warm this schema's buckets from the SERVICE thread (the
            # handshake/model-fetch slack), so the first real request
            # is not the one paying the jit compile — a compile longer
            # than fallback_after would bounce it to local fallback
            self._warm.append(cid)
        return {
            "client": cid,
            "board": self.board.name,
            "req": client.req.descriptor(),
            "rsp": client.rsp.descriptor(),
            "traj": client.traj.descriptor(),
        }

    def set_model(self, model, epoch):
        """Hot-swap the serving snapshot; adopted between batches, so
        no in-flight request is ever dropped."""
        with self._lock:
            self._pending_model = (model, int(epoch))

    # -- network plane (serving frontend handler threads) --------------
    def submit(self, seat, seq, rows, leaves, epoch=None) -> bool:
        """Queue one network-plane request into the batching window.
        ``seat`` is the frontend's client duck type (``example`` /
        ``treedef`` / ``deliver``); ``epoch`` pins the request to a
        specific snapshot (None = the live model).  False = the
        service is shut down for good (the frontend sheds with a typed
        reply).  A merely-dead (killed, pre-respawn) service still
        accepts: the queue belongs to the object, so these requests
        are served by the respawned incarnation — the frontend's
        admission check (``service.alive``) is what sheds NEW arrivals
        during the gap."""
        if self._stop:
            return False
        with self._lock:
            self._net_pending.append(
                (seat, seq, int(rows), leaves,
                 None if epoch is None else int(epoch)))
        return True

    def inject_kill(self):
        """Chaos: the loop exits without a parting beat — exactly what
        a SIGKILLed dedicated server process would look like to the
        workers (stale board) and the learner (dead thread)."""
        self._kill = True

    @property
    def alive(self):
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        self._kill = False
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="infer-service")
        self._thread.start()

    def respawn(self):
        """Relaunch after a death: same rings, same clients — state
        lives in shared memory, so workers resume on their own once
        the beat returns (a fresh generation stamp says it's a new
        incarnation)."""
        self.board.bump_generation()
        self.start()

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=5)

    def close(self):
        self.stop()
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            # the graveyard holds reaped clients whose GRAVE_GRACE has
            # not expired; final teardown must not strand their rings
            # (3 shm segments each) waiting for a reaper that is gone
            clients.extend(c for _due, c in self._grave)
            self._grave = []
        for c in clients:
            c.req.close()
            c.rsp.close()
            c.traj.close()
        self.board.close()

    # -- metrics -------------------------------------------------------
    def ring_full_count(self):
        """Cumulative push refusals across every ring of every client,
        read straight from the shm headers — includes the counts the
        WORKERS' producer sides maintained (req/traj rings), with no
        control-plane reporting needed."""
        total = 0
        with self._lock:
            clients = list(self._clients.values())
        for c in clients:
            total += (c.req.full_count + c.rsp.full_count
                      + c.traj.full_count)
        return total

    def torn_slot_count(self):
        """Cumulative torn/corrupt slots skipped across every ring of
        every client — the consumer-side skip counters live in the shm
        headers, so this covers the WORKERS' reply-ring skips too (no
        control-plane reporting needed), plus this side's reclaims."""
        total = 0
        with self._lock:
            clients = list(self._clients.values())
        for c in clients:
            total += (c.req.torn_count + c.rsp.torn_count
                      + c.traj.torn_count)
        return total

    def epoch_stats(self):
        """Per-epoch reduction for metrics.jsonl; resets the epoch
        accumulators.  Keys are the docs/observability.md contract."""
        with self._lock:
            rows = self._batch_rows
            wait = self._queue_wait
            requests = self._requests_epoch
            self._batch_rows = []
            self._queue_wait = 0.0
            self._requests_epoch = 0
        out = {
            "infer_batches": len(rows),
            "infer_requests": requests,
            # the dispatch's guard contract (module docstring): copies
            # is a per-epoch delta whose steady state is 0 — any
            # positive count means a snapshot landed on the wrong
            # layout and every forward pays a silent copy; compiles is
            # cumulative and stops growing once every bucket geometry
            # has compiled (snapshots never add one)
            "infer_resharding_copies": self.shard_guard.snapshot(),
            "infer_compiles": self.retrace_guard.compiles,
            "shm_ring_full_count": self.ring_full_count(),
            # torn/corrupt slots skipped, cumulative, read from the
            # shm headers (covers both endpoints' skips).  Steady
            # state is flat at 0; a climbing line means producers are
            # dying mid-write (or payloads are corrupting) faster
            # than the fleet's churn explains
            "shm_torn_slots": self.torn_slot_count(),
        }
        if rows:
            srt = sorted(rows)
            out["infer_batch_size_mean"] = round(
                sum(rows) / len(rows), 2)
            out["infer_batch_size_p95"] = srt[
                min(len(srt) - 1, int(0.95 * len(srt)))]
            out["infer_queue_wait_sec"] = round(wait / len(rows), 6)
        return out

    def stats(self):
        """Cumulative snapshot (status endpoint)."""
        with self._lock:
            n = len(self._clients)
        return {
            "clients": n,
            "epoch": self._epoch,
            "alive": self.alive,
            "generation": self.board.generation,
            "batches": self.batches,
            "requests": self.requests,
            "net_requests": self.net_requests,
            "rows_served": self.rows_served,
            "shm_ring_full_count": self.ring_full_count(),
            "shm_torn_slots": self.torn_slot_count(),
            "torn_reclaimed": self.reclaimed,
            "corrupt_slots": self.corrupt,
            "reply_drops": self.reply_drops,
            "clients_reaped": self.reaped,
            "infer_resharding_copies": self.shard_guard.copies,
            "infer_compiles": self.retrace_guard.compiles,
            "mesh_devices": (int(self._mesh.size)
                             if self._mesh is not None else 1),
        }

    # -- trajectory intake (learner server thread) ---------------------
    def drain_trajectories(self, max_episodes=512):
        """Pop finished episodes off every client's trajectory ring —
        the learner feeds them straight into episode intake.  This
        thread is those rings' single consumer."""
        episodes = []
        now = self.clock()
        with self._lock:
            clients = list(self._clients.values())
        for c in clients:
            while len(episodes) < max_episodes:
                try:
                    ep = c.traj.pop(loads=loads_view)
                except Exception as exc:
                    self._skip_corrupt(c.traj, c.cid, "trajectory", exc)
                    continue
                if ep is None:
                    c.traj_stuck_since = self._maybe_reclaim(
                        c.traj, c.traj_stuck_since, now,
                        cid=c.cid, kind="trajectory")
                    break
                c.traj_stuck_since = None
                c.last_seen = now
                episodes.append(ep)
        return episodes

    def _skip_corrupt(self, ring, cid, kind, exc):
        """A slot whose seqlock stamp is complete but whose payload
        would not decode (truncation, bit rot): skip it LOUDLY — the
        slot is counted torn in the shm header and the ring flows
        again.  Crashing here would take the learner's server loop
        (and every client) down over one bad frame."""
        if ring.skip_one():
            # bumped from both the learner's drain thread and the
            # service loop — unlocked += on both would lose counts
            with self._lock:
                self.corrupt += 1
            print(f"WARNING: corrupt {kind} slot from client {cid} "
                  f"skipped ({exc!r})")

    def _maybe_reclaim(self, ring, stuck_since, now, cid=-1,
                       kind="request"):
        """Mid-write slot watch: a slot odd-stamped for longer than
        TORN_GRACE means its writer died mid-frame (a live writer
        finishes in microseconds) — skip it LOUDLY so the ring flows
        again.  Returns the updated stuck-since stamp."""
        if not ring.pending() or ring.readable():
            return None
        if stuck_since is None:
            return now
        if now - stuck_since >= self.TORN_GRACE:
            if ring.skip_torn():
                # same two-thread caller set as _skip_corrupt above
                with self._lock:
                    self.reclaimed += 1
                print(f"WARNING: torn {kind} slot from client {cid} "
                      f"reclaimed (writer dead mid-RESERVE-THEN-FILL, "
                      f"stalled {now - stuck_since:.0f}s); the ring "
                      f"flows again")
            return None
        return stuck_since

    # -- the batching loop --------------------------------------------
    def _adopt_model(self):
        with self._lock:
            pending = self._pending_model
            self._pending_model = None
        if pending is None:
            return
        model, epoch = pending
        # the compiled forward survives the swap in _ensure_forward
        # (the service-owned jit is cached by module EQUALITY and
        # params are jit arguments); duck models without a module
        # carry their own inference_batch and need no adoption
        self._model = model
        self._epoch = epoch

    def _obs_tree(self, client, leaves):
        import jax

        if client.treedef is None:
            client.treedef = jax.tree.structure(client.example)
        return jax.tree.unflatten(client.treedef, leaves)

    # -- the guarded (and, with a mesh, GSPMD) forward -----------------
    def _ensure_forward(self, model):
        """The service-owned jitted ``inference_batch``, built once per
        module and shared by every snapshot (params are jit arguments:
        hot-swap and routed dispatch reuse the trace).  None for duck
        models with no jittable ``module`` (they keep their own
        ``inference_batch``)."""
        module = getattr(model, "module", None)
        if module is None or not hasattr(module, "apply") \
                or getattr(model, "params", None) is None:
            return None  # RandomModel/stub ducks keep their own path
        if self._fwd is not None:
            prev = self._fwd_module
            try:
                if prev is module or prev == module:
                    return self._fwd
            except Exception:
                pass
        import jax

        def apply(params, obs):
            return module.apply({"params": params}, obs, None)

        if self._mesh is not None:
            from ..parallel.mesh import inference_shardings

            self._infer_sh = inference_shardings(
                self._mesh, model.params, fsdp=self._fsdp)
            fwd = jax.jit(apply,
                          in_shardings=(self._infer_sh.params,
                                        self._infer_sh.obs),
                          out_shardings=self._infer_sh.out)
        else:
            self._infer_sh = None
            fwd = jax.jit(apply)
        self._fwd = self.retrace_guard.wrap(self.shard_guard.wrap(fwd))
        self._fwd_module = module
        return self._fwd

    def _placed_params(self, model):
        """This snapshot's params on the inference param shardings —
        ``device_put`` ONCE per snapshot (live or routed), cached on
        the model object so the learner's LRU stores sharded pytrees
        and no dispatch ever pays a per-request reshard.  The cache is
        KEYED by the sharding set it was placed with: a snapshot that
        crosses services with different meshes (tests, dry runs)
        re-places instead of dispatching params committed to another
        mesh's layout."""
        if self._infer_sh is None:
            return model.params
        cached = getattr(model, "_infer_placed", None)
        if cached is not None and cached[0] is self._infer_sh:
            return cached[1]
        import jax

        placed = jax.device_put(model.params, self._infer_sh.params)
        try:
            model._infer_placed = (self._infer_sh, placed)
        except Exception:
            pass
        return placed

    def _forward(self, model, obs):
        """One batched forward: numpy leaves in, numpy dict out (the
        ``inference_batch`` contract), through the guarded jit."""
        fwd = self._ensure_forward(model)
        if fwd is None:
            return model.inference_batch(obs, None)
        import jax
        import numpy as np

        out = fwd(self._placed_params(model), obs)
        return jax.tree.map(np.asarray, out)

    def _collect(self, pending, now):
        """One sweep over every request ring plus the network-plane
        queue; appends (client, seq, rows, leaves, epoch_pin) tuples.
        Returns rows collected this sweep."""
        got = 0
        with self._lock:
            clients = list(self._clients.values())
            net = list(self._net_pending)
            self._net_pending.clear()
        for item in net:
            pending.append(item)
            got += item[2]
            self.net_requests += 1
        for c in clients:
            while True:
                try:
                    item = c.req.pop(
                        loads=lambda v, c=c: unpack_request(
                            v, c.leaf_specs))
                except Exception as exc:
                    self._skip_corrupt(c.req, c.cid, "request", exc)
                    continue
                if item is None:
                    c.req_stuck_since = self._maybe_reclaim(
                        c.req, c.req_stuck_since, now,
                        cid=c.cid, kind="request")
                    break
                c.req_stuck_since = None
                c.last_seen = self.clock()
                seq, rows, leaves = item
                pending.append((c, seq, rows, leaves, None))
                got += rows
        return got

    def step(self):
        """One batching-window pass: collect, wait-or-timeout, forward,
        reply.  Returns True when a batch dispatched (the loop idles
        briefly otherwise).  Synchronous and clock-injected: unit
        tests drive it directly, no thread."""
        pending = []
        total = self._collect(pending, self.clock())
        if not pending:
            return False
        t_first = self.clock()
        # wait-or-timeout: give batch-mates from other workers
        # batch_window seconds to arrive, unless the batch is full
        deadline = t_first + self.cfg.batch_window
        while total < self.cfg.max_batch:
            now = self.clock()
            if now >= deadline:
                break
            self.sleep(min(2e-4, deadline - now))
            total += self._collect(pending, self.clock())
        self._dispatch(pending, self.clock() - t_first)
        return True

    def _routed(self, pin):
        """(model, epoch) for one dispatch group.  None pins — and
        pins naming the live snapshot — serve the installed model;
        other pins resolve through ``model_resolver`` (multi-model
        routing: league/opponent-pool snapshots as first-class
        serving targets).  (None, pin) = unroutable, answered as a
        typed unavailable upstream."""
        if pin is None or int(pin) == self._epoch:
            return self._model, self._epoch
        if self.model_resolver is None:
            return None, int(pin)
        try:
            model = self.model_resolver(int(pin))
        except Exception as exc:  # a bad pin costs that request only
            print(f"WARNING: snapshot resolver failed for epoch "
                  f"{pin} ({exc!r})")
            model = None
        return model, int(pin)

    def _dispatch(self, pending, waited):
        import numpy as np

        self._adopt_model()
        # group by epoch pin: the unpinned/live group (the common
        # case — ALL shm traffic plus unpinned network requests) rides
        # one bucket-padded forward; each pinned group dispatches with
        # its routed snapshot's params through the SAME compiled
        # forward (params are jit arguments — no recompile).  A pin
        # naming the LIVE epoch normalizes into the unpinned group —
        # splitting identical-params traffic into two forwards would
        # re-pay exactly the per-dispatch overhead the shared window
        # exists to amortize
        groups = {}
        for item in pending:
            pin = item[4]
            if pin is not None and int(pin) == self._epoch:
                pin = None
            groups.setdefault(pin, []).append(item)
        for pin, items in groups.items():
            model, epoch = self._routed(pin)
            if model is None:
                # unroutable pin (pruned/never-committed epoch, no
                # resolver): typed unavailable, not a silent timeout
                for seat, seq, _n, _leaves, _pin in items:
                    seat.deliver(seq, None, None)
                continue
            # one forward per max_batch chunk (normally exactly one)
            i = 0
            while i < len(items):
                chunk, rows = [], 0
                while i < len(items) and (
                        rows + items[i][2] <= self.cfg.max_batch
                        or not chunk):
                    chunk.append(items[i])
                    rows += items[i][2]
                    i += 1
                t0 = telemetry.span_begin()
                cap = max(rows, self.cfg.max_batch)
                if self._mesh is not None and rows > self.cfg.max_batch:
                    # oversized chunk under a mesh: pad to the FULL
                    # pow2 instead of clamping at the raw row count,
                    # so the bucket keeps dividing the dp axis
                    cap = 1 << (rows - 1).bit_length()
                bucket = _bucket(rows, cap, self._bucket_floor)
                leaves = [np.concatenate(parts, axis=0) for parts in zip(
                    *[leaves for _, _, _, leaves, _ in chunk])]
                if bucket > rows:
                    leaves = [np.concatenate(
                        [leaf, np.zeros((bucket - rows,) + leaf.shape[1:],
                                        leaf.dtype)], axis=0)
                        for leaf in leaves]
                obs = self._obs_tree(chunk[0][0], leaves)
                outputs = self._forward(model, obs)
                outputs.pop("hidden", None)
                lo = 0
                for client, seq, n, _leaves, _pin in chunk:
                    part = {k: np.asarray(v[lo:lo + n])
                            for k, v in outputs.items()}
                    lo += n
                    if not client.deliver(seq, epoch, part):
                        # full or too small for the OUTPUT pickle (reply
                        # slots are sized from the obs schema): the worker
                        # will time out, count it, and degrade itself to
                        # local inference — say why, once per client
                        self.reply_drops += 1
                        if not client.drop_warned:
                            client.drop_warned = True
                            print(f"WARNING: inference reply to client "
                                  f"{client.cid} dropped (reply ring full "
                                  f"or slot smaller than the output "
                                  f"frame); that worker will degrade to "
                                  f"local inference")
                self.batches += 1
                self.requests += len(chunk)
                self.rows_served += rows
                with self._lock:
                    self._batch_rows.append(rows)
                    self._queue_wait += waited
                    self._requests_epoch += len(chunk)
                telemetry.span_end("infer.batch", t0, rows=rows,
                                   wait=round(waited, 6), epoch=epoch)

    def _warm_next(self):
        """Compile the forward for one pending client's likely batch
        buckets (min bucket + its lockstep rows_max) with zero
        observations.  Runs on the service thread between batches."""
        import numpy as np

        with self._lock:
            if not self._warm:
                return False
            # peek, don't pop: warm_pending must stay truthful while
            # the compile below blocks this thread (and the beat) —
            # popping first made "warmed" readable a compile-length
            # early, and a request landing in that window died at the
            # client's health deadline (found live, flaky test)
            client = self._clients.get(self._warm[0])
        try:
            if client is not None:
                self._adopt_model()
                buckets = {_bucket(1, self.cfg.max_batch,
                                   self._bucket_floor),
                           _bucket(client.rows_max, self.cfg.max_batch,
                                   self._bucket_floor)}
                for rows in sorted(buckets):
                    leaves = [np.zeros((rows,) + shape, dtype)
                              for shape, dtype in client.leaf_specs]
                    self._forward(self._model,
                                  self._obs_tree(client, leaves))
        finally:
            with self._lock:
                if self._warm:
                    self._warm.pop(0)
        return client is not None

    def _reap_idle(self):
        """Reclaim clients silent on both rings past CLIENT_IDLE_REAP
        (their worker died or went fully local).  Two-phase: removal
        from the live set now, ring close after GRAVE_GRACE — any
        snapshot iteration taken before removal finishes long before
        the grace expires, so no thread can touch a closing buffer."""
        now = self.clock()
        with self._lock:
            dead = [cid for cid, c in self._clients.items()
                    if now - c.last_seen > self.CLIENT_IDLE_REAP]
            for cid in dead:
                client = self._clients.pop(cid)
                self._grave.append((now + self.GRAVE_GRACE, client))
                self.reaped += 1
                print(f"pipeline: reaped idle client {cid} "
                      f"(silent {self.CLIENT_IDLE_REAP:.0f}s)")
            ready = [c for due, c in self._grave if now >= due]
            self._grave = [(due, c) for due, c in self._grave
                           if now < due]
        for client in ready:
            client.req.close()
            client.rsp.close()
            client.traj.close()
        return bool(dead or ready)

    @property
    def warm_pending(self):
        with self._lock:
            return len(self._warm)

    def _loop(self):
        self.board.beat(epoch=self._epoch)
        while not self._stop:
            if self._kill:
                return  # chaos death: no parting beat, board goes stale
            self._adopt_model()
            worked = self.step()
            if not worked:
                worked = self._warm_next()
            if not worked:
                self._reap_idle()
            self.board.beat(epoch=self._epoch)
            if not worked:
                self.sleep(5e-4)
