"""jaxlint — AST-based JAX/TPU correctness analyzer (CLI + driver).

Runs the rule set in :mod:`.rules` over a package directory (or single
files), with per-line suppression comments and text/JSON output.
Stdlib only; jax is never imported.

Usage::

    python -m handyrl_tpu.analysis.jaxlint handyrl_tpu/
    python -m handyrl_tpu.analysis.jaxlint --json handyrl_tpu/
    python -m handyrl_tpu.analysis.jaxlint --shard handyrl_tpu/
    python -m handyrl_tpu.analysis.jaxlint --comm handyrl_tpu/
    python -m handyrl_tpu.analysis.jaxlint --race handyrl_tpu/
    python -m handyrl_tpu.analysis.jaxlint --num handyrl_tpu/
    python -m handyrl_tpu.analysis.jaxlint --leak handyrl_tpu/
    python -m handyrl_tpu.analysis.jaxlint --sarif handyrl_tpu/
    python -m handyrl_tpu.analysis.jaxlint --list-rules
    handyrl-jaxlint handyrl_tpu/            # console-script entry

``--shard`` additionally runs the sharding/collective-consistency rule
set (:mod:`.shardrules` — mesh-axis validity, implicit resharding,
multihost divergence) and ``--comm`` the control-plane protocol/
concurrency rule set (:mod:`.commrules` — unhandled/dead verbs, reply
wedges, unbounded recvs, unpicklable payloads, fork safety) and
``--race`` the thread-safety rule set (:mod:`.racerules` — unguarded
shared writes, non-atomic read-modify-writes, live-container
iteration, lock-order cycles, blocking under a lock, leaked
acquires) and ``--num`` the dtype/precision-flow rule set
(:mod:`.numrules` — implicit upcasts, weak-type promotion, bf16
accumulation, unguarded lossy casts, split-brain return dtypes,
nonfinite producers) and ``--leak`` the resource-lifecycle rule set
(:mod:`.leakrules` — unreleased/error-path-leaked locals, respawn
overwrites, unjoined threads, unlinked shm creators, double
releases); the flags compose.  ``--sarif`` emits SARIF
2.1.0 for GitHub code scanning; ``--exclude`` drops path prefixes
(e.g. test fixtures) from directory scans.  ``--list-rules`` always
prints all six rule families.

Exit status: 0 when clean, 1 when any finding survives suppression,
2 on usage/IO errors.

Suppression syntax (the reason after ``--`` is REQUIRED — a
suppression that doesn't say why is itself reported)::

    x = foo()  # jaxlint: disable=host-sync -- once per epoch, by design
    # jaxlint: disable=tracer-branch,prng-reuse -- trace-time constant
    # jaxlint: skip-file -- generated code

A ``disable`` comment applies to its own line; a comment-only line
also covers the next line (so long statements can carry the
suppression above their first line).  ``disable=all`` silences every
rule.  ``skip-file`` (first 10 lines) skips the whole file.
"""

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

from .astutil import (
    ModuleInfo,
    Package,
    compute_device_summaries,
    compute_tracer_taint,
)
from .rules import RULES, Finding

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*(disable|skip-file)"
    r"(?:\s*=\s*([\w\-]+(?:\s*,\s*[\w\-]+)*))?"
    r"(?:\s+--\s+(\S.*))?")


def _iter_comments(source: str) -> List[Tuple[int, str]]:
    """``(lineno, comment_text)`` for every real comment token.

    Falls back to whole-line scanning only if tokenization fails (the
    file already parsed as AST before we get here, so that is rare)."""
    import io
    import tokenize

    out: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return [(lineno, line)
                for lineno, line in enumerate(source.splitlines(), 1)
                if "#" in line]
    return out


class Suppressions:
    """Per-file suppression map parsed from REAL comment tokens — a
    docstring or string literal that merely documents the syntax (this
    module's own docstring, say) must neither suppress anything nor
    count as a bare suppression."""

    def __init__(self, source: str, path: str):
        self.path = path
        self.skip_file = False
        self.by_line: Dict[int, Tuple[set, bool, int]] = {}
        bare: List[Tuple[int, str]] = []
        lines = source.splitlines()
        for lineno, comment in _iter_comments(source):
            match = _SUPPRESS_RE.search(comment)
            if match is None:
                continue
            line = lines[lineno - 1] if lineno <= len(lines) else comment
            verb, rules_str, reason = match.groups()
            if verb == "skip-file":
                if lineno <= 10:
                    self.skip_file = True
                if not reason:
                    bare.append((lineno, "skip-file"))
                continue
            rules = {r.strip() for r in (rules_str or "all").split(",")
                     if r.strip()}
            comment_only = line.strip().startswith("#")
            self.by_line[lineno] = (rules, comment_only, lineno)
            if not reason:
                bare.append((lineno, "disable=" + ",".join(sorted(rules))))
        self.bare = bare

    def covers(self, rule_id: str, lineno: int) -> bool:
        for probe in (lineno, lineno - 1):
            entry = self.by_line.get(probe)
            if entry is None:
                continue
            rules, comment_only, _ = entry
            if probe == lineno - 1 and not comment_only:
                continue  # only standalone comments cover the next line
            if "all" in rules or rule_id in rules:
                return True
        return False

    def bare_findings(self) -> List[Finding]:
        return [
            Finding("bare-suppression", self.path, lineno, 0,
                    f"suppression '{what}' has no reason — append "
                    f"' -- <why this is safe>'")
            for lineno, what in self.bare
        ]


def _excluded(path: str, exclude: Optional[List[str]]) -> bool:
    if not exclude:
        return False
    norm = os.path.normpath(path)
    for prefix in exclude:
        p = os.path.normpath(prefix)
        if norm == p or norm.startswith(p + os.sep):
            return True
    return False


def _iter_py_files(paths: List[str], exclude: Optional[List[str]] = None):
    for path in paths:
        if os.path.isfile(path):
            if not _excluded(path, exclude):
                yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git")
                    and not _excluded(os.path.join(root, d), exclude))
                for name in sorted(files):
                    full = os.path.join(root, name)
                    if name.endswith(".py") \
                            and not _excluded(full, exclude):
                        yield full
        else:
            raise FileNotFoundError(path)


def _module_name(path: str, roots: List[str]) -> str:
    """Dotted module name so package-relative imports resolve when a
    package directory is scanned (``handyrl_tpu/ops/update.py`` ->
    ``handyrl_tpu.ops.update``)."""
    norm = os.path.normpath(path)
    for root in roots:
        parent = os.path.dirname(os.path.normpath(root))
        if norm.startswith(os.path.normpath(root) + os.sep) \
                or norm == os.path.normpath(root):
            rel = os.path.relpath(norm, parent)
            break
    else:
        rel = os.path.basename(norm)
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = rel.split(os.sep)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def load_package(paths: List[str], exclude: Optional[List[str]] = None):
    """Parse every .py under ``paths`` into a Package + suppressions.

    Returns ``(package, suppressions_by_path, errors)`` where errors
    are (path, message) for unparseable files.
    """
    roots = [p for p in paths if os.path.isdir(p)]
    modules, suppressions, errors = [], {}, []
    for path in _iter_py_files(paths, exclude):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            module = ModuleInfo(_module_name(path, roots), path, source)
        except (SyntaxError, UnicodeDecodeError) as exc:
            errors.append((path, str(exc)))
            continue
        modules.append(module)
        suppressions[path] = Suppressions(source, path)
    return Package(modules), suppressions, errors


def active_registry(shard: bool = False,
                    comm: bool = False,
                    race: bool = False,
                    num: bool = False,
                    leak: bool = False) -> Dict[str, "object"]:
    """The rule registry in force: jaxlint's base rules, plus the
    shardlint rules with ``shard=True``, the commlint rules with
    ``comm=True``, the racelint rules with ``race=True``, the
    numlint rules with ``num=True``, and the leaklint rules with
    ``leak=True`` (the flags compose)."""
    registry = dict(RULES)
    if shard:
        from .shardrules import SHARD_RULES

        registry.update(SHARD_RULES)
    if comm:
        from .commrules import COMM_RULES

        registry.update(COMM_RULES)
    if race:
        from .racerules import RACE_RULES

        registry.update(RACE_RULES)
    if num:
        from .numrules import NUM_RULES

        registry.update(NUM_RULES)
    if leak:
        from .leakrules import LEAK_RULES

        registry.update(LEAK_RULES)
    return registry


def lint_paths(paths: List[str],
               select: Optional[List[str]] = None,
               shard: bool = False,
               comm: bool = False,
               race: bool = False,
               num: bool = False,
               leak: bool = False,
               exclude: Optional[List[str]] = None) -> List[Finding]:
    """Run the (selected) rules over ``paths``; returns surviving
    findings sorted by location."""
    package, suppressions, errors = load_package(paths, exclude)
    findings = [
        Finding("parse-error", path, 1, 0, f"cannot parse: {msg}")
        for path, msg in errors
    ]
    compute_tracer_taint(package)
    compute_device_summaries(package)
    registry = active_registry(shard, comm, race, num, leak)
    active = [registry[r] for r in (select or sorted(registry))]
    for mod in package.modules.values():
        supp = suppressions[mod.path]
        if supp.skip_file:
            # a reason-less skip-file must not be a silent, zero-cost
            # bypass of the whole gate: the bare suppression itself
            # still surfaces (and fails CI) even though rules skip
            findings.extend(supp.bare_findings())
            continue
        for rule in active:
            for finding in rule.check(package, mod):
                if not supp.covers(finding.rule, finding.line):
                    findings.append(finding)
        findings.extend(supp.bare_findings())
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_source(source: str, name: str = "<string>",
                select: Optional[List[str]] = None,
                shard: bool = False,
                comm: bool = False,
                race: bool = False,
                num: bool = False,
                leak: bool = False) -> List[Finding]:
    """Lint one in-memory module (test/fixture helper)."""
    module = ModuleInfo(name, name, source)
    package = Package([module])
    compute_tracer_taint(package)
    compute_device_summaries(package)
    registry = active_registry(shard, comm, race, num, leak)
    supp = Suppressions(source, name)
    findings: List[Finding] = []
    if supp.skip_file:
        findings.extend(supp.bare_findings())
    else:
        for rule_id in (select or sorted(registry)):
            for finding in registry[rule_id].check(package, module):
                if not supp.covers(finding.rule, finding.line):
                    findings.append(finding)
        findings.extend(supp.bare_findings())
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def _print_text(findings: List[Finding], file=None):
    file = file or sys.stdout
    for f in findings:
        print(f"{f.location}: [{f.rule}] {f.message}", file=file)
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    if findings:
        by_rule = ", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
        print(f"\n{len(findings)} finding(s) ({by_rule})", file=file)
    else:
        print("jaxlint: clean", file=file)


def _print_json(findings: List[Finding], file=None):
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    json.dump({
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "col": f.col + 1, "message": f.message}
            for f in findings
        ],
        "counts": counts,
        "total": len(findings),
    }, file or sys.stdout, indent=2)
    print(file=file or sys.stdout)


def _print_sarif(findings: List[Finding], registry, file=None):
    """SARIF 2.1.0 — the schema GitHub code scanning ingests, so CI
    lint findings render as inline PR annotations."""
    rule_ids = sorted({f.rule for f in findings} | set(registry))
    rules_meta = []
    for rule_id in rule_ids:
        rule = registry.get(rule_id)
        summary = rule.summary if rule is not None else {
            "bare-suppression": "a suppression comment with no reason",
            "parse-error": "a file the analyzer cannot parse",
        }.get(rule_id, rule_id)
        meta = {"id": rule_id,
                "shortDescription": {"text": summary}}
        if rule is not None and rule.doc:
            meta["fullDescription"] = {
                "text": " ".join(rule.doc.split())}
        rules_meta.append(meta)
    json.dump({
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "handyrl-jaxlint",
                "informationUri":
                    "https://github.com/handyrl-tpu/handyrl-tpu"
                    "/blob/main/docs/static_analysis.md",
                "rules": rules_meta,
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/")},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                }}],
            } for f in findings],
        }],
    }, file or sys.stdout, indent=2)
    print(file=file or sys.stdout)


def _print_rules(registry):
    for rule_id in sorted(registry):
        rule = registry[rule_id]
        print(f"{rule_id}: {rule.summary}")
        doc = " ".join((rule.doc or "").split())
        if doc:
            print(f"    {doc}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="jaxlint",
        description="AST-based JAX/TPU correctness analyzer")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or package directories "
                             "(default: handyrl_tpu)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON output")
    parser.add_argument("--sarif", action="store_true",
                        help="SARIF 2.1.0 output (GitHub code "
                             "scanning annotations)")
    parser.add_argument("--shard", action="store_true",
                        help="also run the sharding/collective-"
                             "consistency rules (shardlint)")
    parser.add_argument("--comm", action="store_true",
                        help="also run the control-plane protocol/"
                             "concurrency rules (commlint)")
    parser.add_argument("--race", action="store_true",
                        help="also run the thread-safety/lock-order "
                             "rules (racelint)")
    parser.add_argument("--num", action="store_true",
                        help="also run the dtype/precision-flow "
                             "rules (numlint)")
    parser.add_argument("--leak", action="store_true",
                        help="also run the resource-lifecycle/"
                             "ownership rules (leaklint)")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--exclude", action="append", default=None,
                        metavar="PREFIX",
                        help="path prefix to skip (repeatable), e.g. "
                             "tests/fixtures")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    args = parser.parse_args(argv)

    registry = active_registry(args.shard, args.comm, args.race,
                               args.num, args.leak)
    if args.list_rules:
        # the rule LISTING is documentation, not a gate: always show
        # every registered family (jax + shard + comm + race + num +
        # leak) with its doc
        _print_rules(active_registry(shard=True, comm=True, race=True,
                                     num=True, leak=True))
        return 0
    if args.json and args.sarif:
        print("jaxlint: --json and --sarif are mutually exclusive",
              file=sys.stderr)
        return 2

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in registry]
        if unknown:
            print(f"jaxlint: unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    paths = args.paths or ["handyrl_tpu"]
    try:
        findings = lint_paths(paths, select=select, shard=args.shard,
                              comm=args.comm, race=args.race,
                              num=args.num, leak=args.leak,
                              exclude=args.exclude)
    except FileNotFoundError as exc:
        print(f"jaxlint: no such path: {exc}", file=sys.stderr)
        return 2

    if args.sarif:
        _print_sarif(findings, registry)
        if findings:
            # stdout is redirected to the .sarif artifact in CI: a red
            # job must still show WHAT failed in its log
            _print_text(findings, file=sys.stderr)
    elif args.json:
        _print_json(findings)
    else:
        _print_text(findings)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
