"""Static analysis + runtime guards for JAX/TPU correctness.

Two halves, one goal — keep the learner hot path device-bound and
trace-stable as the codebase grows:

  * :mod:`handyrl_tpu.analysis.jaxlint` — an AST-based analyzer (stdlib
    ``ast`` only, no runtime jax import) that enforces the classic JAX
    invariants repo-wide: no PRNG key reuse, no Python branching on
    tracers inside jitted code, no host syncs in hot loops, no
    use-after-donation, no retrace-forcing jit patterns, no leftover
    debug calls.  CLI: ``python -m handyrl_tpu.analysis.jaxlint``.
  * :mod:`handyrl_tpu.analysis.shardlint` + ``shardrules`` — the
    sharding/collective-consistency layer (``--shard``): an abstract
    interpreter over the same package model that validates mesh axes,
    ``PartitionSpec`` consistency, collective/shard_map agreement,
    implicit resharding at jit boundaries, multihost control-flow
    divergence, and divisibility guarantees.
  * :mod:`handyrl_tpu.analysis.commlint` + ``commrules`` — the
    control-plane protocol/concurrency layer (``--comm``): builds the
    package's ``(verb, payload)`` protocol graph (sent vs handled
    verbs, request/reply round trips) and checks blocking recvs,
    payload picklability, and fork safety around it.
  * :mod:`handyrl_tpu.analysis.racelint` + ``racerules`` — the
    thread-safety layer (``--race``): the thread-spawn graph and lock
    environment behind the unguarded-write/lock-order rules.
  * :mod:`handyrl_tpu.analysis.numlint` + ``numrules`` — the
    dtype/precision-flow layer (``--num``): an interprocedural dtype
    lattice (bf16/fp32/uint8/weak scalars, the ``compute_dtype`` /
    ``obs_store`` config facts) behind the implicit-upcast /
    lowp-accum / unguarded-cast / nonfinite-risk rules.
  * :mod:`handyrl_tpu.analysis.guards` — runtime guards that measure
    what the linters cannot prove: ``RetraceGuard`` (compile counts of
    the update step), ``HostTransferGuard`` (device->host transfer
    counts per epoch), ``ShardingContractGuard`` (resharding copies at
    the update step's boundary), ``StallWatchdog`` (silent wedges
    of the control-plane loops, per-epoch ``stall_events``),
    ``LockOrderGuard`` (lock contention/ordering at runtime), and
    ``NumericsGuard`` (dtype-contract breaks + nonfinite update
    steps at the jit boundary).

Guard classes are re-exported lazily (PEP 562) so importing the
analysis package — e.g. from the jaxlint CLI — never pulls in jax.
"""

_GUARD_EXPORTS = ("RetraceGuard", "RetraceError", "HostTransferGuard",
                  "HostTransferError", "ShardingContractGuard",
                  "ShardingContractError", "StallWatchdog",
                  "NumericsGuard", "NumericsError")

__all__ = list(_GUARD_EXPORTS)


def __getattr__(name):
    if name in _GUARD_EXPORTS:
        from . import guards

        return getattr(guards, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
