"""Suppressed: the intentional blocking waits carry reasoned
suppressions saying why each wedge is bounded."""


def drain(conn, sink):
    while True:
        # jaxlint: disable=unbounded-recv -- child process on a parent pipe: parent death breaks the pipe and raises here
        data = conn.recv()
        sink.append(data)


def pull(jobs):
    # jaxlint: disable=unbounded-recv -- the producer enqueues a None sentinel per consumer at shutdown, so this drain terminates
    item = jobs.get()
    return item
