"""Fault injection: kill children, corrupt control-plane frames.

Nothing in CI used to EXERCISE a failure — the supervision and framing
hardening in this package would otherwise be dead code with green
tests.  The chaos harness makes failure a configured input:

  * :class:`ChaosMonkey` kills supervised children at a configured
    rate/point; the e2e chaos test arms it via the ``chaos:`` config
    section and asserts training still completes with ``respawns >= 1``.
  * :class:`ChaosConnection` wraps a connection and drops, delays, or
    truncates whole frames, driving the receiver's ``FrameError`` /
    dead-peer paths in unit tests.

All randomness flows through one injectable RNG (``seed`` in the
config), so chaos tests are seedable and non-flaky.
"""

import os
import pickle
import random
import signal
import struct
import time
from dataclasses import dataclass, fields
from typing import Any, Callable, Dict, Optional


@dataclass
class ChaosConfig:
    """The ``chaos:`` config section (docs/parameters.md).

    Everything defaults off; a run with an empty section is exactly a
    run without one.  Probabilities are per opportunity: per
    supervision tick for ``kill_prob``, per sent frame for the
    ``frame_*`` knobs.
    """

    kill_prob: float = 0.0        # P(kill one running child) per tick
    kill_after: float = 0.0       # seconds after arm before kills start
    max_kills: int = 0            # total kill budget; 0 = unlimited
    frame_drop_prob: float = 0.0      # P(frame silently vanishes)
    frame_truncate_prob: float = 0.0  # P(frame cut mid-payload + close)
    frame_delay_prob: float = 0.0     # P(frame delayed by frame_delay)
    frame_delay: float = 0.05         # seconds per injected delay
    # -- scheduled surge (a preemption wave, not a dice roll): fires
    # ONCE when the learner epoch reaches surge_epoch
    surge_epoch: int = 0          # epoch that triggers the surge; 0 = off
    surge_kills: int = 0          # gathers burst-killed at the surge
    surge_respawn_hold: float = 0.0   # seconds respawns stay held after it
    surge_hold_uploads: float = 0.0   # seconds gathers sit on their upload
    #                                   backlog after seeing the surge epoch
    # -- scheduled LEARNER kill (durability chaos): a hard SIGKILL of
    # the learner process itself mid-epoch — the preemption the
    # manifest/WAL/auto-resume machinery exists to survive.  Fires
    # exactly once per run directory (a marker file under models/
    # guards relaunches, so the supervised resume is not re-killed)
    learner_kill_epoch: int = 0   # learner epoch that arms the kill; 0 = off
    learner_kill_after_episodes: int = 1  # episodes received past the armed
    #                                       epoch before the SIGKILL lands
    # -- scheduled INFERENCE-SERVER kill (pipeline chaos): the batched
    # inference service dies without a parting heartbeat when the
    # learner epoch reaches this — workers must fall back to local CPU
    # inference and the learner must respawn the service.  Fires once
    infer_kill_epoch: int = 0     # learner epoch of the kill; 0 = off
    seed: int = 0                 # seeds the shared chaos RNG

    @classmethod
    def from_config(cls, raw: Optional[Dict[str, Any]]) -> "ChaosConfig":
        raw = dict(raw or {})
        known = {f.name for f in fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown chaos keys: {sorted(unknown)}")
        cfg = cls(**raw)
        for name in ("kill_prob", "frame_drop_prob",
                     "frame_truncate_prob", "frame_delay_prob"):
            p = getattr(cfg, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"chaos.{name} must be in [0, 1]")
        for name in ("kill_after", "frame_delay", "surge_respawn_hold",
                     "surge_hold_uploads", "max_kills", "surge_epoch",
                     "surge_kills", "learner_kill_epoch",
                     "learner_kill_after_episodes",
                     "infer_kill_epoch"):
            if getattr(cfg, name) < 0:
                raise ValueError(f"chaos.{name} must be >= 0")
        total = (cfg.frame_drop_prob + cfg.frame_truncate_prob
                 + cfg.frame_delay_prob)
        if total > 1.0:
            # one uniform draw picks at most one fault per frame, so
            # the configured rates only hold when they sum to <= 1
            raise ValueError(
                f"chaos frame probabilities must sum to <= 1 "
                f"(got {total:g})")
        return cfg

    @property
    def kills_enabled(self) -> bool:
        return self.kill_prob > 0.0

    @property
    def frames_enabled(self) -> bool:
        return (self.frame_drop_prob > 0.0
                or self.frame_truncate_prob > 0.0
                or self.frame_delay_prob > 0.0)

    @property
    def surges_enabled(self) -> bool:
        return self.surge_epoch > 0

    @property
    def learner_kill_enabled(self) -> bool:
        return self.learner_kill_epoch > 0

    @property
    def infer_kill_enabled(self) -> bool:
        return self.infer_kill_epoch > 0


class ChaosMonkey:
    """Kills supervised children on a seeded schedule, and fires
    scheduled SURGES.

    Drive it from the supervision loop: ``maybe_kill(supervisor)`` and
    ``maybe_surge(supervisor)`` once per tick; the learner reports its
    epoch via :meth:`note_epoch`.  Kills route through
    ``Supervisor.kill_slot`` so the victim dies exactly the way a
    preempted host does — and the normal failure -> backoff -> respawn
    path takes over.  A surge is a PREEMPTION WAVE, not a dice roll:
    when the observed epoch reaches ``surge_epoch`` it burst-kills
    ``surge_kills`` gathers ONCE (deterministically the lowest slots)
    and holds every respawn for ``surge_respawn_hold`` seconds, so the
    fleet stays degraded for a window instead of bouncing straight
    back.
    """

    def __init__(self, cfg: ChaosConfig,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.rng = rng if rng is not None else random.Random(cfg.seed)
        self.clock = clock
        self.armed_at = clock()
        self.kills = 0            # dice-roll kills (capped by max_kills)
        self.surge_kill_count = 0  # scheduled-surge kills (uncapped)
        self.epoch = 0
        self.surged = False

    def maybe_kill(self, supervisor, now: Optional[float] = None) -> bool:
        cfg = self.cfg
        if not cfg.kills_enabled:
            return False
        if cfg.max_kills and self.kills >= cfg.max_kills:
            return False
        if now is None:
            now = self.clock()
        if now - self.armed_at < cfg.kill_after:
            return False
        if self.rng.random() >= cfg.kill_prob:
            return False
        targets = supervisor.running_children()
        if not targets:
            return False
        index, _ = targets[self.rng.randrange(len(targets))]
        self.kills += 1
        supervisor.kill_slot(index, reason=f"chaos kill #{self.kills}")
        return True

    def note_epoch(self, epoch: int):
        """Learner-reported epoch: the surge trigger's clock."""
        self.epoch = max(self.epoch, int(epoch))

    def maybe_surge(self, supervisor, now: Optional[float] = None) -> bool:
        """Fire the scheduled surge once the noted epoch reaches it."""
        cfg = self.cfg
        if not cfg.surges_enabled or self.surged:
            return False
        if self.epoch < cfg.surge_epoch:
            return False
        self.surged = True
        if now is None:
            now = self.clock()
        targets = supervisor.running_children()
        # deterministic victims (lowest slots): a surge is a scheduled
        # event the e2e must replay exactly, so no RNG is involved.
        # Counted apart from `kills` — the surge is a scheduled wave,
        # not a dice roll, so it must not consume the max_kills budget
        # reserved for the random kills
        for index, _ in sorted(targets)[:cfg.surge_kills]:
            self.surge_kill_count += 1
            supervisor.kill_slot(
                index, reason=f"chaos surge at epoch {self.epoch}")
        if cfg.surge_respawn_hold > 0:
            supervisor.hold_respawns(cfg.surge_respawn_hold, now=now)
        return True


class LearnerKillSwitch:
    """Schedules a hard SIGKILL of the LEARNER process mid-epoch.

    The durability counterpart of :class:`ChaosMonkey`: where the
    monkey preempts actors, the kill switch preempts the learner host
    itself — no cleanup, no signal handler, exactly an eviction.  The
    learner ticks :meth:`note` from its intake path; the kill lands
    ``learner_kill_after_episodes`` arrivals after the noted epoch
    reaches ``learner_kill_epoch``, which is deterministically
    MID-window (between two checkpoints), the state the WAL exists to
    recover.  A marker file (fsync'd before the kill) makes the switch
    once-per-run-directory, so a supervised relaunch resumes instead
    of being re-killed at the same epoch.  ``kill`` is injectable for
    unit tests."""

    def __init__(self, cfg: ChaosConfig, marker_path: str,
                 kill: Optional[Callable[[], None]] = None):
        self.cfg = cfg
        self.marker_path = marker_path
        self._kill = kill if kill is not None else self._sigkill_self
        self._kill_at: Optional[int] = None
        self.armed = (cfg.learner_kill_enabled
                      and not os.path.exists(marker_path))

    @staticmethod
    def _sigkill_self():  # pragma: no cover - exercised by the e2e
        os.kill(os.getpid(), signal.SIGKILL)

    def note(self, epoch: int, episodes_received: int) -> bool:
        """Intake tick; returns True when the kill fired (test fakes
        only — the real kill never returns)."""
        if not self.armed or epoch < self.cfg.learner_kill_epoch:
            return False
        if self._kill_at is None:
            self._kill_at = (episodes_received
                             + self.cfg.learner_kill_after_episodes)
        if episodes_received < self._kill_at:
            return False
        self.armed = False
        os.makedirs(os.path.dirname(self.marker_path), exist_ok=True)
        with open(self.marker_path, "w") as f:
            f.write(f"epoch {epoch} after {episodes_received} episodes\n")
            f.flush()
            os.fsync(f.fileno())
        print(f"CHAOS: SIGKILL of the learner at epoch {epoch} "
              f"({episodes_received} episodes received) — durability "
              "drill, resume should recover")
        self._kill()
        return True


class ChaosConnection:
    """A connection wrapper that injects frame-level faults on send.

    Wraps anything with the connection duck type; the truncation fault
    needs byte-level access and therefore requires the inner connection
    to be a :class:`~handyrl_tpu.connection.FramedConnection` (it
    writes a header promising the full payload, ships half, and closes
    — exactly what a peer dying mid-send looks like on the wire).
    One uniform draw per frame picks at most one fault, so configured
    probabilities compose additively.
    """

    def __init__(self, inner, cfg: ChaosConfig,
                 rng: Optional[random.Random] = None):
        self.inner = inner
        self.cfg = cfg
        self.rng = rng if rng is not None else random.Random(cfg.seed)
        self.dropped = 0
        self.truncated = 0
        self.delayed = 0

    def fileno(self):
        return self.inner.fileno()

    def close(self):
        self.inner.close()

    def recv(self):
        # jaxlint: disable=unbounded-recv -- transparent wrapper: boundedness (timeouts, heartbeat sweep) is the wrapped connection's property, and chaos only perturbs sends
        return self.inner.recv()

    def _send_truncated(self, data: Any):
        from ..connection import FramedConnection

        if not isinstance(self.inner, FramedConnection):
            self.dropped += 1  # pipes have no wire to cut: drop instead
            return
        payload = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
        partial = struct.pack("!I", len(payload)) \
            + payload[:max(1, len(payload) // 2)]
        try:
            self.inner.sock.sendall(partial)
        finally:
            self.inner.close()  # mid-frame death: the receiver must
            #                     see a truncated payload, not a stall

    def send(self, data: Any):
        cfg = self.cfg
        draw = self.rng.random()
        if draw < cfg.frame_drop_prob:
            self.dropped += 1
            return
        draw -= cfg.frame_drop_prob
        if draw < cfg.frame_truncate_prob:
            self.truncated += 1
            self._send_truncated(data)
            return
        draw -= cfg.frame_truncate_prob
        if draw < cfg.frame_delay_prob:
            self.delayed += 1
            time.sleep(cfg.frame_delay)
        self.inner.send(data)
