"""Game environment registry and interface contract.

API parity with the reference environment layer
(/root/reference/handyrl/environment.py:9-145): the same registry
semantics (short name or dotted module path) and the same
``BaseEnvironment`` method surface, covering turn-based and simultaneous
games, partial observability, and the delta-sync protocol used by
network battles.

TPU-native conventions layered on top:
  * observations are numpy arrays (or pytrees of arrays) with
    **channel-last** (NHWC) layout, matching TPU-friendly Flax convs —
    the reference emits channel-first for PyTorch;
  * ``net()`` returns a Flax ``linen.Module`` (the reference returns a
    ``torch.nn.Module``).
"""

import importlib

# short name -> module path; any dotted path is also accepted directly,
# mirroring /root/reference/handyrl/environment.py:17-36.
ENV_REGISTRY = {
    "TicTacToe": "handyrl_tpu.envs.tictactoe",
    "ParallelTicTacToe": "handyrl_tpu.envs.parallel_tictactoe",
    "Geister": "handyrl_tpu.envs.geister",
    "HungryGeese": "handyrl_tpu.envs.kaggle.hungry_geese",
    "GRFProxy": "handyrl_tpu.envs.grf_proxy",
}

# pure-JAX twins of registered envs: functional (state, action, key)
# modules the Anakin engine (handyrl_tpu.anakin) can vmap/scan inside
# one jitted rollout+update program.  The Python env stays the spec —
# a twin must bit-match its transition/reward/legal semantics (the
# exhaustive parity test in tests/test_anakin.py enforces it for
# TicTacToe).  Envs absent here keep the IMPALA worker path.
JAX_ENV_REGISTRY = {
    "TicTacToe": "handyrl_tpu.envs.tictactoe_jax",
}


def _resolve(env_args):
    name = env_args["env"]
    return importlib.import_module(ENV_REGISTRY.get(name, name))


def jax_env_available(env_args) -> bool:
    """Whether the configured env has a registered pure-JAX twin."""
    return env_args.get("env") in JAX_ENV_REGISTRY


def make_jax_env(env_args):
    """Import the configured env's pure-JAX module (the functional
    ``init/step/observe/...`` surface the Anakin engine drives)."""
    name = env_args["env"]
    if name not in JAX_ENV_REGISTRY:
        raise ValueError(
            f"env {name!r} has no pure-JAX twin (JAX_ENV_REGISTRY); "
            "Anakin mode requires one — non-JAX envs use the IMPALA "
            "worker path")
    return importlib.import_module(JAX_ENV_REGISTRY[name])


def prepare_env(env_args):
    """Run a module-level ``prepare()`` hook if the env defines one."""
    module = _resolve(env_args)
    if hasattr(module, "prepare"):
        module.prepare()


def make_env(env_args):
    """Instantiate the ``Environment`` class of the configured env."""
    return _resolve(env_args).Environment(env_args)


class BaseEnvironment:
    """The framework <-> game contract.

    A game implements state transition, observation, and scoring; the
    framework drives rollout, training, and evaluation through exactly
    these methods.  Two interaction styles are supported:

      * **turn-based** games implement ``play(action, player)`` and
        ``turn()``; the default ``step`` applies each submitted action
        in sequence;
      * **simultaneous** games override ``step(actions)`` and
        ``turns()`` to report every player that must act.

    ``diff_info``/``update`` define a delta-sync protocol: a server-side
    env emits per-player deltas after each transition and mirrored
    client envs replay them, which is how network battles (and the
    mirrored-env contract test) keep distributed copies consistent
    without sharing full state.
    """

    def __init__(self, args=None):
        pass

    def __str__(self):
        return ""

    # -- lifecycle --------------------------------------------------
    def reset(self, args=None):
        """Start a new game. Return a truthy value to signal failure."""
        raise NotImplementedError()

    # -- state transition -------------------------------------------
    def play(self, action, player=None):
        """Apply one player's action (turn-based games)."""
        raise NotImplementedError()

    def step(self, actions):
        """Apply a ``{player: action}`` map for one transition."""
        for player, action in actions.items():
            if action is not None:
                self.play(action, player)

    # -- whose move -------------------------------------------------
    def turn(self):
        """The single player to move (turn-based games)."""
        return 0

    def turns(self):
        """All players that must act this transition."""
        return [self.turn()]

    def observers(self):
        """Non-acting players that should still observe (RNN models)."""
        return []

    # -- scoring ----------------------------------------------------
    def terminal(self):
        raise NotImplementedError()

    def reward(self):
        """Immediate per-player rewards for the last transition."""
        return {}

    def outcome(self):
        """Final per-player outcomes at the terminal state."""
        raise NotImplementedError()

    # -- actions & players ------------------------------------------
    def legal_actions(self, player=None):
        raise NotImplementedError()

    def players(self):
        return [0]

    # -- neural-net interface ---------------------------------------
    def observation(self, player=None):
        """Feature pytree for ``player`` (channel-last arrays)."""
        raise NotImplementedError()

    def net(self):
        """Return the Flax module for this game's policy-value net."""
        raise NotImplementedError()

    # -- string encodings -------------------------------------------
    def action2str(self, action, player=None):
        return str(action)

    def str2action(self, s, player=None):
        return int(s)

    # -- delta-sync protocol ----------------------------------------
    def diff_info(self, player=None):
        return ""

    def update(self, info, reset):
        raise NotImplementedError()
