"""Benchmark: learner update steps/sec on the jitted training step.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` compares against the reference's equivalent update loop
measured on this host if available (see BASELINE.md: the reference
publishes no numbers, so the ratio is against our recorded CPU-reference
measurement when present, else 1.0).
"""

import json
import time


def main():
    from __graft_entry__ import _build_model_and_batch

    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer, make_update_step

    import numpy as np

    # generate a few real episodes, then tile to the benchmark batch
    # size — rollout inference through the device tunnel is slow and is
    # not what this benchmark measures (actors run on CPU in production)
    batch_size = 64
    seed_eps = 4
    model, batch, cfg = _build_model_and_batch(
        batch_size=seed_eps, env_name="HungryGeese")
    import jax

    reps = batch_size // seed_eps
    batch = jax.tree.map(
        lambda v: np.tile(v, (reps,) + (1,) * (v.ndim - 1)), batch)
    loss_cfg = LossConfig.from_config(cfg)
    optimizer = make_optimizer(1e-3)
    params = model.params
    opt_state = optimizer.init(params)
    update = make_update_step(model, loss_cfg, optimizer)

    # compile + warmup
    params, opt_state, metrics = update(params, opt_state, batch)
    float(metrics["total"])

    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, metrics = update(params, opt_state, batch)
    float(metrics["total"])  # sync
    dt = time.perf_counter() - t0

    steps_per_sec = iters / dt
    baseline = None
    try:
        with open("BASELINE_MEASURED.json") as f:
            baseline = json.load(f).get("learner_steps_per_sec")
    except OSError:
        pass
    vs = steps_per_sec / baseline if baseline else 1.0

    print(json.dumps({
        "metric": "learner_update_steps_per_sec",
        "value": round(steps_per_sec, 2),
        "unit": (f"steps/sec (GeeseNet, "
                 f"batch={batch_size}x{cfg['forward_steps']})"),
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
