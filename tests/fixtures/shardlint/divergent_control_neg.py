"""Fixture: the safe control-word idiom — a host-divergent VALUE flows
into a collective every process runs unconditionally, and control flow
branches only on the synchronized result."""

import jax
from jax.experimental import multihost_utils


def sync_code(code):
    out = multihost_utils.broadcast_one_to_all(code)
    return int(out)


def epoch_control(update_flag):
    code = 0
    if jax.process_index() == 0 and update_flag:
        code = 1  # divergent value is fine: the collective still runs
    code = sync_code(code)
    if code == 1:  # branching on the synchronized result is fine
        return "epoch-end"
    return "step"


def primary_only_io(record):
    if jax.process_index() == 0:
        print(record)  # host-side work under a divergent branch is fine
    return record
