"""Self-contained ONNX protobuf codec (no ``onnx`` package needed).

The reference ships/consumes ``.onnx`` artifacts for deployment — its
``--eval`` runs them through onnxruntime
(/root/reference/handyrl/evaluation.py:287-365) and
``scripts/make_onnx_model.py`` produces them.  This image has neither
``onnx`` nor ``onnxruntime``, so interop is implemented from the wire
format up: protobuf is a simple TLV encoding, and the slice of
``onnx.proto`` a policy net needs is small.

Messages are plain dicts keyed by field name; repeated fields are
lists.  ``SCHEMAS`` maps message name -> {field number: (name, kind,
submessage)} with kinds:

  int    — varint (int64/enum/bool)
  str    — length-delimited utf-8
  bytes  — length-delimited raw
  float  — fixed32
  msg    — nested message
  packed — packed repeated varints (also accepts unpacked)

Field numbers follow the official ``onnx/onnx.proto`` (stable since
IR version 3).
"""

import struct

# kind tags
INT, STR, BYTES, FLT, MSG, PACKED = "int", "str", "bytes", "float", \
    "msg", "packed"

# (name, kind, repeated, submessage-name)
SCHEMAS = {
    "Model": {
        1: ("ir_version", INT, False, None),
        8: ("opset_import", MSG, True, "OperatorSetId"),
        2: ("producer_name", STR, False, None),
        3: ("producer_version", STR, False, None),
        4: ("domain", STR, False, None),
        5: ("model_version", INT, False, None),
        6: ("doc_string", STR, False, None),
        7: ("graph", MSG, False, "Graph"),
    },
    "OperatorSetId": {
        1: ("domain", STR, False, None),
        2: ("version", INT, False, None),
    },
    "Graph": {
        1: ("node", MSG, True, "Node"),
        2: ("name", STR, False, None),
        5: ("initializer", MSG, True, "Tensor"),
        10: ("doc_string", STR, False, None),
        11: ("input", MSG, True, "ValueInfo"),
        12: ("output", MSG, True, "ValueInfo"),
        13: ("value_info", MSG, True, "ValueInfo"),
    },
    "Node": {
        1: ("input", STR, True, None),
        2: ("output", STR, True, None),
        3: ("name", STR, False, None),
        4: ("op_type", STR, False, None),
        7: ("domain", STR, False, None),
        5: ("attribute", MSG, True, "Attribute"),
        6: ("doc_string", STR, False, None),
    },
    "Attribute": {
        1: ("name", STR, False, None),
        20: ("type", INT, False, None),
        2: ("f", FLT, False, None),
        3: ("i", INT, False, None),
        4: ("s", BYTES, False, None),
        5: ("t", MSG, False, "Tensor"),
        7: ("floats", FLT, True, None),
        8: ("ints", PACKED, True, None),
        9: ("strings", BYTES, True, None),
    },
    "Tensor": {
        1: ("dims", PACKED, True, None),
        2: ("data_type", INT, False, None),
        4: ("float_data", FLT, True, None),
        5: ("int32_data", PACKED, True, None),
        7: ("int64_data", PACKED, True, None),
        8: ("name", STR, False, None),
        9: ("raw_data", BYTES, False, None),
    },
    "ValueInfo": {
        1: ("name", STR, False, None),
        2: ("type", MSG, False, "Type"),
    },
    "Type": {
        1: ("tensor_type", MSG, False, "TypeTensor"),
    },
    "TypeTensor": {
        1: ("elem_type", INT, False, None),
        2: ("shape", MSG, False, "TensorShape"),
    },
    "TensorShape": {
        1: ("dim", MSG, True, "Dimension"),
    },
    "Dimension": {
        1: ("dim_value", INT, False, None),
        2: ("dim_param", STR, False, None),
    },
}

# AttributeProto.AttributeType values
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8

# TensorProto.DataType values
DT_FLOAT, DT_UINT8, DT_INT8, DT_INT32, DT_INT64 = 1, 2, 3, 6, 7
DT_BOOL, DT_FLOAT16, DT_DOUBLE, DT_BFLOAT16 = 9, 10, 11, 16


# -- encoding -----------------------------------------------------------

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's complement, 10 bytes (protobuf int64)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def encode(msg: dict, schema_name: str) -> bytes:
    schema = SCHEMAS[schema_name]
    by_name = {spec[0]: (num, spec) for num, spec in schema.items()}
    out = bytearray()
    for name, value in msg.items():
        if value is None:
            continue
        num, (_, kind, repeated, sub) = by_name[name]
        values = value if repeated else [value]
        if kind == PACKED:
            payload = b"".join(_varint(int(v)) for v in values)
            out += _tag(num, 2) + _varint(len(payload)) + payload
            continue
        for v in values:
            if kind == INT:
                out += _tag(num, 0) + _varint(int(v))
            elif kind == STR:
                raw = v.encode() if isinstance(v, str) else bytes(v)
                out += _tag(num, 2) + _varint(len(raw)) + raw
            elif kind == BYTES:
                out += _tag(num, 2) + _varint(len(v)) + bytes(v)
            elif kind == FLT:
                out += _tag(num, 5) + struct.pack("<f", float(v))
            elif kind == MSG:
                raw = encode(v, sub)
                out += _tag(num, 2) + _varint(len(raw)) + raw
            else:  # pragma: no cover
                raise ValueError(f"unknown kind {kind}")
    return bytes(out)


# -- decoding -----------------------------------------------------------

def _read_varint(buf, pos):
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >= 1 << 63:
                result -= 1 << 64
            return result, pos
        shift += 7


def decode(buf: bytes, schema_name: str) -> dict:
    schema = SCHEMAS[schema_name]
    msg = {}
    for num, (name, _, repeated, _) in schema.items():
        if repeated:
            msg[name] = []
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        spec = schema.get(field)
        # read the raw value per wire type
        if wire == 0:
            value, pos = _read_varint(buf, pos)
        elif wire == 2:
            length, pos = _read_varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wire == 5:
            value = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == 1:
            value = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:  # pragma: no cover
            raise ValueError(f"unsupported wire type {wire}")
        if spec is None:
            continue  # unknown field: skip (forward compatible)
        name, kind, repeated, sub = spec
        if kind == INT:
            pass
        elif kind == STR:
            value = bytes(value).decode("utf-8", "replace")
        elif kind == BYTES:
            value = bytes(value)
        elif kind == FLT:
            if wire == 2:  # packed floats
                raw = bytes(value)
                floats = [struct.unpack("<f", raw[i:i + 4])[0]
                          for i in range(0, len(raw), 4)]
                msg[name].extend(floats) if repeated else None
                continue
        elif kind == PACKED:
            if wire == 2:
                raw = bytes(value)
                p = 0
                while p < len(raw):
                    v, p = _read_varint(raw, p)
                    msg[name].append(v)
                continue
            # unpacked single varint falls through
        elif kind == MSG:
            value = decode(bytes(value), sub)
        if repeated:
            msg[name].append(value)
        else:
            msg[name] = value
    return msg
