"""Negative: threads are daemonic (explicit fire-and-forget), joined
before the handle drops, or joined on the class's shutdown path."""

import threading


def run_daemon(fn):
    worker = threading.Thread(target=fn, daemon=True)
    worker.start()


def run_and_wait(fn):
    worker = threading.Thread(target=fn)
    worker.start()
    worker.join()


class Pool:
    def __init__(self, fn):
        self._worker = threading.Thread(target=fn)
        self._worker.start()

    def stop(self):
        self._worker.join(timeout=5)
