"""Measure the reference implementation's learner throughput on THIS host.

Drives the reference's own update loop — compute_loss / backward /
clip_grad_norm(4.0) / Adam.step, i.e. /root/reference/handyrl/train.py
Trainer.train semantics — by importing the reference package from
/root/reference (no code is copied) and feeding it synthetic batches in
its native (B, T, P, ...) tensor format at the same GeeseNet geometry
our bench uses.  Results land in BASELINE_MEASURED.json, which bench.py
reads to report a real ``vs_baseline`` ratio.

The reference is torch-CPU on this host (it has no TPU path); this is
the honest like-for-like "reference on the same machine" number the
driver asked for.  Run:

    PYTHONPATH=/root/repo python scripts/measure_reference_baseline.py
"""

import json
import os
import sys
import time

REFERENCE_ROOT = "/root/reference"

GEESE_ARGS = {
    "turn_based_training": False,
    "observation": False,
    "gamma": 0.8,
    "forward_steps": 8,
    "burn_in_steps": 0,
    "compress_steps": 4,
    "entropy_regularization": 0.1,
    "entropy_regularization_decay": 0.1,
    "lambda": 0.7,
    "policy_target": "UPGO",
    "value_target": "TD",
}

OBS_SHAPE = (17, 7, 11)  # reference GeeseNet input planes
NUM_ACTIONS = 4
# the reference gathers ONE random seat per episode for simultaneous
# games ("solo training", /root/reference/handyrl/train.py:57-58), so
# the true training batch is (B, T, 1, ...) — P here is the batch's
# player axis, not the game's player count
NUM_PLAYERS = 1


def synthetic_batch(torch, batch_size, steps):
    """A batch in the reference make_batch output format
    (train.py:33-125): simultaneous 4-player play, all seats active."""
    g = torch.Generator().manual_seed(0)
    B, T, P = batch_size, steps, NUM_PLAYERS
    obs = torch.rand((B, T, P) + OBS_SHAPE, generator=g)
    ones = torch.ones((B, T, P, 1))
    return {
        "observation": obs,
        "selected_prob": torch.full((B, T, P, 1), 0.25),
        "value": torch.zeros((B, T, P, 1)),
        "action": torch.randint(0, NUM_ACTIONS, (B, T, P, 1), generator=g),
        "outcome": (torch.randint(0, 2, (B, 1, P, 1), generator=g)
                    .float() * 2 - 1),
        "reward": torch.zeros((B, T, P, 1)),
        "return": torch.zeros((B, T, P, 1)),
        "episode_mask": torch.ones((B, T, 1, 1)),
        "turn_mask": ones.clone(),
        "observation_mask": ones.clone(),
        "action_mask": torch.zeros((B, T, P, NUM_ACTIONS)),
        "progress": (torch.arange(T).float() / T)
        .reshape(1, T, 1).repeat(B, 1, 1),
    }


def measure(batch_size, steps, iters, warmup=1):
    sys.path.insert(0, REFERENCE_ROOT)
    import torch
    torch.set_num_threads(os.cpu_count() or 1)

    # the reference env module imports kaggle_environments at load time;
    # we only need its GeeseNet class, so satisfy the import with a stub
    import types

    if "kaggle_environments" not in sys.modules:
        stub = types.ModuleType("kaggle_environments")
        stub.make = lambda *a, **k: None
        sys.modules["kaggle_environments"] = stub

    from handyrl.envs.kaggle.hungry_geese import GeeseNet
    from handyrl.train import compute_loss

    model = GeeseNet()
    model.train()
    optimizer = torch.optim.Adam(
        model.parameters(), lr=3e-8 * batch_size * steps,
        weight_decay=1e-5)
    batch = synthetic_batch(torch, batch_size, steps)
    args = dict(GEESE_ARGS, forward_steps=steps)

    def one_step():
        # the reference hot loop: train.py:358-372
        losses, dcnt = compute_loss(batch, model, None, args)
        optimizer.zero_grad()
        losses["total"].backward()
        torch.nn.utils.clip_grad_norm_(model.parameters(), 4.0)
        optimizer.step()

    for _ in range(warmup):
        one_step()
    t0 = time.perf_counter()
    for _ in range(iters):
        one_step()
    dt = time.perf_counter() - t0
    return iters / dt


def measure_actor(iters=6):
    """The reference actor hot loop on TicTacToe (its only env with no
    external game dependency): Generator.generate with the torch conv
    net through ModelWrapper — generation.py:31-73 semantics."""
    sys.path.insert(0, REFERENCE_ROOT)
    import random

    import torch
    torch.set_num_threads(1)  # actor procs are thread-pinned (model.py:6-11)

    from handyrl.envs.tictactoe import Environment
    from handyrl.generation import Generator
    from handyrl.model import ModelWrapper

    random.seed(0)
    env = Environment()
    model = ModelWrapper(env.net())
    args = {
        "turn_based_training": True, "observation": False,
        "gamma": 0.8, "compress_steps": 4,
    }
    gen = Generator(env, args)
    players = env.players()
    job = {"player": players, "model_id": {p: 1 for p in players}}
    models = {p: model for p in players}
    gen.generate(models, job)  # warmup
    steps = 0
    t0 = time.perf_counter()
    done = 0
    while done < iters:
        ep = gen.generate(models, job)
        if ep is None:
            continue
        steps += ep["steps"]
        done += 1
    dt = time.perf_counter() - t0
    return steps / dt


def main():
    results = {
        "source": "reference handyrl (torch CPU) update loop on this host",
        "model": "GeeseNet",
        "host_cpu_count": os.cpu_count(),
    }
    for batch_size, iters in ((64, 6), (256, 3)):
        sps = measure(batch_size, steps=8, iters=iters)
        key = ("learner_steps_per_sec" if batch_size == 64
               else f"learner_steps_per_sec_b{batch_size}")
        results[key] = round(sps, 4)
        print(f"batch {batch_size}: {sps:.4f} steps/s")
    actor_sps = measure_actor()
    # TicTacToe is 2-player turn-based: frames == env steps (one seat
    # observes per step)
    results["actor_env_steps_per_sec_ttt"] = round(actor_sps, 2)
    print(f"reference actor TicTacToe: {actor_sps:.2f} env-steps/s")
    out = os.path.join(os.path.dirname(__file__), "..",
                       "BASELINE_MEASURED.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
