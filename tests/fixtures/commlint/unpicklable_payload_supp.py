"""Suppressed: the device-array send carries a reasoned suppression."""

import jax.numpy as jnp


def ship_device(conn):
    arr = jnp.zeros((4,))
    # jaxlint: disable=unpicklable-payload -- same-host pipe to a CPU-backend child; the one-off transfer is the cheapest correct option here
    conn.send(arr)
