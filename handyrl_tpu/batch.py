"""Host-side training-batch assembly.

Semantic parity with the reference ``make_batch``
(/root/reference/handyrl/train.py:33-125): decompress episode moment
blocks, select the training players (turn-based gathers only the turn
player; otherwise one random player — or all players when observers
train too), build ``(T, P, ...)`` arrays with the full mask set, and pad
short slices to the static ``burn_in + forward_steps`` window.

This runs on CPU (in batcher processes) and emits fixed-shape float32/
int32 numpy arrays ready for ``jax.device_put`` — static shapes are what
lets the jitted update step compile once and stream batches forever.

Batch layout (B = batch, T = time, P = players, A = actions):
  observation      pytree of (B, T, P_in, ...)   P_in = 1 if turn-based
  selected_prob    (B, T, P_in, 1)   behavior-policy probability
  action           (B, T, P_in, 1)   int32
  action_mask      (B, T, P_in, A)   0 legal / 1e32 illegal
  value/reward/return (B, T, P, V)
  outcome          (B, 1, P, 1)
  episode_mask     (B, T, 1, 1)      0 on padding
  turn_mask        (B, T, P, 1)      1 where the player acted
  observation_mask (B, T, P, 1)      1 where the player observed
  progress         (B, T, 1)         fraction of episode elapsed
"""

import bz2
import pickle
import random
from collections import OrderedDict

import numpy as np

try:
    import ml_dtypes

    BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    BF16 = None

from .utils.tree import tree_map, tree_stack, stack_time_player

ILLEGAL = np.float32(1e32)


def load_block(blob):
    """Moment block bytes -> list of moment dicts.

    Two wire formats share the episode schema, told apart by stream
    magic (no flag to thread through the columnar cache): the legacy
    control-plane format is bz2-compressed pickle (``BZh`` magic); the
    shm trajectory path ships raw pickle blocks (``\\x80`` protocol-2+
    opcode) — shared-memory bandwidth is free, so it skips the bz2 CPU
    cost on both ends (``pipeline.compress`` re-enables it)."""
    if blob[:2] == b"BZ":
        blob = bz2.decompress(blob)
    return pickle.loads(blob)


def decompress_moments(ep):
    """Inflate an episode's moment blocks and slice to [start, end).

    Uncached: the production batch path consumes the columnar cache
    below; this raw-moment view serves tests and tooling."""
    moments = [m for blob in ep["moment"] for m in load_block(blob)]
    return moments[ep["start"] - ep["base"]: ep["end"] - ep["base"]]


def _pad_time(arr, before, after, value=0.0):
    pad = [(before, after)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad, constant_values=value)


# ---------------------------------------------------------------------
# columnar block cache
#
# Recency-biased sampling draws the same episodes many times per epoch;
# the per-draw cost used to be a Python walk over every moment dict.
# Instead, each bz2 block is converted ONCE into stacked "columnar"
# arrays over (T_block, P_all, ...) — all players, with presence masks —
# and every draw then reduces to concatenate + slice + (turn-gather or
# column-select) + pad, which is pure numpy.  Cached per compressed
# blob (blocks arrive as fresh objects over the batcher pipe), bounded
# by decompressed bytes.
# ---------------------------------------------------------------------

_COL_CACHE = OrderedDict()  # blob -> (columnar dict, nbytes)
# PER BATCHER PROCESS: total resident cache is this times num_batchers
# (config key ``columnar_cache_mb`` adjusts it; see set_columnar_cache_mb)
_COL_CACHE_MAX_BYTES = 512 * 1024 * 1024
_col_cache_bytes = 0


def set_columnar_cache_mb(mb):
    """Resize this process's columnar cache cap (called by each batcher
    child from its config; 0/None keeps the default)."""
    global _COL_CACHE_MAX_BYTES, _col_cache_bytes
    if not mb:
        return
    _COL_CACHE_MAX_BYTES = int(mb) * 1024 * 1024
    while _col_cache_bytes > _COL_CACHE_MAX_BYTES and _COL_CACHE:
        _, (_, freed) = _COL_CACHE.popitem(last=False)
        _col_cache_bytes -= freed


def _nbytes_tree(x):
    if isinstance(x, dict):
        return sum(_nbytes_tree(v) for v in x.values())
    if isinstance(x, (list, tuple)):
        return sum(_nbytes_tree(v) for v in x)
    return getattr(x, "nbytes", 8)


def _build_columnar(moments):
    """Stack one block's moments into (T, P_all, ...) arrays."""
    players = list(moments[0]["observation"].keys())
    turn0 = moments[0]["turn"][0]
    obs_template = tree_map(
        lambda a: np.zeros_like(a), moments[0]["observation"][turn0]
    )
    num_actions = len(moments[0]["action_mask"][turn0])

    def pick(m, key, p, default):
        v = m[key][p]
        return default if v is None else v

    obs = stack_time_player(
        [[m["observation"][p] for p in players] for m in moments],
        obs_template,
    )
    prob = np.array(
        [[[pick(m, "selected_prob", p, 1.0)] for p in players]
         for m in moments],
        np.float32,
    )
    act = np.array(
        [[[pick(m, "action", p, 0)] for p in players] for m in moments],
        np.int32,
    )
    amask = np.stack(
        [
            np.stack(
                [
                    np.asarray(m["action_mask"][p], np.float32)
                    if m["action_mask"][p] is not None
                    else np.full(num_actions, ILLEGAL, np.float32)
                    for p in players
                ]
            )
            for m in moments
        ]
    )

    def channel(key):
        return np.array(
            [
                [
                    np.ravel(m[key][p]) if m[key][p] is not None else [0.0]
                    for p in players
                ]
                for m in moments
            ],
            np.float32,
        ).reshape(len(moments), len(players), -1)

    tmask = np.array(
        [[[m["selected_prob"][p] is not None] for p in players]
         for m in moments],
        np.float32,
    )
    omask = np.array(
        [[[m["observation"][p] is not None] for p in players]
         for m in moments],
        np.float32,
    )
    turn_idx = np.array(
        [players.index(m["turn"][0]) for m in moments], np.int64)

    return {
        "players": players,
        "obs": obs,
        "prob": prob,
        "act": act,
        "amask": amask,
        "value": channel("value"),
        "reward": channel("reward"),
        "return": channel("return"),
        "tmask": tmask,
        "omask": omask,
        "turn_idx": turn_idx,
    }


def _columnar_block(blob):
    global _col_cache_bytes
    hit = _COL_CACHE.get(blob)
    if hit is not None:
        _COL_CACHE.move_to_end(blob)
        return hit[0]
    col = _build_columnar(load_block(blob))
    nbytes = _nbytes_tree(col)
    if nbytes <= _COL_CACHE_MAX_BYTES // 4:
        _COL_CACHE[blob] = (col, nbytes)
        _col_cache_bytes += nbytes
        while _col_cache_bytes > _COL_CACHE_MAX_BYTES:
            _, (_, freed) = _COL_CACHE.popitem(last=False)
            _col_cache_bytes -= freed
    return col


def _tree_cat_slice(trees, spans):
    """Assemble the training window from per-block slices: each tree i
    contributes rows ``spans[i]`` and the pieces are concatenated.
    Slicing BEFORE concatenating copies only window bytes per draw."""
    first = trees[0]
    if isinstance(first, dict):
        return {k: _tree_cat_slice([t[k] for t in trees], spans)
                for k in first}
    if isinstance(first, (list, tuple)):
        return type(first)(
            _tree_cat_slice([t[i] for t in trees], spans)
            for i in range(len(first))
        )
    if len(trees) == 1:
        a, b = spans[0]
        return first[a:b]
    return np.concatenate(
        [t[a:b] for t, (a, b) in zip(trees, spans)])


def _take_turn(arr, turn_idx):
    """Gather each step's acting player's row: (T, P, ...) -> (T, 1, ...)."""
    idx = turn_idx.reshape((len(turn_idx), 1) + (1,) * (arr.ndim - 2))
    return np.take_along_axis(arr, idx, axis=1)


def _episode_tensors(ep, cfg):
    """Build one episode's (T, P, ...) tensors, padded to batch_steps."""
    blocks = [_columnar_block(blob) for blob in ep["moment"]]
    lo, hi = ep["start"] - ep["base"], ep["end"] - ep["base"]

    # per-block overlap with the window [lo, hi)
    spanned, spans, offset = [], [], 0
    for block in blocks:
        length = len(block["turn_idx"])
        a, b = max(0, lo - offset), min(length, hi - offset)
        if a < b:
            spanned.append(block)
            spans.append((a, b))
        offset += length

    def cat(key):
        return _tree_cat_slice([b[key] for b in spanned], spans)

    players_all = blocks[0]["players"]
    players = players_all
    if not cfg["turn_based_training"]:
        # solo training: one random seat per draw (reference
        # train.py:57-58 — same random.choice call per episode)
        players = [random.choice(players)]
    sel = [players_all.index(p) for p in players]

    if cfg["turn_based_training"] and not cfg["observation"]:
        # one acting seat per step: gather the turn player's data
        # (P_in = 1)
        turn_idx = cat("turn_idx")
        obs = tree_map(lambda a: _take_turn(a, turn_idx), cat("obs"))
        prob = _take_turn(cat("prob"), turn_idx)
        act = _take_turn(cat("act"), turn_idx)
        amask = _take_turn(cat("amask"), turn_idx)
    else:
        obs = tree_map(lambda a: a[:, sel], cat("obs"))
        prob = cat("prob")[:, sel]
        act = cat("act")[:, sel]
        amask = cat("amask")[:, sel]

    v = cat("value")[:, sel]
    rew = cat("reward")[:, sel]
    ret = cat("return")[:, sel]
    oc = np.array(
        [ep["outcome"][p] for p in players], np.float32
    ).reshape(1, len(players), 1)

    steps = hi - lo
    emask = np.ones((steps, 1, 1), np.float32)
    tmask = cat("tmask")[:, sel]
    omask = cat("omask")[:, sel]
    progress = (
        np.arange(ep["start"], ep["end"], dtype=np.float32)[:, None] / ep["total"]
    )

    # pad short slices to the static window; burn-in alignment keeps the
    # training start at index burn_in_steps
    batch_steps = cfg["burn_in_steps"] + cfg["forward_steps"]
    if steps < batch_steps:
        pad_b = cfg["burn_in_steps"] - (ep["train_start"] - ep["start"])
        pad_a = batch_steps - steps - pad_b
        obs = tree_map(lambda a: _pad_time(a, pad_b, pad_a), obs)
        prob = _pad_time(prob, pad_b, pad_a, 1.0)
        # after the terminal step the value bootstrap is the final outcome
        v = np.concatenate(
            [_pad_time(v, pad_b, 0), np.tile(oc, [pad_a, 1, 1])]
        )
        act = _pad_time(act, pad_b, pad_a)
        rew = _pad_time(rew, pad_b, pad_a)
        ret = _pad_time(ret, pad_b, pad_a)
        emask = _pad_time(emask, pad_b, pad_a)
        tmask = _pad_time(tmask, pad_b, pad_a)
        omask = _pad_time(omask, pad_b, pad_a)
        amask = _pad_time(amask, pad_b, pad_a, ILLEGAL)
        progress = _pad_time(progress, pad_b, pad_a, 1.0)

    return obs, {
        "selected_prob": prob,
        "value": v,
        "action": act,
        "outcome": oc,
        "reward": rew,
        "return": ret,
        "episode_mask": emask,
        "turn_mask": tmask,
        "observation_mask": omask,
        "action_mask": amask,
        "progress": progress,
    }


def make_batch(episodes, cfg):
    """Assemble a ``(B, T, P, ...)`` training batch from episode slices.

    With ``transfer_dtype: bfloat16`` the observation tree — by far the
    largest tensor — is emitted in bf16, halving host->device transfer
    bytes.  The update step computes in bf16 anyway under the default
    ``compute_dtype``, so the cast costs nothing numerically; all the
    small mask/target tensors stay float32.
    """
    obs_list, datum = [], []
    for ep in episodes:
        obs, row = _episode_tensors(ep, cfg)
        obs_list.append(obs)
        datum.append(row)

    batch = {k: np.stack([d[k] for d in datum]) for k in datum[0]}
    batch["observation"] = _encode_obs(
        tree_stack(obs_list), cfg.get("transfer_dtype"))
    return batch


def _encode_obs(obs, transfer_dtype):
    """Compact-transfer encodings for the observation tree (only the
    floating leaves; the update step restores the compute dtype on
    device).  ``uint8`` is opt-in for envs whose observations are
    integer-valued planes (binary boards): it quarters transfer bytes
    and is verified exact here, off the learner's critical path."""
    if transfer_dtype == "bfloat16" and BF16 is not None:
        return tree_map(
            lambda a: a.astype(BF16)
            if np.issubdtype(a.dtype, np.floating) else a,
            obs,
        )
    if transfer_dtype == "uint8":
        def quantize(a):
            if not np.issubdtype(a.dtype, np.floating):
                return a
            q = a.astype(np.uint8)
            if not np.array_equal(q.astype(a.dtype), a):
                raise ValueError(
                    "transfer_dtype 'uint8' requires integer-valued "
                    "observations in [0, 255]; this env's observations "
                    "are not — use 'bfloat16' instead")
            return q

        return tree_map(quantize, obs)
    return obs
