"""Positive: a bare acquire whose release is not finally-protected —
the first exception in between leaks the lock forever."""

import threading

GATE = threading.Lock()


def grab(work):
    GATE.acquire()
    result = work()
    GATE.release()
    return result
