"""NEG: the scalar is staged at the compute dtype, no promotion."""
import jax
import jax.numpy as jnp


@jax.jit
def forward(x):
    h = x.astype(jnp.bfloat16)
    scale = jnp.asarray(0.5, dtype=jnp.bfloat16)
    return h * scale
