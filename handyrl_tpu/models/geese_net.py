"""Torus-convolution residual policy-value net for Hungry Geese.

Capability parity with the reference ``GeeseNet``/``TorusConv2d``
(/root/reference/handyrl/envs/kaggle/hungry_geese.py:23-59): wrap-around
padding so convs see the board's toroidal topology, a 32-filter stem +
12 residual blocks, a policy head read from the goose's head cell and a
value head from [head features, board-average features] — NHWC Flax
with GroupNorm.

The whole body is a single fused conv stack: 7x11x32 activations are
tiny, so the batch dimension carries the MXU load — exactly the shape
of the learner's (B*T) flattened forward.
"""

import jax.numpy as jnp
from flax import linen as nn

from .blocks import pick_num_groups


class TorusConv(nn.Module):
    """Conv with wrap-around (toroidal) padding."""

    filters: int
    kernel: int = 3
    use_norm: bool = True

    @nn.compact
    def __call__(self, x):
        e = self.kernel // 2
        h = jnp.pad(x, ((0, 0), (e, e), (e, e), (0, 0)), mode="wrap")
        h = nn.Conv(self.filters, (self.kernel, self.kernel),
                    padding="VALID", use_bias=not self.use_norm)(h)
        if self.use_norm:
            h = nn.GroupNorm(num_groups=pick_num_groups(self.filters))(h)
        return h


class GeeseNet(nn.Module):
    filters: int = 32
    blocks: int = 12

    @nn.compact
    def __call__(self, obs, hidden=None):
        # obs: (B, 7, 11, 17); plane 0 marks the observer's head cell
        h = nn.relu(TorusConv(self.filters)(obs))
        for _ in range(self.blocks):
            h = nn.relu(h + TorusConv(self.filters)(h))

        head_mask = obs[..., :1]                      # (B, 7, 11, 1)
        h_head = (h * head_mask).sum(axis=(1, 2))     # (B, C)
        h_avg = h.mean(axis=(1, 2))                   # (B, C)

        policy = nn.Dense(4, use_bias=False)(h_head)
        value = jnp.tanh(
            nn.Dense(1, use_bias=False)(
                jnp.concatenate([h_head, h_avg], axis=-1)))
        return {"policy": policy, "value": value}
