"""NEG: bf16 inputs, fp32 accumulation via preferred_element_type."""
import jax
import jax.numpy as jnp


@jax.jit
def attention(q, k):
    qh = q.astype(jnp.bfloat16)
    kh = k.astype(jnp.bfloat16)
    return jnp.matmul(qh, kh, preferred_element_type=jnp.float32)
