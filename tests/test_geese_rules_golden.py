"""Golden-trace rule validation for the native Hungry Geese.

The reference delegates the game rules to the official simulator
(/root/reference/handyrl/envs/kaggle/hungry_geese.py:67 — ``from
kaggle_environments import make``); this repo reimplements them
natively.  ``kaggle_environments`` is not installable here, so each
trace below is HAND-DERIVED from the official interpreter's published
semantics (kaggle_environments/envs/hungry_geese/hungry_geese.py):

  1. per active agent, in index order: reversal check (kills only if
     the goose has a body, ``len(goose) > 1``), insert new head, pop
     tail unless the head landed on food (eat = grow);
  2. hunger: every 40th step each surviving mover pops a tail
     segment; shrinking to nothing is death;
  3. collisions on the POSITION HISTOGRAM of all goose cells after
     movement: any head on a cell occupied more than once dies
     (head-on kills every head involved; pass-through swaps of
     length-1 geese are legal because only the final histogram is
     consulted);
  4. rewards update for still-ACTIVE agents only, so a dying goose
     keeps its previous step's reward = (step * step_weight + length
     at death), making survival time dominate length in the final
     pairwise ranking.

Board addressing: cell = row * 11 + col on the 7x11 torus.
Actions: 0 NORTH (row-1), 1 SOUTH (row+1), 2 WEST (col-1), 3 EAST.
"""

import pytest

from handyrl_tpu.envs.kaggle.hungry_geese import (
    EPISODE_STEPS,
    HUNGER_RATE,
    NUM_AGENTS,
    Environment,
)

NORTH, SOUTH, WEST, EAST = 0, 1, 2, 3


def set_state(env, geese, food=(), last_actions=None, step_count=0):
    """Pin the full game state; dead seats are any with an empty
    goose.  Rewards re-derive exactly as a live game would have them
    at this point (active geese re-sync, dead geese keep 0)."""
    env.geese = [list(g) for g in geese]
    env.food = set(food)
    env.statuses = ["ACTIVE" if g else "DONE" for g in geese]
    env.rewards = [0] * NUM_AGENTS
    env.last_actions = dict(last_actions or {})
    env.prev_heads = [g[0] if g else None for g in geese]
    env.step_count = step_count
    env._sync_rewards()


@pytest.fixture
def env():
    return Environment()


def test_head_on_collision_kills_both(env):
    # A at 0 moving EAST and B at 2 moving WEST meet head-on at 1
    set_state(env, [[0], [2], [], []], food=[40, 50], step_count=5)
    env.step({0: EAST, 1: WEST})
    assert env.statuses[0] == "DONE" and env.statuses[1] == "DONE"
    assert env.geese[0] == [] and env.geese[1] == []
    assert env.terminal()
    # equal length, same death step -> they tie each other and both
    # outrank the two seats that were already dead
    out = env.outcome()
    assert out[0] == out[1] == pytest.approx(2 / 3)
    assert out[2] == out[3] == pytest.approx(-2 / 3)


def test_pass_through_swap_is_legal_for_bodiless_geese(env):
    # adjacent length-1 geese swap cells: the official interpreter
    # only consults the AFTER-move histogram, so no cell is occupied
    # twice and both survive
    set_state(env, [[1], [2], [], []], food=[40, 50], step_count=5)
    env.step({0: EAST, 1: WEST})
    assert env.geese[0] == [2] and env.geese[1] == [1]
    assert env.statuses[0] == "ACTIVE" and env.statuses[1] == "ACTIVE"


def test_swap_with_a_body_kills_the_crosser(env):
    # A has a body: A [1,0] EAST -> {2,1}; B [2] WEST lands on 1,
    # still occupied by A's body -> histogram count 2 -> B dies;
    # A's head lands on 2, vacated by B -> count 1 -> A survives.
    # (C is a far-away bystander keeping the episode alive.)
    set_state(env, [[1, 0], [2], [60], []], food=[40, 50],
              step_count=5)
    env.step({0: EAST, 1: WEST, 2: WEST})
    assert env.geese[0] == [2, 1]
    assert env.statuses[0] == "ACTIVE"
    assert env.statuses[1] == "DONE" and env.geese[1] == []


def test_neck_reversal_dies_but_bodiless_reversal_lives(env):
    # A [10, 11] came from the east (last action WEST): EAST reverses
    # its neck -> death.  B [30] also reverses, but a length-1 goose
    # has no neck -> legal move.
    set_state(env, [[10, 11], [30], [66], []], food=[60, 61],
              last_actions={0: WEST, 1: WEST}, step_count=5)
    env.step({0: EAST, 1: EAST, 2: WEST})
    assert env.statuses[0] == "DONE" and env.geese[0] == []
    assert env.statuses[1] == "ACTIVE" and env.geese[1] == [31]


def test_eat_and_starve_same_step_cancel(env):
    # hunger fires on the transition into step 40 (native step_count
    # 39 -> 40).  A eats on the hunger step: insert head + keep tail
    # (grow), then hunger pops one segment -> net length unchanged,
    # food consumed.  B (length 1, no food) starves to death.
    step = HUNGER_RATE - 1
    set_state(env, [[5, 4], [20], [70, 69], []], food=[6, 60],
              last_actions={0: EAST, 1: EAST, 2: EAST},
              step_count=step)
    env.step({0: EAST, 1: EAST, 2: EAST})
    assert env.geese[0] == [6, 5]
    assert env.statuses[0] == "ACTIVE"
    assert env.statuses[1] == "DONE" and env.geese[1] == []
    assert env.geese[2] == [71]  # hunger shrinks the bystander too
    assert 6 not in env.food
    assert len(env.food) == 2  # respawned back up to MIN_FOOD
    # control: one step earlier, eating grows and nobody starves
    set_state(env, [[5, 4], [20], [70, 69], []], food=[6, 60],
              last_actions={0: EAST, 1: EAST, 2: EAST},
              step_count=step - 1)
    env.step({0: EAST, 1: EAST, 2: EAST})
    assert env.geese[0] == [6, 5, 4]
    assert env.statuses[1] == "ACTIVE" and env.geese[1] == [21]
    assert env.geese[2] == [71, 70]


def test_simultaneous_death_ranks_by_frozen_length(env):
    # the last two geese die head-on in the same step: both keep the
    # PREVIOUS step's reward, where survival step ties and A's length
    # 3 beats B's length 2 -> A first, B second, earlier deaths last
    set_state(env, [[0, 11, 22], [2, 13], [], []], food=[40, 50],
              step_count=8)
    env.step({0: EAST, 1: WEST})
    assert env.terminal()
    assert env.rewards[0] > env.rewards[1] > 0
    out = env.outcome()
    assert out[0] == pytest.approx(1.0)
    assert out[1] == pytest.approx(1 / 3)
    assert out[2] == out[3] == pytest.approx(-2 / 3)


def test_survival_step_dominates_length(env):
    # B (length 1) outlives A (length 5) by one step -> B ranks
    # higher: the step weight (78) exceeds any attainable length
    set_state(env, [[0, 11, 22, 33, 44], [60], [], []],
              food=[40, 50], step_count=8)
    # A reverses into its own neck and dies; B survives the step
    env.last_actions[0] = WEST
    env.step({0: EAST, 1: WEST})
    assert env.statuses[0] == "DONE"
    assert env.statuses[1] == "DONE"  # sole survivor -> episode over
    assert env.rewards[1] > env.rewards[0]
    out = env.outcome()
    assert out[1] > out[0]


def test_episode_step_cap(env):
    # two geese far apart idle until the 200-step cap ends the game
    set_state(env, [[0], [60], [], []], food=[40, 50],
              step_count=EPISODE_STEPS - 2)
    env.step({0: EAST, 1: WEST})
    assert env.terminal()
    assert env.statuses[0] == "DONE" and env.statuses[1] == "DONE"
    # both survived to the cap with equal length: a clean tie
    out = env.outcome()
    assert out[0] == out[1]
