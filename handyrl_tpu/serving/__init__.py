"""handyrl_tpu.serving — the SLO-bound network serving tier.

A network-facing continuous-batching frontend over the pipeline
inference core (docs/serving.md): remote clients' requests feed the
same ``pipeline.InferenceService`` batching window as the colocated
shm workers, with per-request latency histograms + QPS, SLO-bound
admission control (typed shed replies, never silent drops), and
multi-model routing for epoch-pinned requests (league/opponent-pool
snapshots as first-class serving targets).

Public surface:

  * :class:`.config.ServingConfig` — the validated ``serving.*`` keys;
  * :class:`.config.RouterConfig` — the validated ``router.*`` keys;
  * :class:`.frontend.ServingFrontend` — the learner-side acceptor;
  * :class:`.registry.ServiceRegistry` /
    :class:`.registry.ReplicaAnnouncer` — the pool bulletin and the
    replica-side heartbeat loop (docs/serving.md "Pool routing");
  * :class:`.router.RouterFrontend` — the one-endpoint pool router;
  * :class:`.client.ServeClient` (+ :class:`.client.ShedError` /
    :class:`.client.ServeError`) — the consumer SDK.

The config classes import eagerly (config validation reads them
without jax); everything else resolves lazily (PEP 562) so importing
the package stays cheap for config-only consumers — the same
convention as ``handyrl_tpu.anakin``.
"""

from .config import RouterConfig, ServingConfig  # noqa: F401

_LAZY = {
    "ServingFrontend": ("handyrl_tpu.serving.frontend",
                        "ServingFrontend"),
    "ServiceRegistry": ("handyrl_tpu.serving.registry",
                        "ServiceRegistry"),
    "ReplicaAnnouncer": ("handyrl_tpu.serving.registry",
                         "ReplicaAnnouncer"),
    "RouterFrontend": ("handyrl_tpu.serving.router", "RouterFrontend"),
    "ServeClient": ("handyrl_tpu.serving.client", "ServeClient"),
    "ShedError": ("handyrl_tpu.serving.client", "ShedError"),
    "ServeError": ("handyrl_tpu.serving.client", "ServeError"),
}

__all__ = ["ServingConfig", "RouterConfig", *_LAZY]


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
