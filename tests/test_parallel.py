"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax

from handyrl_tpu.parallel import MeshSpec, make_mesh, make_sharded_update_step
from handyrl_tpu.parallel.mesh import batch_sharding, param_sharding


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")


def test_mesh_spec_from_config():
    spec = MeshSpec.from_config({"dp": 4, "tp": 2})
    assert spec.size == 8 and spec.shape() == (4, 1, 2)
    with pytest.raises(ValueError):
        MeshSpec.from_config({"bogus": 2})


def test_make_mesh_default_all_dp():
    _need_devices(8)
    mesh = make_mesh()
    assert mesh.shape["dp"] == len(jax.devices())
    assert mesh.shape["tp"] == 1


def test_param_sharding_tp_rule():
    _need_devices(8)
    mesh = make_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices()[:8])
    params = {
        "dense": {"kernel": np.zeros((64, 256)), "bias": np.zeros((256,))},
        "conv": {"kernel": np.zeros((3, 3, 32, 128))},
        "head": {"kernel": np.zeros((32, 9))},
    }
    shardings = param_sharding(mesh, params)
    # wide kernels shard output features over tp
    assert shardings["dense"]["kernel"].spec == jax.sharding.PartitionSpec(None, "tp")
    assert shardings["conv"]["kernel"].spec == jax.sharding.PartitionSpec(
        None, None, None, "tp")
    # biases and narrow heads replicate
    assert shardings["dense"]["bias"].spec == jax.sharding.PartitionSpec()
    assert shardings["head"]["kernel"].spec == jax.sharding.PartitionSpec()


@pytest.mark.slow
def test_sharded_update_step_dp():
    """Full training step, batch sharded dp=4: compiles, runs, finite."""
    _need_devices(4)
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import _build_model_and_batch

    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer

    mesh = make_mesh(MeshSpec(dp=4), devices=jax.devices()[:4])
    model, batch, cfg = _build_model_and_batch(batch_size=4)
    loss_cfg = LossConfig.from_config(cfg)
    optimizer = make_optimizer(1e-3)
    params, opt_state = model.params, None
    opt_state = optimizer.init(params)

    update = make_sharded_update_step(model, loss_cfg, optimizer, mesh, params)
    params2, opt_state, metrics = update(params, opt_state, batch)
    assert np.isfinite(float(metrics["total"]))
    # params changed and stayed replicated
    leaf = jax.tree.leaves(params2)[0]
    assert leaf.sharding.is_fully_replicated


@pytest.mark.slow
def test_dryrun_multichip_8():
    _need_devices(8)
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)
