"""Comm-substrate unit tests: elastic churn and checkpoint hygiene.

The reference's listeners accept at most ~1024 lifetime connections
before going deaf; ours must survive unbounded worker churn
(/root/reference/docs/large_scale_training.md scale claim)."""

import os
import pickle
import threading

from handyrl_tpu.connection import (
    QueueCommunicator,
    accept_socket_connections,
    find_free_port,
    open_socket_connection,
)


def test_listener_survives_1500_connect_disconnect_cycles():
    """Elastic churn far past the old 1024 lifetime-accept cap: every
    cycle must still be served."""
    port = find_free_port()
    served = []
    stop = threading.Event()

    def serve():
        for conn in accept_socket_connections(port=port, timeout=0.2):
            if stop.is_set():
                return
            if conn is None:
                continue
            try:
                conn.send(len(served))
                served.append(1)
            finally:
                conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    # the listener thread binds lazily on its first accept iteration
    import time

    for _ in range(100):
        try:
            probe = open_socket_connection("127.0.0.1", port)
            probe.recv()
            probe.close()
            break
        except ConnectionRefusedError:
            time.sleep(0.05)

    cycles = 1500
    got = 1  # the readiness probe was cycle 0
    for i in range(cycles):
        conn = open_socket_connection("127.0.0.1", port)
        assert conn.recv() == got
        got += 1
        conn.close()
    stop.set()
    t.join(timeout=5)
    assert got == cycles + 1


def test_checkpoint_retention_and_atomicity(tmp_path, monkeypatch):
    """keep-last-N pruning retains the newest N epochs plus every K-th,
    and checkpoint writes leave no .tmp debris."""
    monkeypatch.chdir(tmp_path)
    from handyrl_tpu.learner import Learner, model_path

    learner = Learner.__new__(Learner)  # no server/env needed
    learner.args = {"checkpoint_keep_last": 3, "checkpoint_keep_every": 5}
    learner.model_epoch = 0
    learner.primary = True

    class FakeModel:
        params = {"w": 0}

    for _ in range(12):
        Learner.update_model(learner, FakeModel(), steps=learner.model_epoch)

    kept = sorted(
        int(f.split(".")[0]) for f in os.listdir("models")
        if f[0].isdigit())
    # newest 3 = {10, 11, 12}; every 5th = {5, 10}
    assert kept == [5, 10, 11, 12]
    assert not any(f.endswith(".tmp") for f in os.listdir("models"))
    with open(model_path(12), "rb") as f:
        assert pickle.load(f)["epoch"] == 12
    with open(os.path.join("models", "latest.ckpt"), "rb") as f:
        assert pickle.load(f)["epoch"] == 12


def test_unknown_verbs_counted_and_logged_once(capsys):
    """The runtime counterpart of commlint's unhandled-verb: unknown
    requests are counted per verb in drop_stats() and logged once per
    verb name, not once per message."""
    hub = QueueCommunicator()
    try:
        for _ in range(3):
            hub.note_unknown_verb("frobnicate")
        hub.note_unknown_verb("zap")
        out = capsys.readouterr().out
        assert out.count("'frobnicate'") == 1    # logged once
        assert out.count("'zap'") == 1
        stats = hub.drop_stats()
        assert stats["unknown_verbs"] == 4
        assert hub.unknown_verbs == {"frobnicate": 3, "zap": 1}
    finally:
        hub.shutdown()


def test_unknown_verbs_surface_in_fleet_registry_snapshot():
    """unknown_verbs rides drop_stats() into the FleetRegistry but is
    reported as its own metric, NOT folded into conn_drops."""
    from handyrl_tpu.resilience import FleetRegistry

    reg = FleetRegistry(heartbeat_timeout=30.0, clock=lambda: 0.0)
    reg.record_drops({"send_drops": 2, "disconnects": 1,
                      "unknown_verbs": 7})
    snap = reg.snapshot(now=0.0)
    assert snap["unknown_verbs"] == 7
    assert snap["conn_drops"] == 3
