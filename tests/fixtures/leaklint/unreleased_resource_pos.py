"""Positive: a function-local socket reaches an exit still live — no
release exists on any path, and the caller never received the handle,
so the fd is simply gone (one per call)."""

import socket


def fetch_banner(host):
    sock = socket.create_connection((host, 80))
    data = sock.recv(64)
    return data


def probe(host, deep):
    sock = socket.create_connection((host, 80))
    if not deep:
        return None  # early return sidesteps the release below
    sock.send(b"ping")
    sock.close()
    return True
