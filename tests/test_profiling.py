"""utils.profiling: the TraceWindow step-window state machine.

The window drives ``jax.profiler`` start/stop from the update-step
count; the tests stub the profiler (monkeypatched module attribute) so
the semantics — start at ``start_step``, stop at ``stop_step``,
one-shot, close-while-active flush, inactive with no ``trace_dir`` —
are asserted without touching a real trace backend."""

import jax
import pytest

from handyrl_tpu.utils.profiling import SectionTimers, TraceWindow


class _StubProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, trace_dir):
        self.calls.append(("start", trace_dir))

    def stop_trace(self):
        self.calls.append(("stop", None))


@pytest.fixture()
def profiler(monkeypatch):
    stub = _StubProfiler()
    monkeypatch.setattr(jax, "profiler", stub)
    return stub


def test_window_starts_and_stops_at_configured_steps(profiler):
    win = TraceWindow("/tmp/tw", start_step=3, stop_step=5)
    for _ in range(2):
        win.tick()
    assert profiler.calls == [] and not win.active
    win.tick()                       # step 3: start fires
    assert profiler.calls == [("start", "/tmp/tw")]
    assert win.active and not win.done
    win.tick()                       # step 4: inside the window
    assert len(profiler.calls) == 1
    win.tick()                       # step 5: stop fires, one-shot
    assert profiler.calls[-1] == ("stop", None)
    assert win.done and not win.active


def test_window_is_one_shot_after_stop(profiler):
    win = TraceWindow("/tmp/tw", start_step=1, stop_step=2)
    for _ in range(6):
        win.tick()
    # exactly one start/stop pair no matter how many later ticks
    assert profiler.calls == [("start", "/tmp/tw"), ("stop", None)]
    assert win.step == 2             # done windows stop counting


def test_close_while_active_stops_the_trace(profiler):
    win = TraceWindow("/tmp/tw", start_step=1, stop_step=10)
    win.tick()
    assert win.active
    win.close()                      # early shutdown mid-window
    assert profiler.calls == [("start", "/tmp/tw"), ("stop", None)]
    assert win.done and not win.active
    win.tick()                       # and it stays closed
    assert len(profiler.calls) == 2


def test_close_when_never_started_is_a_noop(profiler):
    win = TraceWindow("/tmp/tw", start_step=5, stop_step=6)
    win.tick()
    win.close()
    assert profiler.calls == []
    assert not win.active


def test_empty_trace_dir_disables_the_window(profiler):
    win = TraceWindow("", start_step=1, stop_step=2)
    for _ in range(4):
        win.tick()
    win.close()
    assert profiler.calls == []
    assert win.done and win.step == 0


def test_section_timers_accumulate_and_reset():
    timers = SectionTimers()
    with timers.section("update"):
        pass
    with timers.section("update"):
        pass
    snap = timers.snapshot()
    assert snap["update"]["n"] == 2
    assert snap["update"]["sec"] >= 0.0
    # snapshot(reset=True) is the default: the next epoch starts clean
    assert timers.snapshot() == {}
