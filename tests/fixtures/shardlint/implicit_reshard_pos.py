"""Fixture: feeding a jit an array laid out differently from its
declared in_shardings — XLA inserts a silent copy, and the donated
position's donation is defeated."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), ("dp", "tp"))


def train_step(mesh, params, batch):
    rep = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("dp"))
    step = jax.jit(lambda p, b: (p, b.sum()), in_shardings=(rep, dp),
                   donate_argnums=(0,))
    params = jax.device_put(params, dp)  # but the jit expects P()
    return step(params, batch)


class InferShardings:
    def __init__(self, params, obs):
        self.params = params
        self.obs = obs


def infer_shardings(mesh):
    # the inference_shardings shape: a struct of per-role specs whose
    # fields must resolve through the builder-return summary
    return InferShardings(params=NamedSharding(mesh, P()),
                          obs=NamedSharding(mesh, P("dp")))


def serve_step(mesh, params, obs):
    shards = infer_shardings(mesh)
    fwd = jax.jit(lambda p, o: (p * o).sum(),
                  in_shardings=(shards.params, shards.obs))
    obs = jax.device_put(obs, shards.params)  # but the jit wants P('dp')
    return fwd(params, obs)
