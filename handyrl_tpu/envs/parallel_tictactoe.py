"""Simultaneous-move Tic-Tac-Toe.

Both players submit an action each transition; the env applies exactly
one of them, chosen uniformly at random.  Exercises the framework's
simultaneous-game path (``turns()`` = all players).  Behavioral parity
with /root/reference/handyrl/envs/parallel_tictactoe.py:13-74.
"""

import random

import numpy as np

from .tictactoe import Environment as TicTacToe, WIN_LINES, FIRST, SECOND, GLYPH, COLS, ROWS


class Environment(TicTacToe):
    MARKS = (FIRST, SECOND)  # player index -> mark

    def step(self, actions):
        chosen = random.choice(list(actions.keys()))
        self._apply(actions[chosen], chosen)

    def _apply(self, action, player):
        mark = self.MARKS[player]
        self.cells[action] = mark
        sums = self.cells[WIN_LINES].sum(axis=1)
        if np.any(sums == 3 * mark):
            self.winner = mark
        self.history.append((mark, action))

    def turn(self):
        return NotImplementedError()

    def turns(self):
        return self.players()

    def diff_info(self, player=None):
        if not self.history:
            return ""
        mark, action = self.history[-1]
        return self.action2str(action) + ":" + GLYPH[mark]

    def update(self, info, reset):
        if reset:
            self.reset()
        else:
            s_action, s_mark = info.split(":")
            player = "OX".index(s_mark)
            self._apply(self.str2action(s_action), player)

    def __str__(self):
        board = self.cells.reshape(3, 3)
        lines = ["  " + " ".join(COLS)]
        for r in range(3):
            lines.append(ROWS[r] + " " + " ".join(GLYPH[v] for v in board[r]))
        return "\n".join(lines)


if __name__ == "__main__":
    e = Environment()
    for _ in range(5):
        e.reset()
        while not e.terminal():
            e.step({p: random.choice(e.legal_actions(p)) for p in e.turns()})
        print(e)
        print(e.outcome())
