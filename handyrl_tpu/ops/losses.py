"""Forward prediction and loss composition — the jitted learner math.

Semantic parity with /root/reference/handyrl/train.py:128-268:
  * feed-forward nets run one big flattened forward over (B*T*P, ...)
    — MXU-friendly: a single large batched matmul/conv stream;
  * recurrent nets run a ``lax.scan`` over time with observation-mask
    hidden blending, turn-based hidden gathering, and gradient-free
    burn-in (``stop_gradient`` per step — GroupNorm models have no
    train/eval mode divergence, so burn-in needs no mode switch);
  * losses: V-Trace/UPGO/TD/MC targets on detached values, importance
    ratios clipped at ``rho_clip``/``c_clip`` (both 1 by default, the
    reference behavior), two-player zero-sum value symmetrization,
    terminal outcome bootstrap, entropy regularization decayed by
    episode progress;
  * ``update_algorithm: impact`` (IMPACT, arXiv:1912.00167) swaps the
    policies behind the math: importance ratios are computed against a
    maintained TARGET network instead of the live learner policy (so
    V-Trace corrections stay stable however stale the episodes are),
    and the policy loss becomes a PPO-style two-sided surrogate clip of
    the current/target ratio.  The target params ride the jitted update
    step (ops.update) and refresh by hard sync or Polyak average.

Everything here is pure and traced once per batch geometry.
"""

from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .targets import compute_target

# reference defaults for the importance-ratio clips; the live values
# come from LossConfig (rho_clip / c_clip surface them as config keys)
CLIP_RHO = 1.0
CLIP_C = 1.0


class LossConfig(NamedTuple):
    """Static (trace-time) training hyper-parameters."""

    turn_based_training: bool
    observation: bool
    burn_in_steps: int
    lambda_: float
    gamma: float
    policy_target: str
    value_target: str
    entropy_regularization: float
    entropy_regularization_decay: float
    # off-policy correction knobs (defaults keep existing runs
    # bit-identical; read with .get so raw pre-PR config dicts work)
    rho_clip: float = CLIP_RHO
    c_clip: float = CLIP_C
    # "standard" = live-policy ratios + score-function policy loss;
    # "impact" = target-network ratios + clipped surrogate objective
    update_algorithm: str = "standard"
    surrogate_clip: float = 0.2
    # target-network refresh cadence (impact only): hard sync every
    # `target_update_interval` optimizer steps, or Polyak averaging
    # with `target_update_tau` when > 0 (tau wins if both are set)
    target_update_interval: int = 0
    target_update_tau: float = 0.0

    @classmethod
    def from_config(cls, cfg) -> "LossConfig":
        return cls(
            turn_based_training=bool(cfg["turn_based_training"]),
            observation=bool(cfg["observation"]),
            burn_in_steps=int(cfg["burn_in_steps"]),
            lambda_=float(cfg["lambda"]),
            gamma=float(cfg["gamma"]),
            policy_target=str(cfg["policy_target"]),
            value_target=str(cfg["value_target"]),
            entropy_regularization=float(cfg["entropy_regularization"]),
            entropy_regularization_decay=float(cfg["entropy_regularization_decay"]),
            rho_clip=float(cfg.get("rho_clip", CLIP_RHO) or CLIP_RHO),
            c_clip=float(cfg.get("c_clip", CLIP_C) or CLIP_C),
            update_algorithm=str(
                cfg.get("update_algorithm", "standard") or "standard"),
            surrogate_clip=float(cfg.get("surrogate_clip", 0.2) or 0.2),
            target_update_interval=int(
                cfg.get("target_update_interval", 0) or 0),
            target_update_tau=float(
                cfg.get("target_update_tau", 0.0) or 0.0),
        )


def _flatten_lead(tree, n):
    return jax.tree.map(
        lambda a: a.reshape((-1,) + a.shape[n:]), tree
    )


def forward_prediction(apply_fn: Callable, params, hidden, batch,
                       cfg: LossConfig) -> Dict[str, jnp.ndarray]:
    """Run the net over a (B, T, P_in, ...) batch -> (B, T, P_in/P, ...).

    ``hidden`` is the initial (B, P, ...) recurrent state or None.
    """
    observations = batch["observation"]
    B, T, P_in = batch["action"].shape[:3]

    if hidden is None:
        obs_flat = _flatten_lead(observations, 3)  # (B*T*P_in, ...)
        out = apply_fn(params, obs_flat, None)
        outputs = {
            k: v.reshape((B, T, P_in) + v.shape[1:])
            for k, v in out.items()
            if v is not None
        }
    else:
        omask_full = batch["observation_mask"]  # (B, T, P, 1)
        # seats the net was applied to this step: the single acting seat
        # in turn-based mode, every player otherwise
        P_model = 1 if (cfg.turn_based_training and not cfg.observation) \
            else omask_full.shape[2]

        def step(carry, xs):
            hidden = carry
            obs_t, omask_t, t = xs  # (B, P_in, ...), (B, P, 1), scalar

            # zero hidden where the player did not observe (episode
            # starts inside the window restart the recurrence)
            def mask_like(h):
                return omask_t.reshape(omask_t.shape[:2] + (1,) * (h.ndim - 2))

            h_masked = jax.tree.map(lambda h: h * mask_like(h), hidden)
            if cfg.turn_based_training and not cfg.observation:
                # only the turn player's hidden is non-zero: the P-sum
                # gathers it into the single acting seat
                h_in = jax.tree.map(lambda h: h.sum(axis=1), h_masked)
            else:
                h_in = _flatten_lead(h_masked, 2)  # (B*P, ...)

            obs_flat = _flatten_lead(obs_t, 2)  # (B*P_in, ...)
            out = apply_fn(params, obs_flat, h_in)
            out = {
                k: v.reshape((B, P_in) + v.shape[1:]) if k != "hidden"
                else v
                for k, v in out.items()
                if v is not None
            }
            next_hidden = out.pop("hidden")
            next_hidden = jax.tree.map(
                lambda h: h.reshape((B, P_model) + h.shape[1:]),
                next_hidden,
            )

            # burn-in steps contribute no gradient
            burn = t < cfg.burn_in_steps
            out = jax.tree.map(
                lambda v: jnp.where(burn, lax.stop_gradient(v), v), out
            )
            next_hidden = jax.tree.map(
                lambda v: jnp.where(burn, lax.stop_gradient(v), v), next_hidden
            )

            # write the new hidden into observed seats only
            new_hidden = jax.tree.map(
                lambda h, nh: h * (1 - mask_like(h)) + nh * mask_like(h),
                hidden,
                next_hidden,
            )
            return new_hidden, out

        xs = (
            jax.tree.map(lambda a: jnp.moveaxis(a, 1, 0), observations),
            jnp.moveaxis(omask_full, 1, 0),
            jnp.arange(T),
        )
        _, outs = lax.scan(step, hidden, xs)
        outputs = {k: jnp.moveaxis(v, 0, 1) for k, v in outs.items()}

    # mask heads: policy by turn, scalar heads by observation
    result = {}
    for k, o in outputs.items():
        if k == "policy":
            o = o * batch["turn_mask"]  # may broadcast P_in -> P
            if o.shape[2] > P_in:
                # turn-alternating batch: collapse back to the acting seat
                o = o.sum(axis=2, keepdims=True)
            result[k] = o - batch["action_mask"]
        else:
            result[k] = o * batch["observation_mask"]
    return result


def _huber(x):
    """Smooth-L1 with delta=1 (matches F.smooth_l1_loss)."""
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0, 0.5 * x * x, absx - 0.5)


def _masked_entropy(logits, axis=-1):
    """Categorical entropy that is exact-zero-safe for -1e32 masked
    logits (softmax underflows to exactly 0, and 0 * finite = 0)."""
    lsm = jax.nn.log_softmax(logits, axis=axis)
    p = jnp.exp(lsm)
    return -jnp.sum(p * jnp.clip(lsm, -1e32, 0.0), axis=axis)


def compose_losses(outputs, log_selected_policies, total_advantages,
                   targets, batch, cfg: LossConfig, policy_loss=None):
    """Combine policy / value / return / entropy losses (summed, not
    averaged — the lr schedule normalizes by the data-count EMA).

    ``policy_loss`` (per-element, pre-mask) replaces the default
    score-function term when given — the IMPACT surrogate plugs in
    here without duplicating the rest of the composition."""
    tmasks = batch["turn_mask"]
    omasks = batch["observation_mask"]

    losses = {}
    dcnt = tmasks.sum()

    if policy_loss is None:
        policy_loss = -log_selected_policies * total_advantages
    losses["p"] = (policy_loss * tmasks).sum()
    if "value" in outputs:
        losses["v"] = (
            ((outputs["value"] - targets["value"]) ** 2) * omasks
        ).sum() / 2
    if "return" in outputs:
        losses["r"] = (
            _huber(outputs["return"] - targets["return"]) * omasks
        ).sum()

    entropy = _masked_entropy(outputs["policy"]) * tmasks.sum(-1)  # (B,T,P)
    losses["ent"] = entropy.sum()

    base_loss = losses["p"] + losses.get("v", 0.0) + losses.get("r", 0.0)
    decay_weight = 1.0 - batch["progress"] * (
        1.0 - cfg.entropy_regularization_decay
    )
    entropy_loss = (entropy * decay_weight).sum() * -cfg.entropy_regularization
    losses["total"] = base_loss + entropy_loss

    return losses, dcnt


def compute_loss(apply_fn: Callable, params, batch, hidden, cfg: LossConfig,
                 target_params=None):
    """Full forward + target computation + loss composition.

    With ``cfg.update_algorithm == "impact"`` and ``target_params``
    given, a second (gradient-free) forward through the target network
    provides the correction policy and the bootstrap values: V-Trace
    ratios are target/behavior, the policy loss is the clipped
    surrogate of current/target, and the reported ``clip_frac`` is the
    fraction of acting steps whose surrogate ratio hit the clip."""
    impact = cfg.update_algorithm == "impact" and target_params is not None
    outputs = forward_prediction(apply_fn, params, hidden, batch, cfg)
    tgt_outputs = None
    if impact:
        # gradients only flow w.r.t. `params` (grad argnums in the
        # update core), but stop_gradient keeps the trace honest even
        # if a caller differentiates more broadly
        tgt_outputs = forward_prediction(
            apply_fn, target_params, hidden, batch, cfg)
        tgt_outputs = {k: lax.stop_gradient(v)
                       for k, v in tgt_outputs.items()}
    if cfg.burn_in_steps > 0:
        b = cfg.burn_in_steps
        batch = {
            k: v[:, b:] if v.shape[1] > 1 else v for k, v in batch.items()
            if k != "observation"
        } | {"observation": batch["observation"]}
        outputs = {k: v[:, b:] for k, v in outputs.items()}
        if tgt_outputs is not None:
            tgt_outputs = {k: v[:, b:] for k, v in tgt_outputs.items()}

    actions = batch["action"]
    emasks = batch["episode_mask"]
    omasks = batch["observation_mask"]
    tmasks = batch["turn_mask"]
    value_target_masks, return_target_masks = omasks, omasks

    log_selected_b = (
        jnp.log(jnp.clip(batch["selected_prob"], 1e-16, 1.0)) * emasks
    )
    log_policy = jax.nn.log_softmax(outputs["policy"], axis=-1)
    log_selected_t = (
        jnp.take_along_axis(log_policy, actions, axis=-1) * emasks
    )
    log_selected_g = None
    if impact:
        log_policy_g = jax.nn.log_softmax(tgt_outputs["policy"], axis=-1)
        log_selected_g = (
            jnp.take_along_axis(log_policy_g, actions, axis=-1) * emasks
        )

    # importance-sampling ratios (behavior -> correction policy),
    # clipped at rho_clip/c_clip.  Standard: the live learner policy.
    # IMPACT: the target network's policy — stable under staleness,
    # because the correction target moves on the sync cadence instead
    # of every optimizer step.
    if impact:
        log_rhos = log_selected_g - log_selected_b
    else:
        log_rhos = lax.stop_gradient(log_selected_t) - log_selected_b
    # exp of an unbounded log-ratio overflows to inf on the first
    # badly-stale batch; +/-20 is far beyond the useful range (the
    # ratios are clipped to rho_clip/c_clip right below) but keeps
    # the op finite
    rhos = jnp.exp(jnp.clip(log_rhos, -20.0, 20.0))
    clipped_rhos = jnp.clip(rhos, 0.0, cfg.rho_clip)
    cs = jnp.clip(rhos, 0.0, cfg.c_clip)

    if impact:
        # IMPACT bootstraps targets from the TARGET network's heads
        outputs_nograd = dict(tgt_outputs)
    else:
        outputs_nograd = {k: lax.stop_gradient(v)
                          for k, v in outputs.items()}

    if "value" in outputs_nograd:
        values_nograd = outputs_nograd["value"]
        if cfg.turn_based_training and values_nograd.shape[2] == 2:
            # two-player zero-sum: average own value with the negated
            # opponent view wherever either observed
            values_opp = -jnp.flip(values_nograd, axis=2)
            omasks_opp = jnp.flip(omasks, axis=2)
            values_nograd = (
                values_nograd * omasks + values_opp * omasks_opp
            ) / (omasks + omasks_opp + 1e-8)
            value_target_masks = jnp.clip(omasks + omasks_opp, 0.0, 1.0)
        # beyond the terminal step the target is the final outcome
        outputs_nograd["value"] = (
            values_nograd * emasks + batch["outcome"] * (1 - emasks)
        )

    targets, advantages = {}, {}
    value_args = (
        outputs_nograd.get("value", None), batch["outcome"], None,
        cfg.lambda_, 1.0, clipped_rhos, cs, value_target_masks,
    )
    return_args = (
        outputs_nograd.get("return", None), batch["return"], batch["reward"],
        cfg.lambda_, cfg.gamma, clipped_rhos, cs, return_target_masks,
    )

    targets["value"], advantages["value"] = compute_target(
        cfg.value_target, *value_args
    )
    targets["return"], advantages["return"] = compute_target(
        cfg.value_target, *return_args
    )
    if cfg.policy_target != cfg.value_target:
        _, advantages["value"] = compute_target(cfg.policy_target, *value_args)
        _, advantages["return"] = compute_target(cfg.policy_target, *return_args)

    denom = tmasks.sum() + 1e-8
    if impact:
        # IMPACT surrogate objective: the V-Trace rho factor is
        # replaced by the current/target ratio under a two-sided PPO
        # clip — maximize min(r*A, clip(r, 1-eps, 1+eps)*A)
        adv = sum(advantages.values())
        # same finite-exp discipline as the rhos above: the surrogate
        # clip bounds the USED ratio to 1 +/- eps, so clamping the
        # exponent changes nothing numerically useful
        ratio = jnp.exp(jnp.clip(log_selected_t - log_selected_g,
                                 -20.0, 20.0))
        eps = cfg.surrogate_clip
        surrogate = jnp.minimum(
            ratio * adv, jnp.clip(ratio, 1.0 - eps, 1.0 + eps) * adv)
        policy_loss = -surrogate
        clip_frac = (
            (jnp.abs(ratio - 1.0) > eps) * tmasks).sum() / denom
        losses, dcnt = compose_losses(
            outputs, log_selected_t, None, targets, batch, cfg,
            policy_loss=policy_loss)
    else:
        total_advantages = clipped_rhos * sum(advantages.values())
        # how often the rho clip actually engaged: the off-policy
        # pressure signal (0 on fresh data; grows with staleness)
        clip_frac = ((rhos > cfg.rho_clip) * tmasks).sum() / denom
        losses, dcnt = compose_losses(
            outputs, log_selected_t, total_advantages, targets, batch,
            cfg)
    losses["clip_frac"] = clip_frac
    return losses, dcnt
