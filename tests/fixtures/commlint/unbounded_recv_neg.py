"""Negative: every wait is bounded — a timeout, a settimeout on the
listening socket, or a class that participates in the heartbeat
protocol (its wedges are evicted by the learner's sweep)."""

import queue


def drain(conn, sink):
    while True:
        data = conn.recv(timeout=0.3)
        sink.append(data)


def pull(jobs):
    try:
        return jobs.get(timeout=1.0)
    except queue.Empty:
        return None


def pull_forms(jobs, cfg):
    first = jobs.get(False)         # non-blocking: raises Empty now
    second = jobs.get(True, 2.0)    # get(block, timeout): bounded
    limit = cfg.get("limit")        # dict read, not a wait
    fallback = cfg.get("mode", "x")  # dict read with default
    return first, second, limit, fallback


def serve(sock):
    sock.settimeout(1.0)
    while True:
        peer, addr = sock.accept()  # bounded by settimeout above
        peer.close()


def framed_poll(conn, sink):
    # the framed-connection shape: a settimeout on the underlying
    # socket bounds the wrapper's recv (socket.timeout raises out)
    conn.sock.settimeout(1.0)
    while True:
        sink.append(conn.recv())    # bounded by conn.sock.settimeout


def raw_poll(sock):
    sock.settimeout(0.5)
    return sock.recv()              # bounded by settimeout above


class Gather:
    """Heartbeat participant: a wedged round trip here is recovered by
    the learner's FleetRegistry sweep, not by a local timeout."""

    def __init__(self, conn):
        self.conn = conn

    def _beat_if_due(self):
        self.conn.send("beat")

    def ask(self, request):
        self.conn.send(request)
        return self.conn.recv()     # swept class: bounded by eviction
