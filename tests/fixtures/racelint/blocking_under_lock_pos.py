"""Positive: the critical section parks the thread — every other
thread needing the lock stalls behind it."""

import threading
import time


class Gate:
    def __init__(self, conn):
        self._lock = threading.Lock()
        self.conn = conn
        self.frames = 0

    def nap(self):
        with self._lock:
            time.sleep(1.0)

    def pull(self):
        with self._lock:
            data = self.conn.recv()
            self.frames = self.frames + len(data)
