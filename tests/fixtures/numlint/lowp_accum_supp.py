"""SUPP: bf16 accumulation accepted for this op, with a reason."""
import jax
import jax.numpy as jnp


@jax.jit
def attention(q, k):
    qh = q.astype(jnp.bfloat16)
    kh = k.astype(jnp.bfloat16)
    # jaxlint: disable=lowp-accum -- contraction dim is 64; bf16 error is below the logit noise floor
    return jnp.matmul(qh, kh)
