"""ONNX interop: jaxpr export, numpy runtime, --eval round trip.

Capability parity with the reference's onnx path
(/root/reference/handyrl/evaluation.py:287-365 eval side,
/root/reference/scripts/make_onnx_model.py export side) — implemented
without the onnx/onnxruntime packages (absent from this image):
hand-encoded protobuf + a numpy graph interpreter.

Tolerances note: jax's CPU convolutions go through oneDNN, which uses
reduced-precision fast math (~1e-2 relative vs float64 truth, measured)
— the numpy runner is exact f32, so comparisons against the jax
reference use oneDNN-sized tolerances.
"""

import numpy as np
import pytest

TOL = dict(rtol=2e-2, atol=2e-3)  # oneDNN conv fast-math headroom


def _export(env_name, tmp_path, seed=0):
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.interop.onnx_export import export_onnx
    from handyrl_tpu.models import TPUModel

    env = make_env({"env": env_name})
    env.reset()
    model = TPUModel(env.net())
    obs = env.observation(env.players()[0])
    model.init_params(obs, seed=seed)
    path = str(tmp_path / f"{env_name}.onnx")
    export_onnx(model, obs, path)
    return env, model, obs, path


@pytest.mark.parametrize("env_name", ["TicTacToe", "HungryGeese"])
def test_export_matches_flax(env_name, tmp_path):
    from handyrl_tpu.interop.onnx_run import OnnxModel

    env, model, obs, path = _export(env_name, tmp_path)
    om = OnnxModel(path)
    out = om.inference(obs)
    ref = model.inference(obs)
    np.testing.assert_allclose(
        out["policy"], np.asarray(ref["policy"], np.float32), **TOL)
    np.testing.assert_allclose(
        out["value"], np.asarray(ref["value"], np.float32), **TOL)
    assert out["hidden"] is None


def test_recurrent_export_carries_hidden(tmp_path):
    """The DRC net unrolls: hidden state is explicit graph I/O and two
    different observations must produce different carried states."""
    from handyrl_tpu.interop.onnx_run import OnnxModel

    env, model, obs, path = _export("Geister", tmp_path)
    om = OnnxModel(path)
    hid = om.init_hidden()
    assert hid, "recurrent export must expose hidden inputs"
    out1 = om.inference(obs, hid)
    assert out1["hidden"] and len(out1["hidden"]) == len(hid)

    ref_out = model.inference(obs, model.init_hidden())
    np.testing.assert_allclose(
        out1["policy"], np.asarray(ref_out["policy"], np.float32),
        **TOL)
    # carried state actually evolves
    assert any(np.abs(h).max() > 0 for h in out1["hidden"])
    out2 = om.inference(obs, out1["hidden"])
    assert not np.allclose(out2["policy"], out1["policy"])


def test_eval_plays_full_match_with_onnx_artifact(tmp_path, monkeypatch):
    """--eval of an exported .onnx plays real games end to end
    (the reference capability: evaluation.py:287-365)."""
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.evaluation import exec_match, load_model
    from handyrl_tpu.agent import Agent, RandomAgent

    env, model, obs, path = _export("TicTacToe", tmp_path)
    loaded = load_model(path, env)
    agents = {0: Agent(loaded), 1: RandomAgent()}
    results = [exec_match(env, agents) for _ in range(5)]
    assert all(r is not None for r in results)
    outcomes = [r[0] for r in results]
    assert all(-1.0 <= o <= 1.0 for o in outcomes)


def test_onnx_file_parses_as_protobuf(tmp_path):
    """The artifact is structurally valid: our decoder round-trips it
    and the graph carries nodes, initializers, and named I/O."""
    from handyrl_tpu.interop.onnx_proto import decode

    _, _, _, path = _export("TicTacToe", tmp_path)
    with open(path, "rb") as f:
        model = decode(f.read(), "Model")
    g = model["graph"]
    assert model["opset_import"][0]["version"] >= 13
    assert len(g["node"]) > 10
    assert len(g["initializer"]) > 5
    names = [vi["name"] for vi in g["input"]]
    assert any(n.startswith("input") for n in names)
    out_names = [vi["name"] for vi in g["output"]]
    assert "policy" in out_names and "value" in out_names


def test_runner_executes_foreign_style_graph():
    """A hand-built NCHW Conv+BN+Relu+Gemm graph (the shape of a torch
    export) runs correctly — interop is not limited to our own files."""
    from handyrl_tpu.interop.onnx_proto import decode, encode
    from handyrl_tpu.interop.onnx_run import OnnxModel
    from handyrl_tpu.interop.onnx_export import (
        _value_info,
        numpy_to_tensor,
        _attr,
    )
    import tempfile

    rng = np.random.default_rng(3)
    w = rng.normal(size=(4, 2, 3, 3)).astype(np.float32)
    b = rng.normal(size=(4,)).astype(np.float32)
    scale = np.ones(4, np.float32)
    bias = np.zeros(4, np.float32)
    mean = np.zeros(4, np.float32)
    var = np.ones(4, np.float32)
    dense = rng.normal(size=(4 * 5 * 5, 3)).astype(np.float32)

    def node(op, inputs, outputs, **attrs):
        return {"op_type": op, "input": inputs, "output": outputs,
                "attribute": [_attr(k, v) for k, v in attrs.items()]}

    graph = {
        "name": "foreign",
        "node": [
            node("Conv", ["x", "w", "b"], ["c"],
                 pads=[1, 1, 1, 1], strides=[1, 1]),
            node("BatchNormalization",
                 ["c", "scale", "bias", "mean", "var"], ["n"]),
            node("Relu", ["n"], ["r"]),
            node("Flatten", ["r"], ["f"], axis=1),
            node("Gemm", ["f", "dense"], ["policy"]),
        ],
        "initializer": [
            numpy_to_tensor(a, n) for a, n in [
                (w, "w"), (b, "b"), (scale, "scale"), (bias, "bias"),
                (mean, "mean"), (var, "var"), (dense, "dense")]
        ],
        "input": [_value_info("x", (1, 2, 5, 5))],
        "output": [_value_info("policy", (1, 3))],
    }
    blob = encode({"ir_version": 8, "graph": graph,
                   "opset_import": [{"domain": "", "version": 13}]},
                  "Model")
    with tempfile.NamedTemporaryFile(suffix=".onnx", delete=False) as f:
        f.write(blob)
        path = f.name

    om = OnnxModel(path)
    x = rng.normal(size=(2, 5, 5)).astype(np.float32)
    out = om.inference(x)
    assert out["policy"].shape == (3,)
    assert np.all(np.isfinite(out["policy"]))
    # verify against a straightforward numpy computation
    from handyrl_tpu.interop.onnx_run import _conv

    c = _conv(x[None], w, b, {"pads": [1, 1, 1, 1]})
    r = np.maximum(c, 0)
    expect = r.reshape(1, -1) @ dense
    np.testing.assert_allclose(out["policy"], expect[0], rtol=1e-5)


def _torch_idiom_ttt_graph(tmp_path):
    """A full policy-value TicTacToe net in torch-export idiom —
    Transpose to NCHW, Conv, BatchNormalization, Relu, Reshape via an
    int64 shape initializer, transB Gemm heads, Tanh value — built
    node by node with onnx_proto.encode, NOT by onnx_export."""
    from handyrl_tpu.interop.onnx_export import (
        _attr,
        _value_info,
        numpy_to_tensor,
    )
    from handyrl_tpu.interop.onnx_proto import encode

    rng = np.random.default_rng(11)
    conv_w = rng.normal(size=(8, 3, 3, 3)).astype(np.float32) * 0.3
    conv_b = rng.normal(size=(8,)).astype(np.float32) * 0.1
    bn_scale = rng.uniform(0.5, 1.5, 8).astype(np.float32)
    bn_bias = rng.normal(size=(8,)).astype(np.float32) * 0.1
    bn_mean = rng.normal(size=(8,)).astype(np.float32) * 0.1
    bn_var = rng.uniform(0.5, 1.5, 8).astype(np.float32)
    pol_w = rng.normal(size=(9, 72)).astype(np.float32) * 0.2
    pol_b = rng.normal(size=(9,)).astype(np.float32) * 0.1
    val_w = rng.normal(size=(1, 72)).astype(np.float32) * 0.2
    val_b = np.zeros(1, np.float32)

    def node(op, inputs, outputs, **attrs):
        return {"op_type": op, "input": inputs, "output": outputs,
                "attribute": [_attr(k, v) for k, v in attrs.items()]}

    graph = {
        "name": "third_party_ttt",
        "node": [
            node("Transpose", ["input"], ["nchw"], perm=[0, 3, 1, 2]),
            node("Conv", ["nchw", "conv_w", "conv_b"], ["c"],
                 pads=[1, 1, 1, 1], strides=[1, 1]),
            node("BatchNormalization",
                 ["c", "bn_scale", "bn_bias", "bn_mean", "bn_var"],
                 ["n"], epsilon=1e-5),
            node("Relu", ["n"], ["r"]),
            node("Reshape", ["r", "flat_shape"], ["f"]),
            node("Gemm", ["f", "pol_w", "pol_b"], ["policy"],
                 transB=1),
            node("Gemm", ["f", "val_w", "val_b"], ["v_raw"],
                 transB=1),
            node("Tanh", ["v_raw"], ["value"]),
        ],
        "initializer": [
            numpy_to_tensor(a, n) for a, n in [
                (conv_w, "conv_w"), (conv_b, "conv_b"),
                (bn_scale, "bn_scale"), (bn_bias, "bn_bias"),
                (bn_mean, "bn_mean"), (bn_var, "bn_var"),
                (pol_w, "pol_w"), (pol_b, "pol_b"),
                (val_w, "val_w"), (val_b, "val_b"),
                (np.asarray([1, 72], np.int64), "flat_shape")]
        ],
        "input": [_value_info("input", (1, 3, 3, 3))],
        "output": [_value_info("policy", (1, 9)),
                   _value_info("value", (1, 1))],
    }
    blob = encode({"ir_version": 8, "graph": graph,
                   "opset_import": [{"domain": "", "version": 13}]},
                  "Model")
    path = str(tmp_path / "third_party.onnx")
    with open(path, "wb") as f:
        f.write(blob)
    weights = dict(conv_w=conv_w, conv_b=conv_b, bn_scale=bn_scale,
                   bn_bias=bn_bias, bn_mean=bn_mean, bn_var=bn_var,
                   pol_w=pol_w, pol_b=pol_b, val_w=val_w, val_b=val_b)
    return path, weights


def test_third_party_graph(tmp_path):
    """A graph this repo did NOT produce plays full matches through
    the --eval model slot (the reference accepts any
    onnxruntime-supported graph: evaluation.py:287-365)."""
    from handyrl_tpu.agent import Agent, RandomAgent
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.evaluation import exec_match, load_model

    path, w = _torch_idiom_ttt_graph(tmp_path)
    env = make_env({"env": "TicTacToe"})
    env.reset()
    loaded = load_model(path, env)  # the --eval entry point

    # numbers first: independently recompute the forward in numpy
    # from the raw weights (NHWC -> NCHW by hand, explicit BN algebra)
    obs = env.observation(env.players()[0]).astype(np.float32)
    x = obs.transpose(2, 0, 1)[None]
    xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
    c = np.empty((1, 8, 3, 3), np.float32)
    for o in range(8):
        acc = np.zeros((3, 3), np.float32)
        for ci in range(3):
            for kh in range(3):
                for kw in range(3):
                    acc += (w["conv_w"][o, ci, kh, kw]
                            * xp[0, ci, kh:kh + 3, kw:kw + 3])
        c[0, o] = acc + w["conv_b"][o]
    n = ((c - w["bn_mean"].reshape(1, -1, 1, 1))
         / np.sqrt(w["bn_var"].reshape(1, -1, 1, 1) + 1e-5)
         * w["bn_scale"].reshape(1, -1, 1, 1)
         + w["bn_bias"].reshape(1, -1, 1, 1))
    f = np.maximum(n, 0).reshape(1, -1)
    expect_policy = f @ w["pol_w"].T + w["pol_b"]
    expect_value = np.tanh(f @ w["val_w"].T + w["val_b"])

    out = loaded.inference(obs)
    np.testing.assert_allclose(out["policy"], expect_policy[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out["value"], expect_value[0],
                               rtol=1e-4, atol=1e-5)

    # then full matches, both seats
    agents = {0: Agent(loaded), 1: RandomAgent()}
    results = [exec_match(env, agents) for _ in range(3)]
    agents = {0: RandomAgent(), 1: Agent(loaded)}
    results += [exec_match(env, agents) for _ in range(3)]
    assert all(r is not None for r in results)
    assert all(-1.0 <= r[0] <= 1.0 for r in results)


def test_unsupported_op_errors_are_named(tmp_path):
    """Graphs using ops outside the runner's coverage (e.g. a real
    LSTM node) fail loudly with the op named, not with garbage."""
    from handyrl_tpu.interop.onnx_export import _value_info
    from handyrl_tpu.interop.onnx_proto import encode
    from handyrl_tpu.interop.onnx_run import OnnxModel

    graph = {
        "name": "lstm_graph",
        "node": [{"op_type": "LSTM", "input": ["input"],
                  "output": ["policy"], "attribute": []}],
        "initializer": [],
        "input": [_value_info("input", (1, 4))],
        "output": [_value_info("policy", (1, 4))],
    }
    blob = encode({"ir_version": 8, "graph": graph,
                   "opset_import": [{"domain": "", "version": 13}]},
                  "Model")
    path = str(tmp_path / "lstm.onnx")
    with open(path, "wb") as f:
        f.write(blob)
    om = OnnxModel(path)
    with pytest.raises(NotImplementedError, match="LSTM"):
        om.inference(np.zeros(4, np.float32))
