"""Fixture: the same mesh axis splitting two dims of one spec."""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), ("dp", "tp"))


def bad_spec():
    return P("dp", "dp")


def bad_grouped_spec():
    return P(("dp", "tp"), "dp")
