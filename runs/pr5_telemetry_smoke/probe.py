"""Induced-stall probe: one wedged control-plane loop must produce
exactly ONE flight-recorder dump (flightrec.json) with the causal
timeline — the live twin of tests/test_telemetry.py's injectable-clock
version, run against the real clock and the real sampler thread.

    cd runs/pr5_telemetry_smoke && python probe.py

The probe arms telemetry + a StallWatchdog exactly the way the learner
does (`on_stall = telemetry.stall_hook`), records a few spans of
"work", beats the server loop, then goes silent past
max_stall_seconds.  The watchdog's sampler notices, dumps the ring,
and the probe asserts: one stall event, one dump, the pre-stall spans
present in the file.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from handyrl_tpu import telemetry                      # noqa: E402
from handyrl_tpu.analysis.guards import StallWatchdog  # noqa: E402


def main():
    telemetry.configure(enabled=True, ring=256, log_dir=".",
                        role="probe", primary=True)
    dog = StallWatchdog(max_stall_seconds=2.0)
    dog.on_stall = telemetry.stall_hook
    dog.start()
    # a healthy phase: spans recorded, the loop beating
    for i in range(5):
        with telemetry.trace_span("probe.work", i=i):
            time.sleep(0.1)
        dog.beat("server")
    print("going silent (wedging the 'server' loop)...")
    time.sleep(4.0)  # > max_stall_seconds: the sampler fires
    dog.stop()
    assert dog.stall_events == 1, dog.stall_events
    assert telemetry.dump_count() == 1, telemetry.dump_count()
    with open("flightrec.json") as f:
        doc = json.load(f)
    names = [s["name"] for s in doc["spans"]]
    assert doc["reason"] == "stall_event"
    assert names.count("stall") == 1
    assert "probe.work" in names  # the timeline BEFORE the wedge
    print(f"OK: exactly one dump, reason={doc['reason']}, "
          f"{len(names)} spans ending in {names[-3:]}")


if __name__ == "__main__":
    main()
