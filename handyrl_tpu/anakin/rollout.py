"""The Anakin engine: fused on-device rollout + batch + update.

Podracer's Anakin architecture (arXiv:2104.06272) runs env stepping,
inference, and the learner update as ONE jitted program on the device
mesh — no actor processes, no control-plane traffic, no host work in
the hot loop.  This module is that program for the pure-JAX envs in
``environment.JAX_ENV_REGISTRY``:

  * ``vmap`` advances ``num_envs`` self-play games in lockstep (the
    env axis is the fused step's batch dimension);
  * ``lax.scan`` unrolls one episode-aligned segment per step: every
    game resets at segment start and must be able to terminate within
    ``unroll_length`` env steps (>= the env's MAX_STEPS), so each env
    row becomes exactly one complete-episode batch row — the same
    semantics ``make_batch`` produces for the turn-based host path
    (full window, outcome bootstrap on the padded tail);
  * opponent seats draw from a batched OPPONENT-POOL axis: the env
    axis factors into ``opponent_pool + 1`` equal groups — group 0
    plays pure self-play (both seats the live policy), group k plays
    the learner seat against frozen snapshot k — so scenario diversity
    is one extra ``vmap`` dimension, not a fleet of processes.  The
    learner seat alternates per game and per segment, and opponent
    moves are recorded with the OPPONENT's behavior probabilities, so
    the importance-sampling correction stays exact (the host league
    path's contract);
  * the segment's columnar records assemble into a training batch
    in-jit and flow straight into :func:`ops.update.make_update_core`
    — rollout, batch assembly, loss, grad, and Adam are one XLA
    program with params/optimizer/carry donated across steps.  The
    host contributes NOTHING per step (the carry — PRNG key + segment
    counter — lives on device and rides the jit).

PRNG discipline (jaxlint's prng-reuse rule polices this): the carry
key splits once per segment into (next-carry, init, scan) keys, the
scan key fans out one key per step, and each step key fans out one
action key and one env key PER GAME.  No key is consumed twice.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import ILLEGAL
from ..ops.update import make_apply_fn, make_update_core
from .config import AnakinConfig


class AnakinEngine:
    """Owns the rollout geometry and builds the fused step.

    ``pool`` (the stacked frozen-snapshot pytree) is an ARGUMENT of the
    fused step, not part of the donated carry: it is read-only inside a
    step and refreshed only at epoch boundaries (``refresh_pool``
    shifts the newest snapshot in, oldest out)."""

    def __init__(self, jax_env, model, loss_cfg, optimizer,
                 cfg: AnakinConfig, compute_dtype="float32", seed=0,
                 mesh=None, params=None, fsdp=False):
        if getattr(model, "is_recurrent", False):
            raise ValueError(
                "anakin mode supports feed-forward nets only (the "
                "fused scan carries no hidden state yet)")
        if not loss_cfg.turn_based_training or loss_cfg.observation:
            raise ValueError(
                "anakin mode requires turn_based_training: true and "
                "observation: false (the fused batch layout is the "
                "turn-gathered one)")
        if loss_cfg.burn_in_steps:
            raise ValueError(
                "anakin mode requires burn_in_steps: 0 (segments are "
                "whole episodes; there is no replayed warmup window)")
        self.env = jax_env
        self.model = model
        self.loss_cfg = loss_cfg
        self.optimizer = optimizer
        self.compute_dtype = compute_dtype
        self.seed = int(seed)
        self.num_envs = cfg.num_envs
        self.unroll = cfg.unroll_length or int(jax_env.MAX_STEPS)
        if self.unroll < int(jax_env.MAX_STEPS):
            raise ValueError(
                f"anakin.unroll_length {self.unroll} < the env's "
                f"MAX_STEPS {int(jax_env.MAX_STEPS)}: segments are "
                "episode-aligned, so every game must be able to finish "
                "inside one segment")
        self.K = cfg.opponent_pool          # frozen snapshots
        self.group = self.num_envs // (self.K + 1)
        self.players = int(jax_env.NUM_PLAYERS)
        self.num_actions = int(jax_env.NUM_ACTIONS)
        self._apply = make_apply_fn(model, compute_dtype)
        self._mesh = mesh
        self._params_like = params if params is not None else model.params
        self._fsdp = fsdp
        self._rep = self._out = None
        self._p_shard = self._o_shard = self._pool_shard = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import param_sharding, replicated
            from ..parallel.update import opt_state_sharding

            dp = int(mesh.shape["dp"]) or 1
            if self.num_envs % dp != 0:
                raise ValueError(
                    f"anakin.num_envs {self.num_envs} must be "
                    f"divisible by the mesh dp axis ({dp}): the env "
                    "axis is the fused step's batch dimension")
            self._rep = replicated(mesh)
            # the env axis (games, states, batch rows) lives on dp;
            # divisibility guarded just above
            self._out = NamedSharding(mesh, P("dp"))
            # full mesh shardings, not dp-only batch constraints:
            # params/opt_state per the learner's tp/fsdp rules, and
            # the opponent pool laid out EXACTLY like the params it
            # stacks (leading pool axis replicated, each snapshot's
            # dims on the param spec) — a replicated pool would keep K
            # full copies per device and defeat fsdp's memory win
            self._p_shard = param_sharding(mesh, self._params_like,
                                           fsdp=fsdp)
            self._o_shard = opt_state_sharding(
                optimizer, self._params_like, self._p_shard, self._rep)
            self._pool_shard = jax.tree.map(
                lambda s: NamedSharding(
                    mesh, jax.sharding.PartitionSpec(
                        *((None,) + tuple(s.spec)))),
                self._p_shard)
        self._refresh = None

    # -- host-side state builders (once per run / per epoch) ----------

    def init_carry(self, start_step=0):
        """Device carry for the fused step: the segment PRNG key and
        the segment counter.  Folding the resume step into the key
        keeps restarted runs on a fresh data stream while staying
        config-seed-deterministic."""
        carry = {
            "key": jax.random.fold_in(
                jax.random.PRNGKey(self.seed), int(start_step)),
            "seg": jnp.int32(int(start_step)),
        }
        if self._rep is not None:
            carry = jax.device_put(carry, self._rep)
        return carry

    def init_pool(self, params):
        """Stacked frozen-opponent params — ``opponent_pool`` copies of
        the current params (every snapshot starts as "now"; epoch
        boundaries shift real history in).  Empty pytree when the pool
        is off, so the fused step keeps ONE signature either way."""
        if self.K == 0:
            return ()
        stacked = jax.tree.map(
            lambda a: jnp.stack([jnp.asarray(a)] * self.K), params)
        if self._pool_shard is not None:
            # pool leaves land on the param layout (leading stack axis
            # replicated), so the fused step never reshards them
            stacked = jax.device_put(stacked, self._pool_shard)
        return stacked

    def refresh_pool(self, pool, params):
        """Epoch boundary: shift the newest snapshot into slot 0, drop
        the oldest.  One small jitted shift (compiled once, outside the
        fused step's retrace budget), donating the old pool."""
        if self.K == 0:
            return pool
        if self._refresh is None:
            def shift(pool, params):
                return jax.tree.map(
                    lambda stack, p: jnp.concatenate(
                        [p[None].astype(stack.dtype), stack[:-1]]),
                    pool, params)

            self._refresh = jax.jit(
                shift, donate_argnums=0,
                **({} if self._pool_shard is None
                   else {"out_shardings": self._pool_shard}))
        return self._refresh(pool, params)

    # -- the fused program --------------------------------------------

    def _stage_env(self, states):
        """Pin the vmapped env state onto the dp axis (every leaf has
        the game axis leading; ``num_envs % dp`` guarded at build).
        Without the constraint GSPMD usually infers the same layout
        from the batch constraint downstream, but *usually* is not a
        contract — an inference flip mid-scan would insert per-step
        resharding collectives."""
        if self._out is None:
            return states
        return jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, self._out),
            states)

    def _rollout(self, params, pool, carry):
        """One traced segment: reset -> scan unroll steps -> batch.

        Returns ``(batch, new_carry, frames)`` where ``batch`` is
        bit-compatible with ``make_batch``'s turn-based layout (each
        env row = one complete episode, padded tail carrying the
        outcome bootstrap) and ``frames`` counts committed env
        transitions."""
        env = self.env
        N, T, P, A = (self.num_envs, self.unroll, self.players,
                      self.num_actions)
        next_key, k_init, k_scan = jax.random.split(carry["key"], 3)
        seg = carry["seg"]
        # the learner's seat alternates per game AND per segment, so
        # both seats see both roles whatever the group layout
        learner_seat = (jnp.arange(N, dtype=jnp.int32) + seg) % 2
        states = jax.vmap(env.init)(jax.random.split(k_init, N))
        states = self._stage_env(states)

        def scan_step(states, step_key):
            active = ~jax.vmap(env.terminal)(states)
            obs = jax.vmap(env.observe)(states)              # (N, ...)
            legal = jax.vmap(env.legal_mask)(states)         # (N, A)
            seat = jax.vmap(env.turn)(states)                # (N,)
            out = self._apply(params, obs, None)
            policy, value = out["policy"], out.get("value")
            if self.K:
                # grouped opponent forward: ONE vmap over the pool
                # axis covers every frozen snapshot's games (group 0's
                # opponent is the live policy itself — self-play)
                pool_obs = jax.tree.map(
                    lambda a: a[self.group:].reshape(
                        (self.K, self.group) + a.shape[1:]), obs)
                pout = jax.vmap(self._apply, in_axes=(0, 0, None))(
                    pool, pool_obs, None)
                opp_policy = jnp.concatenate(
                    [policy[:self.group],
                     pout["policy"].reshape(-1, A)])
                is_learner = seat == learner_seat
                policy = jnp.where(
                    is_learner[:, None], policy, opp_policy)
                if value is not None:
                    opp_value = jnp.concatenate(
                        [value[:self.group],
                         pout["value"].reshape(
                             (-1,) + value.shape[1:])])
                    value = jnp.where(
                        is_learner[:, None], value, opp_value)
            # masked behavior policy, exactly agent.masked_logits:
            # illegal entries REPLACED by -1e32, then a temperature-1
            # softmax draw with the drawn probability recorded
            masked = jnp.where(legal, policy, jnp.float32(-ILLEGAL))
            k_act, k_env = jax.random.split(step_key)
            act_keys = jax.random.split(k_act, N)
            action = jax.vmap(jax.random.categorical)(act_keys, masked)
            probs = jax.nn.softmax(masked, axis=-1)
            prob = jnp.take_along_axis(
                probs, action[:, None], axis=1)[:, 0]
            env_keys = jax.random.split(k_env, N)
            states, _, _, _, _ = jax.vmap(env.step)(
                states, action, env_keys)
            states = self._stage_env(states)
            value_rec = (jnp.zeros(N, jnp.float32) if value is None
                         else value[:, 0])
            rec = {
                # inactive rows carry make_batch's padding values:
                # zero obs/action/value, prob 1.0, all-ILLEGAL mask
                "obs": jax.tree.map(
                    lambda a: jnp.where(
                        active.reshape((N,) + (1,) * (a.ndim - 1)),
                        a, 0.0), obs),
                "prob": jnp.where(active, prob, 1.0),
                "act": jnp.where(active, action, 0).astype(jnp.int32),
                "amask": jnp.where(active[:, None] & legal,
                                   jnp.float32(0), jnp.float32(ILLEGAL)),
                "value": jnp.where(active, value_rec, 0.0),
                "seat": seat,
                "active": active,
            }
            return states, rec

        final_states, recs = jax.lax.scan(
            scan_step, states, jax.random.split(k_scan, T))
        # scan stacks time leading: (T, N, ...) -> (N, T, ...)
        recs = jax.tree.map(lambda a: jnp.swapaxes(a, 0, 1), recs)

        active = recs["active"]                              # (N, T)
        ep_len = active.astype(jnp.int32).sum(axis=1)        # (N,)
        outcome = jax.vmap(env.outcome)(final_states)        # (N, P)
        seat_oh = jax.nn.one_hot(recs["seat"], P,
                                 dtype=jnp.float32)          # (N, T, P)
        act_mask = active.astype(jnp.float32)                # (N, T)
        turn_mask = seat_oh * act_mask[..., None]            # (N, T, P)
        # acting player's value on their seat row; the padded tail
        # bootstraps every seat with the final outcome (the host
        # path's np.tile(outcome) padding)
        v_rows = jnp.where(active[..., None],
                           seat_oh * recs["value"][..., None],
                           outcome[:, None, :])              # (N, T, P)
        t_idx = jnp.arange(T, dtype=jnp.float32)[None, :]
        progress = jnp.where(
            active, t_idx / ep_len.astype(jnp.float32)[:, None], 1.0)
        zeros_p = jnp.zeros((N, T, P, 1), jnp.float32)
        batch = {
            "observation": jax.tree.map(
                lambda a: a[:, :, None], recs["obs"]),   # (N,T,1,...)
            "selected_prob": recs["prob"][..., None, None],
            "action": recs["act"][..., None, None],
            "action_mask": recs["amask"][:, :, None, :],
            "value": v_rows[..., None],
            "reward": zeros_p,
            "return": zeros_p,
            "outcome": outcome[:, None, :, None],
            "episode_mask": act_mask[..., None, None],
            "turn_mask": turn_mask[..., None],
            "observation_mask": turn_mask[..., None],
            "progress": progress[..., None],
        }
        if self._out is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, self._out), batch)
        new_carry = {"key": next_key, "seg": seg + 1}
        return batch, new_carry, ep_len.sum()

    def make_fused_step(self):
        """Build the jitted fused step.

        Signatures (static per run, like the replay step):
          * standard: ``step(params, opt_state, carry, pool) ->
            (params, opt_state, metrics, carry)``
          * impact:   ``step(params, opt_state, carry, pool,
            target_params) -> (..., carry, target_params)``

        ``params``/``opt_state``/``carry`` (and the impact target) are
        donated; ``pool`` is read-only and survives across steps.
        ``metrics`` carries the loss metrics plus ``anakin_frames`` /
        ``anakin_games`` (committed transitions / completed games this
        segment) as device scalars — fetched once per epoch with the
        rest."""
        core = make_update_core(self.model, self.loss_cfg,
                                self.optimizer, self.compute_dtype)
        impact = self.loss_cfg.update_algorithm == "impact"
        games = jnp.int32(self.num_envs)

        if impact:
            def step(params, opt_state, carry, pool, target_params):
                batch, carry, frames = self._rollout(
                    params, pool, carry)
                params, opt_state, metrics, target_params = core(
                    params, opt_state, batch, target_params)
                metrics = {**metrics, "anakin_frames": frames,
                           "anakin_games": games}
                return params, opt_state, metrics, carry, target_params
        else:
            def step(params, opt_state, carry, pool):
                batch, carry, frames = self._rollout(
                    params, pool, carry)
                params, opt_state, metrics = core(
                    params, opt_state, batch)
                metrics = {**metrics, "anakin_frames": frames,
                           "anakin_games": games}
                return params, opt_state, metrics, carry

        if self._mesh is None:
            if impact:
                return jax.jit(step, donate_argnums=(0, 1, 2, 4))
            return jax.jit(step, donate_argnums=(0, 1, 2))

        # full mesh shardings computed at build (engine __init__):
        # params/opt_state per the learner's tp/fsdp rules, the pool
        # on the param layout behind its stack axis, and the tiny PRNG
        # carry replicated (env state is segment-local — every game
        # resets at segment start, so nothing env-shaped persists in
        # the carry; the in-scan dp constraints pin the live states)
        p_shard, o_shard, rep = self._p_shard, self._o_shard, self._rep
        pool_in = self._pool_shard if self.K else rep
        if impact:
            return jax.jit(
                step,
                in_shardings=(p_shard, o_shard, rep, pool_in, p_shard),
                out_shardings=(p_shard, o_shard, rep, rep, p_shard),
                donate_argnums=(0, 1, 2, 4),
            )
        return jax.jit(
            step,
            in_shardings=(p_shard, o_shard, rep, pool_in),
            out_shardings=(p_shard, o_shard, rep, rep),
            donate_argnums=(0, 1, 2),
        )
