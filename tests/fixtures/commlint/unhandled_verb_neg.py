"""Negative: every sent verb has a handler (and dynamic verbs whose
names the analyzer cannot resolve stay quiet)."""


def client(conn, extra_verb):
    conn.send(("ping", 1))
    conn.send((extra_verb, 2))   # dynamic: no literal, no finding


def server(hub):
    while True:
        conn, (verb, payload) = hub.recv(timeout=0.3)
        if verb == "ping":
            hub.send(conn, payload)
