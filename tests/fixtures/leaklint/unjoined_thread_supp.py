"""Suppressed: a deliberately unjoined non-daemon thread, explained."""

import threading


def run_worker(fn):
    worker = threading.Thread(target=fn)  # jaxlint: disable=unjoined-thread -- must outlive interpreter shutdown to flush the final batch; joined implicitly by threading._shutdown
    worker.start()
