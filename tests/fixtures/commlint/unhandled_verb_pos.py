"""Positive: a verb is sent but no receiver anywhere handles it."""


def client(conn):
    conn.send(("ping", 1))
    conn.send(("zap", 2))   # no handler anywhere -> unhandled-verb


def server(hub):
    while True:
        conn, (verb, payload) = hub.recv(timeout=0.3)
        if verb == "ping":
            hub.send(conn, payload)
