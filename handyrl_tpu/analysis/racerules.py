"""racelint's rule registry: six thread-safety rules for the control plane.

Same shape as :mod:`.rules` / :mod:`.shardrules` / :mod:`.commrules` —
each rule is ``(Package, ModuleInfo) -> Iterable[Finding]`` under a
stable kebab-case id (what suppression comments name), registered in
``RACE_RULES`` and consuming the thread-spawn graph and lock
environment of :mod:`.racelint`.  None of them import jax (or spawn a
thread).

The rules, and the interleaving each one prevents:

  ``unguarded-shared-write``    an attribute every other site guards
                                with one lock is written bare on a
                                different thread -> readers under the
                                lock still see the torn/stale value;
                                the lock is a fiction.
  ``non-atomic-rmw``            ``self.x += 1`` (or get-then-set) on
                                cross-thread state outside any lock ->
                                two threads interleave LOAD/STORE and
                                an increment is lost — the PR 13
                                inflight-cap bug class.
  ``live-container-iteration``  iterating a dict/list another thread
                                mutates, with no common lock and no
                                snapshot -> ``RuntimeError: dictionary
                                changed size during iteration`` from
                                the status thread — the PR 8
                                ``episode_count`` bug class.
  ``lock-order-cycle``          two locks acquired in opposite orders
                                on different interprocedural paths ->
                                a once-a-week ABBA deadlock no test
                                reproduces.
  ``blocking-under-lock``       send/recv/join/sleep/subprocess while
                                holding a lock (directly or via a
                                callee) -> every thread needing that
                                lock stalls behind one slow peer; the
                                static twin of what StallWatchdog only
                                sees at runtime.
  ``leaked-lock``               a bare ``.acquire()`` with no
                                ``finally``-protected ``.release()``
                                -> the first exception leaves the lock
                                held forever and the process wedges.
                                ``with`` statements never trigger this.

A write of a plain constant (``self._stop = True``) is the GIL-atomic
flag idiom and stays quiet; single-writer monotone counters (all the
read-modify-writes happen on one thread) stay quiet too.  Deliberately
lock-free designs suppress per line with
``# jaxlint: disable=<rule> -- reason``.
"""

from typing import Dict, FrozenSet, Iterable, Set

from .astutil import ModuleInfo, Package
from .racelint import Access, RaceAnalysis, _in_ctor, analyze_race
from .rules import Finding, Rule

RACE_RULES: Dict[str, Rule] = {}


def race_rule(rule_id: str, summary: str):
    def deco(fn):
        RACE_RULES[rule_id] = Rule(rule_id, summary, fn.__doc__ or "",
                                   fn)
        return fn
    return deco


def _loc(node):
    return node.lineno, getattr(node, "col_offset", 0)


def _ctx(an: RaceAnalysis, acc: Access) -> FrozenSet[str]:
    return an.context_of(acc.fn)


def _ctx_names(ctxs: Iterable[str]) -> str:
    short = sorted(c.rsplit(":", 1)[-1] for c in set(ctxs))
    return "/".join(short)


def _group_sites(an: RaceAnalysis, cls: str, attr: str):
    """Non-constructor accesses of one shared attribute."""
    return [a for a in an.accesses.get((cls, attr), [])
            if not _in_ctor(a.fn)]


@race_rule("unguarded-shared-write",
           "cross-thread attribute written outside the lock every "
           "other access holds")
def check_unguarded_shared_write(package: Package, mod: ModuleInfo):
    """An attribute whose every *other* access (read or write, any
    thread) holds one common lock is written or mutated bare at this
    site, and the bare site runs on a different thread context than
    some guarded site.  The guarded readers still race: holding a lock
    only helps if every writer holds it too.  Plain-constant stores
    (``self._stop = True``) are the GIL-atomic flag idiom and exempt;
    attributes with *no* lock discipline anywhere are a design choice
    this rule does not second-guess (``non-atomic-rmw`` and
    ``live-container-iteration`` still apply to them)."""
    an = analyze_race(package)
    for (cls, attr), _ in an.accesses.items():
        sites = _group_sites(an, cls, attr)
        if len(sites) < 2:
            continue
        group_ctx: Set[str] = set()
        for a in sites:
            group_ctx |= _ctx(an, a)
        if len(group_ctx) < 2:
            continue
        for site in sites:
            if site.fn.module is not mod:
                continue
            if site.kind not in ("write", "mutate") or site.const_value:
                continue
            others = [a for a in sites if a is not site]
            common = None
            for a in others:
                common = set(a.locks) if common is None \
                    else common & set(a.locks)
            if not common or (set(site.locks) & common):
                continue
            sctx = _ctx(an, site)
            other_ctx: Set[str] = set()
            for a in others:
                other_ctx |= _ctx(an, a)
            if len(sctx) < 2 and not (other_ctx - sctx):
                continue
            line, col = _loc(site.node)
            lock = sorted(common)[0]
            yield Finding(
                "unguarded-shared-write", mod.path, line, col,
                f"`self.{attr}` is written here without `{lock}`, but "
                f"every other access of `{cls}.{attr}` holds it; this "
                f"site runs on {_ctx_names(sctx)} while guarded sites "
                f"run on {_ctx_names(other_ctx)} — take the lock or "
                f"explain the tear")


@race_rule("non-atomic-rmw",
           "unlocked read-modify-write of cross-thread state")
def check_non_atomic_rmw(package: Package, mod: ModuleInfo):
    """``self.x += 1`` / ``self.x = self.x + ...`` / subscript
    read-modify-write outside any lock, on an attribute that is
    touched from at least two thread contexts, where either the
    mutating function itself runs on several contexts or the
    read-modify-writes span several contexts.  Two interleaved
    LOAD/ADD/STORE sequences lose one update — the inflight-cap bug
    class.  A counter only ever bumped from one thread (however many
    threads read it) is exempt: single-writer monotone counters are a
    supported idiom."""
    an = analyze_race(package)
    for (cls, attr), _ in an.accesses.items():
        sites = _group_sites(an, cls, attr)
        group_ctx: Set[str] = set()
        for a in sites:
            group_ctx |= _ctx(an, a)
        if len(group_ctx) < 2:
            continue
        rmw_sites = [a for a in sites if a.kind == "rmw"]
        rmw_ctx: Set[str] = set()
        for a in rmw_sites:
            rmw_ctx |= _ctx(an, a)
        for site in rmw_sites:
            if site.fn.module is not mod or site.locks:
                continue
            if len(_ctx(an, site)) < 2 and len(rmw_ctx) < 2:
                continue
            line, col = _loc(site.node)
            yield Finding(
                "non-atomic-rmw", mod.path, line, col,
                f"read-modify-write of `{cls}.{attr}` outside any "
                f"lock; the attribute is live on "
                f"{_ctx_names(group_ctx)} and concurrent updates can "
                f"interleave and lose one — guard it or make it "
                f"single-writer")


@race_rule("live-container-iteration",
           "iterating a container another thread mutates, without a "
           "snapshot")
def check_live_container_iteration(package: Package, mod: ModuleInfo):
    """A ``for``/comprehension/``sum(...)``-style iteration over
    ``self.X`` (or its ``.values()``/``.items()``/``.keys()`` view)
    while some other thread context mutates it in place, and no lock
    is common to both sites.  CPython raises ``RuntimeError:
    dictionary changed size during iteration`` — from the status HTTP
    thread this killed live metrics in PR 8.  Iterate a snapshot
    (``list(...)`` under the lock) instead."""
    an = analyze_race(package)
    for (cls, attr), _ in an.accesses.items():
        sites = _group_sites(an, cls, attr)
        iter_sites = [a for a in sites if a.kind == "iterate"]
        mut_sites = [a for a in sites if a.kind in ("mutate", "rmw")]
        if not iter_sites or not mut_sites:
            continue
        for site in iter_sites:
            if site.fn.module is not mod:
                continue
            for m in mut_sites:
                if len(_ctx(an, site) | _ctx(an, m)) < 2:
                    continue
                if set(site.locks) & set(m.locks):
                    continue
                line, col = _loc(site.node)
                mline = m.node.lineno
                yield Finding(
                    "live-container-iteration", mod.path, line, col,
                    f"iterates `{cls}.{attr}` live while "
                    f"{m.fn.qname} (line {mline}, on "
                    f"{_ctx_names(_ctx(an, m))}) mutates it with no "
                    f"common lock — iterate a snapshot taken under "
                    f"the lock")
                break


@race_rule("lock-order-cycle",
           "two locks acquired in opposite orders on different paths")
def check_lock_order_cycle(package: Package, mod: ModuleInfo):
    """The lock-acquisition-order graph (nested ``with`` blocks plus
    calls made under a lock into functions that may acquire another)
    contains a cycle: some path takes A then B while another takes B
    then A.  Run long enough, two threads meet in the middle and
    deadlock.  A non-reentrant lock re-acquired while already held is
    the one-lock version of the same bug and is reported here too;
    RLocks are reentrant by design and self-edges on them stay
    quiet."""
    an = analyze_race(package)
    adj: Dict[str, Set[str]] = {}
    for e in an.order_edges:
        adj.setdefault(e.src, set()).add(e.dst)
    reach: Dict[str, Set[str]] = {}

    def reachable(src: str) -> Set[str]:
        if src in reach:
            return reach[src]
        seen: Set[str] = set()
        stack = [src]
        while stack:
            node = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        reach[src] = seen
        return seen

    reported: Set[tuple] = set()
    for e in an.order_edges:
        if e.fn.module is not mod:
            continue
        line, col = _loc(e.node)
        if e.src == e.dst:
            key = (e.src, e.dst, line)
            if key in reported:
                continue
            reported.add(key)
            yield Finding(
                "lock-order-cycle", mod.path, line, col,
                f"`{e.dst}` is acquired while already held and is not "
                f"reentrant — this thread deadlocks on itself"
                + (f" (via {e.via})" if e.via else ""))
            continue
        if e.src not in reachable(e.dst):
            continue
        key = (e.src, e.dst)
        if key in reported:
            continue
        reported.add(key)
        via = f" (via {e.via})" if e.via else ""
        yield Finding(
            "lock-order-cycle", mod.path, line, col,
            f"acquires `{e.dst}` while holding `{e.src}`{via}, but "
            f"another path acquires them in the opposite order — "
            f"ABBA deadlock; pick one global order")


@race_rule("blocking-under-lock",
           "blocking call while holding a lock")
def check_blocking_under_lock(package: Package, mod: ModuleInfo):
    """A call that can park the thread — ``time.sleep``, socket
    ``recv``/``accept``/``connect``/``send``, ``join``, ``wait``,
    ``select``, ``subprocess`` — executes while a lock is held, either
    directly or through a callee whose summary says it blocks.  Every
    other thread needing that lock now stalls behind one slow peer:
    the static form of the stall classes StallWatchdog only observes
    at runtime.  Move the slow call outside the critical section and
    keep the lock for the state update alone."""
    an = analyze_race(package)
    for bs in an.block_sites:
        if bs.fn.module is not mod or not bs.locks:
            continue
        line, col = _loc(bs.node)
        lock = sorted(bs.locks)[0]
        yield Finding(
            "blocking-under-lock", mod.path, line, col,
            f"blocking call `{bs.desc}` while holding `{lock}` — "
            f"every thread contending on the lock stalls with it")
    for cs in an.call_sites:
        if cs.caller.module is not mod or not cs.locks:
            continue
        sm = an.summaries.get(cs.callee)
        if sm is None or sm.blocking is None:
            continue
        line, col = _loc(cs.node)
        lock = sorted(cs.locks)[0]
        yield Finding(
            "blocking-under-lock", mod.path, line, col,
            f"calls `{cs.callee.qname}` while holding `{lock}`, and "
            f"it can block on `{sm.blocking[0]}` (line "
            f"{sm.blocking[1]}) — hoist the slow call out of the "
            f"critical section")


@race_rule("leaked-lock",
           "acquire() without a finally-protected release()")
def check_leaked_lock(package: Package, mod: ModuleInfo):
    """A bare ``.acquire()`` on a known lock whose function has no
    matching ``.release()`` inside a ``finally`` block.  The first
    exception between acquire and release leaves the lock held
    forever; every later acquirer wedges silently.  ``with lock:``
    (which this rule never flags) or try/finally is the idiom."""
    an = analyze_race(package)
    released_safely: Set[tuple] = set()
    for op in an.lock_ops:
        if op.op == "release" and op.in_finally:
            released_safely.add((op.fn, op.key))
    for op in an.lock_ops:
        if op.op != "acquire" or op.fn.module is not mod:
            continue
        if (op.fn, op.key) in released_safely:
            continue
        line, col = _loc(op.node)
        yield Finding(
            "leaked-lock", mod.path, line, col,
            f"`{op.key}.acquire()` with no `.release()` in a "
            f"`finally` block of this function — an exception here "
            f"leaks the lock; use `with` or try/finally")
