"""Typed configuration with the reference YAML schema.

The reference passes ``yaml.safe_load`` output around as a raw dict with
no validation or defaults layer (/root/reference/main.py:9-10,
/root/reference/config.yaml).  Here the same YAML keys
(/root/reference/docs/parameters.md schema) load into dataclasses with
defaults, type checks, and the derived quantities the reference computes
inline (``num_gathers``: /root/reference/handyrl/worker.py:183-184,
eval-rate floor: /root/reference/handyrl/train.py:415-416).

``TrainConfig`` also supports item access (``cfg['gamma']``) so code
that naturally treats it as a mapping (e.g. serializing to workers)
stays simple.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

POLICY_TARGETS = ("MC", "TD", "VTRACE", "UPGO", "IMPACT")
VALUE_TARGETS = ("MC", "TD", "VTRACE", "UPGO", "IMPACT")
UPDATE_ALGORITHMS = ("standard", "impact")


@dataclass
class WorkerConfig:
    num_parallel: int = 6
    num_gathers: int = 0          # 0 -> derived: 1 + (num_parallel-1)//16
    base_worker_id: int = 0
    server_address: str = ""

    def __post_init__(self):
        if self.num_gathers <= 0:
            self.num_gathers = 1 + max(0, self.num_parallel - 1) // 16


@dataclass
class EvalConfig:
    opponent: List[str] = field(default_factory=lambda: ["random"])


@dataclass
class TrainConfig:
    turn_based_training: bool = True
    observation: bool = False
    gamma: float = 0.8
    forward_steps: int = 16
    burn_in_steps: int = 0
    compress_steps: int = 4
    entropy_regularization: float = 1e-1
    entropy_regularization_decay: float = 0.1
    update_episodes: int = 200
    batch_size: int = 128
    minimum_episodes: int = 400
    maximum_episodes: int = 100_000
    epochs: int = -1
    num_batchers: int = 2
    eval_rate: float = 0.1
    lambda_: float = 0.7
    policy_target: str = "TD"
    value_target: str = "TD"
    seed: int = 0
    # epoch to resume from (0 = fresh start), or "auto" to scan the
    # checkpoint manifest for the newest VALID checkpoint — the
    # preemption-recovery mode: no config surgery after a learner kill
    restart_epoch: Any = 0
    worker: WorkerConfig = field(default_factory=WorkerConfig)
    eval: EvalConfig = field(default_factory=EvalConfig)
    env: Dict[str, Any] = field(default_factory=dict)

    # --- TPU-native additions (absent from the reference) ---
    # concurrent lockstep episodes per actor process: every rollout
    # step runs ONE (episodes x players)-row batched CPU forward
    # instead of one dispatch per seat; 1 = sequential fallback
    lockstep_episodes: int = 16
    # device mesh shape for the learner, e.g. {"dp": 4}; empty = single chip
    mesh: Dict[str, int] = field(default_factory=dict)
    # multi-host learner (one process per host over one global mesh);
    # empty = single process.  Keys: coordinator_address ("host:port"
    # of process 0), num_processes, process_id (all auto-detected on
    # Cloud TPU pods — `distributed: {auto: true}` suffices there)
    distributed: Dict[str, Any] = field(default_factory=dict)
    # number of device-resident batches to keep prefetched
    prefetch_batches: int = 2
    # background host->device transfer threads feeding the prefetch
    transfer_threads: int = 2
    # observation wire format for host->device transfer:
    #   auto     — bfloat16 when compute_dtype is bfloat16, else float32
    #   uint8    — quarter-width, for integer-valued (binary-plane)
    #              observations only (verified in the batcher)
    transfer_dtype: str = "auto"
    # compute dtype for the update step: bfloat16 rides the MXU at
    # full rate (params/optimizer stay float32); set "float32" to
    # opt out for numerics debugging
    compute_dtype: str = "bfloat16"
    # structured metrics sink (jsonl path); "" disables
    metrics_path: str = ""
    # XLA profiler trace output dir; "" disables trace capture
    profile_dir: str = ""
    # columnar decompression cache cap, MiB PER BATCHER PROCESS
    # (total resident cache ~= this * num_batchers); 0 = default 512
    columnar_cache_mb: int = 0
    # cap update steps per epoch; 0 = unlimited (train as fast as the
    # feed allows, the reference behavior).  A fast learner otherwise
    # replays the same window thousands of times per epoch and starves
    # co-located actors of host CPU (single-process learners only)
    updates_per_epoch: int = 0
    # device-resident replay: episodes live in HBM and every batch is
    # built on device by one jitted gather (no host assembly, no
    # per-step transfer).  auto = on for single-process learners
    # (multi-host keeps the host path); on | off force it
    device_replay: str = "auto"
    # HBM budget for the device replay ring, MiB (per device when the
    # ring is replicated over a mesh)
    device_replay_mb: int = 4096
    # explicit ring capacity in episodes; 0 = maximum_episodes,
    # clamped to the byte budget either way
    device_replay_episodes: int = 0
    # checkpoint retention: keep the newest N epoch files (0 = keep
    # all, the reference behavior) ...
    checkpoint_keep_last: int = 0
    # ... plus every K-th epoch regardless of age (0 = none)
    checkpoint_keep_every: int = 0
    # -- durability (handyrl_tpu.durability) --
    # stamp a sha256 footer on every checkpoint write and verify it on
    # load: truncated/bit-flipped files are rejected and resume falls
    # back to the newest valid manifest entry instead of training on
    # garbage.  Footer-less legacy files still load either way
    checkpoint_checksum: bool = True
    # episode write-ahead log: admitted episodes append to segmented,
    # crc-checksummed logs under models/wal/ so a restarted learner
    # replays its staged backlog instead of re-generating it
    wal_enabled: bool = True
    # seconds between WAL fsyncs (bounds the episode-loss window of a
    # hard kill); 0 = fsync every append
    wal_flush_interval: float = 1.0
    # WAL segment size before rolling to a fresh file, MiB
    wal_segment_mb: int = 8
    # episodes of WAL history retained for replay; 0 = follow
    # maximum_episodes (the replay buffer's own capacity)
    wal_keep_episodes: int = 0
    # SIGTERM grace window, seconds: how long the preemption handler
    # waits for the trainer to land an emergency checkpoint before the
    # flight-recorder dump and exit.  0 = seal the WAL and dump only
    preempt_grace_seconds: float = 5.0
    # run the learner under a relaunch supervisor (resilience.guardian):
    # a crashed/killed learner process restarts with `restart_epoch:
    # auto` behind the same backoff + circuit breaker the actor fleet
    # uses, so a poison checkpoint cannot restart-storm
    supervise_learner: bool = False
    # retrace budget for the jitted update step, asserted by a
    # RetraceGuard after every training step: compiling more than this
    # many times per run means input shapes/dtypes are churning (each
    # recompile stalls the learner for seconds on TPU).  0 = count and
    # report in the metrics jsonl, but never raise
    max_update_compiles: int = 0
    # arm a HostTransferGuard around the learner process and report
    # device->host transfer counts per epoch in the metrics jsonl
    # (counts jax.device_get / np.asarray / np.array on device values;
    # a growing count means a host sync crept into the hot loop)
    host_transfer_guard: bool = True
    # arm a ShardingContractGuard around the jitted update step and
    # report per-epoch resharding-copy counts (`resharding_copies`) in
    # the metrics jsonl: an argument whose sharding deviates from its
    # first call costs a silent XLA copy per step and defeats donation
    sharding_contract_guard: bool = True
    # resharding-copy budget asserted by the guard at the offending
    # call; 0 = count and report, but never raise
    max_resharding_copies: int = 0
    # arm a NumericsGuard around the jitted update step: latches the
    # per-leaf dtype treedef at first call and reports per-epoch
    # `numerics_contract_breaks` / `weak_upcasts`, plus
    # `nonfinite_steps` from the step's in-graph loss/grad-norm
    # finiteness flag — the runtime twin of numlint's rules
    numerics_guard: bool = True
    # nonfinite-step budget asserted at the epoch boundary
    # (NumericsError past it); 0 = count and report, but never raise
    max_nonfinite_steps: int = 0
    # -- resilience (handyrl_tpu.resilience) --
    # seconds of control-plane silence after which a gather sends an
    # explicit heartbeat (liveness otherwise piggybacks on its normal
    # traffic); 0 disables explicit beats
    heartbeat_interval: float = 2.0
    # seconds of total silence after which the learner counts a
    # heartbeat miss and evicts the wedged gather (supervised local
    # fleets respawn it)
    heartbeat_timeout: float = 30.0
    # circuit breaker: more than this many failures of one gather slot
    # inside the supervisor's failure window marks the slot dead and
    # shrinks the fleet instead of restart-storming (0 = strictest:
    # dead on the first failure, no respawns)
    max_respawns: int = 5
    # base seconds for the jittered exponential respawn backoff
    respawn_backoff: float = 0.5
    # ceiling on one control-plane frame: a corrupt length header
    # fails with FrameError instead of allocating gigabytes.  0 = the
    # built-in 1 GiB default
    max_frame_bytes: int = 0
    # arm a StallWatchdog over the learner's control-plane loops
    # (server loop + communicator reader/writer threads): a loop
    # silent past max_stall_seconds is a counted `stall_events` in the
    # metrics jsonl with a one-shot stack dump of the wedged thread
    stall_watchdog: bool = True
    # silence threshold for the watchdog, seconds.  Must comfortably
    # exceed the longest legitimate pause of a watched loop (the epoch
    # boundary beats through trainer.update(), so ordinary long epochs
    # do not count)
    max_stall_seconds: float = 60.0
    # arm a LockOrderGuard over the control plane's lock objects
    # (communicator, fleet registry, inference service, serving
    # frontend, supervisor, watchdog): per-epoch `lock_contention_sec`
    # and `lock_order_inversions` in the metrics jsonl — the runtime
    # twin of racelint's lock-order-cycle rule
    lock_order_guard: bool = True
    # arm a ResourceLedger sampling the process's resource population
    # once per epoch: `fd_count`/`thread_count`/`shm_segments`/
    # `resource_growth` in the metrics jsonl plus a `resources`
    # status section — the runtime twin of leaklint's lifecycle rules
    resource_ledger: bool = True
    # hard fd-growth budget for the ledger: a post-warmup epoch whose
    # fd count exceeds the baseline by more than this raises
    # ResourceError.  0 = count and report only, never raise
    max_fd_growth: int = 0
    # -- telemetry (handyrl_tpu.telemetry) --
    # arm span tracing + the flight recorder: trace_span sections,
    # trace-context propagation over the control plane, per-process
    # span logs next to metrics_path, and flightrec.json dumps on
    # stall/crash/SIGTERM.  Off = every telemetry entry point is a
    # constant-time no-op and the wire format carries no envelopes
    telemetry: bool = True
    # fraction of episodes that carry a propagated trace context
    # (per-episode sampling decision at generation); spans for
    # unsampled episodes still record locally without a context
    trace_sample_rate: float = 1.0
    # flight-recorder ring capacity: the last N spans/events kept for
    # the post-mortem dump
    flightrec_spans: int = 2048
    # read-only learner status endpoint (live JSON over HTTP for
    # dashboards); 0 = off
    status_port: int = 0
    # chaos fault injection for resilience tests (kill/frame/surge/
    # learner-kill/infer-kill/shm_* keys — see ChaosConfig and
    # docs/parameters.md); empty = off
    chaos: Dict[str, Any] = field(default_factory=dict)
    # -- pipelined rollout dataflow (handyrl_tpu.pipeline) --
    # Sebulba-style split: per-worker CPU inference is replaced by the
    # learner's batched inference service and finished trajectories
    # ride the zero-copy shared-memory transport (the framed control
    # plane keeps control verbs only).  Keys (validated through
    # PipelineConfig.from_config): mode, batch_window, max_batch,
    # ring_slots, slot_bytes, traj_slots, traj_slot_mb, fallback,
    # fallback_after, compress.  Empty = ON (the default since the shm
    # plane earned its chaos pedigree); {mode: 'off'} = legacy path
    pipeline: Dict[str, Any] = field(default_factory=dict)
    # -- network serving tier (handyrl_tpu.serving) --
    # SLO-bound, network-facing continuous-batching frontend over the
    # pipeline inference core: remote clients' requests share the
    # batching window (and the jitted dispatch) with the colocated shm
    # workers, with latency histograms + QPS, admission control /
    # load-shedding under the latency SLO, and multi-model routing for
    # epoch-pinned requests.  Keys (validated through
    # ServingConfig.from_config): mode, port, slo_ms, slo_window,
    # max_inflight, breach_admit_every, reply_timeout, snapshot_cache.
    # Empty = off (a public port must be an explicit decision);
    # requires the inference service (pipeline.mode on, local primary
    # learner).  See docs/serving.md
    serving: Dict[str, Any] = field(default_factory=dict)
    # -- replica-pool router (handyrl_tpu.serving.router) --
    # one endpoint over N serving replicas: a service registry each
    # frontend heartbeats into (capacity, committed epochs, p99,
    # generation; silent replicas evicted, never routed to) and a
    # router spreading live traffic least-loaded (or hash on seat),
    # re-routing epoch pins to any replica advertising the snapshot,
    # and escalating typed sheds only when the WHOLE pool is
    # unhealthy.  Keys (validated through RouterConfig.from_config):
    # mode, port, heartbeat_interval, heartbeat_timeout, policy,
    # max_attempts, max_inflight, max_connections, reply_timeout,
    # replica_failures, failure_window.  Empty = off; requires
    # serving.mode on.  See "Pool routing" in docs/serving.md
    router: Dict[str, Any] = field(default_factory=dict)
    # -- Anakin mode (handyrl_tpu.anakin; Podracer arXiv:2104.06272) --
    # fused on-device rollout+update for envs with a pure-JAX twin
    # (environment.JAX_ENV_REGISTRY): `mode: on|auto` runs env
    # stepping, inference, batch assembly, and the optimizer update as
    # ONE jitted, vmap'd program — generation leaves the worker fleet
    # (which then only evaluates).  Keys (validated through
    # AnakinConfig.from_config): mode, num_envs, unroll_length,
    # opponent_pool.  Empty = off (the IMPALA worker path).  Requires
    # updates_per_epoch > 0: the epoch cadence is the trainer's step
    # count, since nothing ticks episode intake
    anakin: Dict[str, Any] = field(default_factory=dict)
    # -- off-policy robustness (IMPACT, arXiv:1912.00167) --
    # "standard" (default): importance ratios against the live learner
    # policy, score-function policy loss — the reference behavior.
    # "impact": a target network rides the jitted update step; V-Trace
    # ratios are computed against ITS policy and the policy loss is a
    # two-sided surrogate clip of the current/target ratio, so the
    # learner tolerates much staler episodes (deep queues, bursty
    # fleets) without the correction collapsing
    update_algorithm: str = "standard"
    # hard target sync cadence in optimizer steps (impact); 0 = off
    target_update_interval: int = 0
    # Polyak target averaging coefficient (impact); wins over the
    # interval when both are set.  0 = off
    target_update_tau: float = 0.0
    # importance-ratio clips, surfaced from the previously hard-wired
    # V-Trace constants (rho: the delta/advantage weight; c: the trace
    # accumulation weight).  Defaults keep existing runs bit-identical
    rho_clip: float = 1.0
    c_clip: float = 1.0
    # IMPACT surrogate clip epsilon: the current/target ratio is
    # clipped to [1 - eps, 1 + eps] in the policy objective
    surrogate_clip: float = 0.2
    # staleness budget at intake: an arriving episode whose generating
    # snapshot is more than this many epochs old is dropped (counted
    # as `episodes_rejected_stale` in the metrics jsonl).  0 = accept
    # everything (the reference behavior)
    max_policy_lag: int = 0
    # league-lite: schedule PAST-SELF opponents into generation jobs.
    # {past_epochs: K} samples one opponent seat per league job from
    # the retained checkpoints of the last K epochs; optional prob
    # (default 0.25) is the fraction of generation jobs that become
    # league jobs.  Empty = off (pure self-play, the reference
    # behavior).  League episodes fall back to the sequential actor
    # path (the lockstep pool shares one snapshot) and train with
    # exact importance weights — the recorded behavior probs are the
    # past policy's.
    generation_opponent: Dict[str, Any] = field(default_factory=dict)
    # -- perf attribution (handyrl_tpu.telemetry.costmodel) --
    # runtime MFU/roofline cost accounting over the guarded jit
    # programs.  Keys (validated through PerfConfig.from_config):
    # peak_tflops / peak_hbm_gbs (override the per-device-kind peak
    # table — how CPU hosts and unlisted accelerators get real MFU
    # numbers) and cost_analysis (harvest XLA flops/bytes at each new
    # guarded-program signature; default on).  Empty = table lookup by
    # device kind.  See "Attribution & roofline" in
    # docs/observability.md
    perf: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.policy_target not in POLICY_TARGETS:
            raise ValueError(f"unknown policy_target {self.policy_target!r}")
        if self.value_target not in VALUE_TARGETS:
            raise ValueError(f"unknown value_target {self.value_target!r}")
        if self.forward_steps < 1:
            raise ValueError("forward_steps must be >= 1")
        if self.burn_in_steps < 0:
            raise ValueError("burn_in_steps must be >= 0")
        if self.compress_steps < 1:
            raise ValueError("compress_steps must be >= 1")
        if not 0.0 <= self.eval_rate <= 1.0:
            raise ValueError("eval_rate must be in [0, 1]")
        if self.transfer_dtype not in (
                "auto", "float32", "bfloat16", "uint8"):
            raise ValueError(
                f"unknown transfer_dtype {self.transfer_dtype!r}")
        for key in ("columnar_cache_mb", "checkpoint_keep_last",
                    "checkpoint_keep_every", "device_replay_mb",
                    "device_replay_episodes", "updates_per_epoch",
                    "max_update_compiles", "max_resharding_copies",
                    "max_nonfinite_steps", "max_fd_growth",
                    "heartbeat_interval", "max_respawns",
                    "max_frame_bytes", "status_port",
                    "target_update_interval", "max_policy_lag",
                    "wal_flush_interval", "wal_keep_episodes",
                    "preempt_grace_seconds"):
            if getattr(self, key) < 0:
                raise ValueError(f"{key} must be >= 0")
        if self.wal_segment_mb < 1:
            raise ValueError("wal_segment_mb must be >= 1")
        if self.restart_epoch != "auto" and not (
                isinstance(self.restart_epoch, int)
                and not isinstance(self.restart_epoch, bool)
                and self.restart_epoch >= 0):
            raise ValueError(
                "restart_epoch must be an epoch number >= 0 or 'auto'")
        if self.update_algorithm not in UPDATE_ALGORITHMS:
            raise ValueError(
                f"unknown update_algorithm {self.update_algorithm!r}")
        if self.rho_clip <= 0 or self.c_clip <= 0:
            raise ValueError("rho_clip and c_clip must be > 0")
        if not 0.0 < self.surrogate_clip < 1.0:
            raise ValueError("surrogate_clip must be in (0, 1)")
        if not 0.0 <= self.target_update_tau <= 1.0:
            raise ValueError("target_update_tau must be in [0, 1]")
        if (self.update_algorithm == "impact"
                and self.target_update_interval <= 0
                and self.target_update_tau <= 0.0):
            raise ValueError(
                "update_algorithm: impact needs a target refresh — set "
                "target_update_interval > 0 or target_update_tau > 0")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError("trace_sample_rate must be in [0, 1]")
        if self.flightrec_spans < 1:
            raise ValueError("flightrec_spans must be >= 1")
        if self.respawn_backoff <= 0:
            raise ValueError("respawn_backoff must be > 0")
        if self.max_stall_seconds <= 0:
            raise ValueError("max_stall_seconds must be > 0")
        if self.heartbeat_timeout <= self.heartbeat_interval:
            raise ValueError(
                "heartbeat_timeout must exceed heartbeat_interval")
        # chaos keys/ranges validate in one place: the dataclass the
        # injector actually runs with
        from .resilience.chaos import ChaosConfig

        ChaosConfig.from_config(self.chaos)
        # pipeline keys likewise validate through the dataclass the
        # inference service and worker-side client run with
        from .pipeline.config import PipelineConfig

        pipeline_cfg = PipelineConfig.from_config(self.pipeline)
        # serving keys validate through the dataclass the network
        # frontend runs with; the service dependency is checked here
        # because it crosses sections
        from .serving.config import RouterConfig, ServingConfig

        serving_cfg = ServingConfig.from_config(self.serving)
        if serving_cfg.enabled and not pipeline_cfg.enabled:
            raise ValueError(
                "serving.mode: on needs the batched inference service "
                "— it feeds the pipeline batching window, so "
                "pipeline.mode must be on (the default)")
        # router keys validate through the dataclass the pool router
        # runs with; the frontend dependency crosses sections
        if (RouterConfig.from_config(self.router).enabled
                and not serving_cfg.enabled):
            raise ValueError(
                "router.mode: on needs a serving frontend to front — "
                "serving.mode must be on")
        # anakin keys validate through the dataclass the fused rollout
        # engine runs with; the epoch-cadence requirement is checked
        # here because it crosses fields
        from .anakin.config import AnakinConfig

        if (AnakinConfig.from_config(self.anakin).enabled
                and self.updates_per_epoch <= 0):
            raise ValueError(
                "anakin mode needs updates_per_epoch > 0 — the fused "
                "loop makes its own data, so the epoch cadence is the "
                "trainer's step count, not episode intake")
        # perf keys validate through the dataclass the cost model runs
        # with (jax-free import: the peak table only)
        from .telemetry.costmodel import PerfConfig

        PerfConfig.from_config(self.perf)
        if self.device_replay not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown device_replay {self.device_replay!r}")
        if self.generation_opponent:
            unknown = set(self.generation_opponent) - {
                "past_epochs", "prob"}
            if unknown:
                raise ValueError(
                    f"unknown generation_opponent keys: "
                    f"{sorted(unknown)}")
            if int(self.generation_opponent.get(
                    "past_epochs", 0)) < 1:
                raise ValueError(
                    "generation_opponent.past_epochs must be >= 1")
            prob = float(self.generation_opponent.get("prob", 0.25))
            if not 0.0 < prob <= 1.0:
                raise ValueError(
                    "generation_opponent.prob must be in (0, 1]")

    # The reference floors the eval rate so at least ~n^0.85 of every
    # update window is evaluation (/root/reference/handyrl/train.py:415).
    @property
    def effective_eval_rate(self) -> float:
        floor = (self.update_episodes ** 0.85) / self.update_episodes
        return max(self.eval_rate, floor)

    @property
    def batch_steps(self) -> int:
        return self.burn_in_steps + self.forward_steps

    # -- mapping-style access (keys mirror the YAML schema) --
    _ALIASES = {"lambda": "lambda_"}

    def __getitem__(self, key: str):
        key = self._ALIASES.get(key, key)
        value = getattr(self, key)
        if isinstance(value, (WorkerConfig, EvalConfig)):
            return dataclasses.asdict(value)
        return value

    def __contains__(self, key: str) -> bool:
        try:
            self[key]
            return True
        except AttributeError:
            return False

    def get(self, key: str, default=None):
        try:
            return self[key]
        except AttributeError:
            return default

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["lambda"] = d.pop("lambda_")
        return d


def _build_train_config(train_args: Dict[str, Any],
                        env_args: Dict[str, Any]) -> TrainConfig:
    args = dict(train_args)
    if "lambda" in args:
        args["lambda_"] = args.pop("lambda")
    worker = WorkerConfig(**args.pop("worker", {}))
    eval_cfg = EvalConfig(**args.pop("eval", {}))
    known = {f.name for f in dataclasses.fields(TrainConfig)}
    unknown = set(args) - known
    if unknown:
        raise ValueError(f"unknown train_args keys: {sorted(unknown)}")
    return TrainConfig(worker=worker, eval=eval_cfg, env=dict(env_args), **args)


@dataclass
class Config:
    """Top-level config mirroring the reference's three YAML sections."""

    env_args: Dict[str, Any]
    train_args: TrainConfig
    worker_args: WorkerConfig

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "Config":
        env_args = dict(raw.get("env_args", {}))
        train = _build_train_config(raw.get("train_args", {}), env_args)
        wraw = dict(raw.get("worker_args", {}))
        wraw.setdefault("num_parallel", 8)
        worker_args = WorkerConfig(**wraw)
        return cls(env_args=env_args, train_args=train, worker_args=worker_args)

    @classmethod
    def load(cls, path: str) -> "Config":
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))
