"""Positive: shared-memory CREATORS that close their mapping but never
unlink the segment — the /dev/shm file outlives every process that
attached (the ~66 MB-per-dead-worker bug class)."""

from multiprocessing import shared_memory


def scratch(size):
    seg = shared_memory.SharedMemory(create=True, size=size)
    seg.buf[0] = 1
    seg.close()
    return True


class Board:
    def __init__(self, size):
        self._seg = shared_memory.SharedMemory(create=True, size=size)

    def close(self):
        self._seg.close()
