"""Perf regression ledger: append run/bench summaries, check drift.

The bench variants and the runtime metrics both end as JSON nobody
re-reads; this script gives them a MEMORY.  ``append`` folds one
source — a bench tail-1 JSON (``{"metric": ..., "value": ...}`` plus
sibling scalars) or a run directory (its ``metrics.jsonl`` tail) —
into one ledger line::

    {"ts": ..., "source": ..., "metrics": {name: value, ...}}

``--check`` then compares the NEWEST entry of each source against the
rolling median of its prior entries, metric by metric, and exits 1
when any regresses past the tolerance IN ITS BAD DIRECTION — the
direction registry below says which way is bad for which family
(steps/s falling is a regression; batch-wait share rising is).
Metrics with no registered direction are archived but never gate.
Fewer than ``--min-prior`` priors = trivially green (a new bench
variant must not fail CI on its first appearance).

The ledger is append-only jsonl (``runs/ledger.jsonl`` by default):
re-appends are cheap, history is a ``jq`` away, and CI uploads the
file as an artifact so the rolling window survives ephemeral runners.
"""

import argparse
import json
import os
import re
import sys
import time

DEFAULT_LEDGER = os.path.join("runs", "ledger.jsonl")

# metric-name regex -> direction ("up" = higher is better, "down" =
# lower is better).  First match wins; unmatched metrics never gate.
DIRECTIONS = [
    (r"(steps|frames|games|episodes)_per_sec", "up"),
    (r"_rps($|_)", "up"),
    (r"^rps($|_)", "up"),
    (r"speedup|_ratio$|_vs_", "up"),
    (r"^value$", "up"),
    (r"^mfu", "up"),
    (r"achieved_tflops", "up"),
    (r"tflops_est", "up"),
    (r"amortization|_amortized", "up"),
    (r"degradation", "up"),        # chaos/clean ratio, 1.0 = free
    (r"share$", "down"),           # batch_wait/residual wall shares
    (r"recovery_sec", "down"),
    (r"wait_sec", "down"),
    (r"latency|_p50|_p99|_ms($|_)", "down"),
]


def direction(name):
    for pattern, sense in DIRECTIONS:
        if re.search(pattern, name):
            return sense
    return None


def _numbers(doc):
    """Top-level numeric scalars of a bench JSON (bools excluded)."""
    out = {}
    for key, value in doc.items():
        if isinstance(value, bool):
            continue
        if isinstance(value, (int, float)):
            out[key] = value
    return out


def summarize_run(run_dir, tail=5):
    """A run directory's ledger metrics from its metrics.jsonl tail:
    throughput, MFU, and the wall-share decomposition the attribution
    layer emits (batch-wait share, untracked-residual share)."""
    path = os.path.join(run_dir, "metrics.jsonl")
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    if not records:
        raise SystemExit(f"{path}: no records")
    window = records[-tail:]
    walls = [r.get("epoch_wall_sec") or 0.0 for r in window]
    metrics = {}

    def med(values):
        values = sorted(values)
        n = len(values)
        if not n:
            return None
        mid = n // 2
        return (values[mid] if n % 2
                else (values[mid - 1] + values[mid]) / 2.0)

    # steps/s from the cumulative step counter across the tail window
    first, last = window[0], window[-1]
    dsteps = (last.get("steps") or 0) - (first.get("steps") or 0)
    dwall = sum(walls[1:])
    if dsteps > 0 and dwall > 0:
        metrics["steps_per_sec"] = round(dsteps / dwall, 3)
    for key in ("mfu", "achieved_tflops", "arithmetic_intensity"):
        values = [r[key] for r in window
                  if isinstance(r.get(key), (int, float))]
        if values:
            metrics[key] = round(med(values), 4)
    for key, share in (("batch_wait_sec", "batch_wait_share"),
                       ("untracked_residual_sec", "residual_share")):
        shares = [r[key] / r["epoch_wall_sec"] for r in window
                  if isinstance(r.get(key), (int, float))
                  and (r.get("epoch_wall_sec") or 0) > 0]
        if shares:
            metrics[share] = round(med(shares), 4)
    return metrics


def load_source(path):
    """(default source name, metrics) for one append input: a bench
    tail-1 JSON file or a run directory."""
    if os.path.isdir(path):
        return os.path.basename(os.path.normpath(path)), \
            summarize_run(path)
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise SystemExit(f"{path}: expected a JSON object")
    name = doc.get("metric") or \
        os.path.splitext(os.path.basename(path))[0]
    metrics = _numbers(doc)
    if not metrics:
        raise SystemExit(f"{path}: no numeric metrics to ledger")
    return name, metrics


def read_ledger(path):
    entries = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    entries.append(json.loads(line))
    return entries


def append_entry(ledger_path, source, metrics, ts=None):
    entry = {
        "ts": round(float(ts if ts is not None else time.time()), 3),
        "source": source,
        "metrics": metrics,
    }
    parent = os.path.dirname(ledger_path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(ledger_path, "a") as f:
        f.write(json.dumps(entry) + "\n")
    return entry


def _median(values):
    values = sorted(values)
    mid = len(values) // 2
    return (values[mid] if len(values) % 2
            else (values[mid - 1] + values[mid]) / 2.0)


def check(entries, tolerance=0.25, window=5, min_prior=2):
    """Regression verdicts for the newest entry of every source.

    Returns (failures, report_lines).  A metric fails when the newest
    value is past ``tolerance`` (fractional) of the rolling median of
    up to ``window`` prior same-source values, in its bad direction.
    """
    failures = []
    lines = []
    by_source = {}
    for entry in entries:
        by_source.setdefault(entry["source"], []).append(entry)
    for source in sorted(by_source):
        history = by_source[source]
        newest = history[-1]
        priors = history[:-1][-window:]
        for name in sorted(newest["metrics"]):
            value = newest["metrics"][name]
            sense = direction(name)
            prior_values = [e["metrics"][name] for e in priors
                            if isinstance(e["metrics"].get(name),
                                          (int, float))]
            if sense is None or len(prior_values) < min_prior:
                status = "skip" if sense is None else "new"
                lines.append(f"  .  {source}/{name} = {value} "
                             f"({status})")
                continue
            base = _median(prior_values)
            if base == 0:
                lines.append(f"  .  {source}/{name} = {value} "
                             "(zero baseline)")
                continue
            delta = (value - base) / abs(base)
            bad = -delta if sense == "up" else delta
            mark = "REGRESS" if bad > tolerance else "ok"
            lines.append(
                f"  {mark:>7} "
                f"{source}/{name} = {value} vs median {round(base, 4)} "
                f"({'+' if delta >= 0 else ''}{round(delta * 100, 1)}%"
                f", {sense}-is-better, n={len(prior_values)})")
            if mark == "REGRESS":
                failures.append((source, name, value, base, delta))
    return failures, lines


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("inputs", nargs="*",
                        help="bench tail-1 JSON files and/or run "
                             "directories to append")
    parser.add_argument("--ledger", default=DEFAULT_LEDGER)
    parser.add_argument("--source", default=None,
                        help="override the source tag (one input only)")
    parser.add_argument("--ts", type=float, default=None,
                        help="entry timestamp (default: now)")
    parser.add_argument("--check", action="store_true",
                        help="verdict the newest entry per source "
                             "against the rolling median; exit 1 on "
                             "any regression")
    parser.add_argument("--tolerance", type=float, default=0.25,
                        help="fractional regression tolerance "
                             "(default 0.25)")
    parser.add_argument("--window", type=int, default=5,
                        help="rolling-median window of prior entries")
    parser.add_argument("--min-prior", type=int, default=2,
                        help="priors needed before a metric can gate")
    args = parser.parse_args(argv)
    if args.source and len(args.inputs) > 1:
        parser.error("--source needs exactly one input")
    if not args.inputs and not args.check:
        parser.error("nothing to do: no inputs and no --check")

    for path in args.inputs:
        source, metrics = load_source(path)
        entry = append_entry(args.ledger, args.source or source,
                             metrics, ts=args.ts)
        print(f"appended {entry['source']}: "
              f"{len(entry['metrics'])} metrics -> {args.ledger}")

    if args.check:
        entries = read_ledger(args.ledger)
        if not entries:
            raise SystemExit(f"{args.ledger}: empty ledger")
        failures, lines = check(entries, tolerance=args.tolerance,
                                window=args.window,
                                min_prior=args.min_prior)
        print(f"perf ledger check ({args.ledger}, "
              f"tolerance {args.tolerance:.0%}, window {args.window}):")
        for line in lines:
            print(line)
        if failures:
            print(f"FAIL: {len(failures)} regression(s)")
            return 1
        print("ok: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
