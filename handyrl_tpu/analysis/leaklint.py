"""leaklint — resource-lifecycle & ownership analysis for the fleet.

jaxlint (PR 1) covered the jit layer, shardlint (PR 2) the mesh,
commlint (PR 4) the wire protocol, racelint the interleavings, numlint
the dtype lattice; this module covers the failure class that dominates
*weeks-long* serving runs: resources acquired and never released.  The
review pass of PR 9 found exactly this live — three shm rings (~66 MB)
leaked per dead worker — and only a human caught it; the router makes
replicas long-lived processes whose slow leaks now outrank crashes as
the unmodeled failure mode.  This module computes the package-level
facts the rules in :mod:`.leakrules` consume:

  * **resource-acquisition facts**: every construction of a socket /
    Thread / Process / SharedMemory / file / ThreadingHTTPServer plus
    the repo-local owners (``ShmRing``/``ShmBoard`` create+attach,
    ``FramedConnection``), grown through a *constructor-wrapper
    fixpoint* the way commlint grows send wrappers — a function that
    returns a fresh resource (``open_socket_connection`` returning a
    ``FramedConnection``) is itself a constructor at its call sites;
  * the **ownership / escape lattice**: a resource that is returned,
    stored on ``self``, yielded, passed to another call, or put in a
    container TRANSFERS its close obligation to the new owner; one
    that stays function-local must be released on every path out;
  * **per-path release coverage**: which exits (returns, fall-off-end)
    a local resource can take while still live, whether its releases
    sit inside ``finally``/``with`` (exception-safe) or on the happy
    path only, and whether two unconditional releases double-fire;
  * per-class **attribute-lifecycle tables**: every ``self.X = <fresh
    resource>`` store with its guard discipline (an ``is None`` check,
    a prior release/``None``-assign/swap in the same function, a call
    to a sibling method whose summary releases the attribute, or the
    *entry-guard* idiom where every in-package caller checks first —
    the WAL ``_open_segment`` shape), plus every ``self.X.close()``/
    ``.join()``/``.unlink()``/``= None`` release event.

Everything is stdlib ``ast`` only — like its five siblings the
analyzer never imports jax (or opens a socket).  The abstraction is
deliberately approximate in the quiet direction: only named locals and
``self.X`` state participate, any escape transfers the obligation, a
release in either branch of a conditional counts, and ``daemon=True``
threads/processes carry no join obligation (dropping their handle is a
supported fire-and-forget idiom — the ``_stop``-flag shutdown
discipline racelint already audits).  The per-line suppression syntax
is the escape hatch for intentional process-lifetime resources.
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .astutil import (
    FunctionInfo,
    ModuleInfo,
    Package,
    _enclosing_class,
    dotted_parts,
)

# -- name tables ------------------------------------------------------

# full dotted constructor names -> resource kind
RESOURCE_CTORS = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "socket.create_server": "socket",
    "socket.socketpair": "socket",
    "threading.Thread": "thread",
    "threading.Timer": "thread",
    "multiprocessing.Process": "process",
    "open": "file",
    "io.open": "file",
    "tempfile.NamedTemporaryFile": "file",
    "tempfile.TemporaryFile": "file",
    "http.server.ThreadingHTTPServer": "server",
    "http.server.HTTPServer": "server",
    "socketserver.ThreadingTCPServer": "server",
    "socketserver.TCPServer": "server",
}

# trailing-name fallbacks for constructors reached through handles the
# resolver cannot chase: ``_mp = mp.get_context("spawn")`` then
# ``_mp.Process(...)``, re-exported repo classes (``FramedConnection``
# is a class, so resolve_callee reports it as an external name), and
# the ``ShmRing.create`` classmethod spelling
RESOURCE_CTOR_SUFFIXES = {
    ".Process": "process",
    ".SharedMemory": "shm",
    ".FramedConnection": "conn",
    ".ShmRing.create": "shm_ring",
    ".ShmRing.attach": "shm_ring",
    ".ShmBoard.create": "shm_ring",
    ".ShmBoard.attach": "shm_ring",
    ".ThreadingHTTPServer": "server",
}

# method names that discharge a close obligation on their receiver
RELEASE_VERBS = frozenset({
    "close", "shutdown", "terminate", "kill", "join", "unlink",
    "stop", "disconnect", "server_close", "cancel", "release",
})

# with-statement wrappers that adopt their argument's close obligation
CLOSING_WRAPPERS = frozenset({"contextlib.closing", "closing"})

# kinds whose dropped handle is never a leak when daemon=True was
# passed (fire-and-forget workers shut down by flag/atexit, the idiom
# racelint's shutdown rules already audit)
_DAEMONIZABLE = frozenset({"thread", "process"})


def _human_kind(kind: str) -> str:
    return {
        "socket": "socket", "thread": "thread", "process": "process",
        "shm": "shared-memory segment", "shm_ring": "shm ring",
        "conn": "framed connection", "file": "file handle",
        "server": "server socket",
    }.get(kind, kind)


# -- facts ------------------------------------------------------------

@dataclass
class Release:
    """One release call on a tracked resource."""

    line: int
    verb: str
    depth: int                   # conditional nesting at the call
    in_finally: bool
    in_handler: bool
    finally_of: Optional[int]    # id() of the Try whose finalbody holds it


@dataclass
class Acq:
    """One resource acquisition."""

    fn: FunctionInfo
    node: ast.AST                # the constructor call
    kind: str
    name: Optional[str]          # bound local name, None when unbound
    line: int
    daemon: bool = False
    shm_create: bool = False
    via_with: bool = False       # acquired by a with statement
    escaped: bool = False        # obligation transferred to a new owner
    releases: List[Release] = field(default_factory=list)
    risky: bool = False          # some call ran while live & unreleased
    leak_exits: List[int] = field(default_factory=list)


@dataclass
class AttrStore:
    """``self.X = <fresh resource>`` — ownership transferred to self."""

    cls: str
    attr: str
    fn: FunctionInfo
    node: ast.AST
    kind: str
    daemon: bool
    shm_create: bool
    line: int
    guarded: bool = False        # computed after all functions walk


@dataclass
class AttrEvent:
    """A lifecycle event on ``self.X``: a release verb, ``= None``
    ("clear"), a takeover read into a local ("swap"), or an ``is
    None``-style test ("guard")."""

    cls: str
    attr: str
    fn: FunctionInfo
    verb: str
    line: int
    depth: int
    in_finally: bool


def _fn_body(fn: FunctionInfo) -> List[ast.stmt]:
    if isinstance(fn.node, ast.Lambda):
        return [ast.copy_location(ast.Expr(fn.node.body),
                                  fn.node.body)]
    return fn.node.body


def _in_ctor(fn: FunctionInfo) -> bool:
    """Is this function ``__init__`` (or nested inside it)?  The first
    store of an attribute there has no previous incarnation to leak."""
    probe = fn
    while probe is not None:
        if probe.qname.rsplit(":", 1)[-1].split(".")[-1] == "__init__":
            return True
        probe = probe.parent
    return False


def _method_name(fn: FunctionInfo) -> str:
    return fn.qname.rsplit(":", 1)[-1].split(".")[-1]


def _own_stmts(fn: FunctionInfo):
    """The function's own statements, excluding nested def/class
    bodies (those analyze as their own functions)."""
    stack = list(_fn_body(fn))
    while stack:
        stmt = stack.pop()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield stmt
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                stack.append(child)


def _self_attr2(expr) -> Optional[str]:
    """``self.X`` (exactly two parts) -> ``X``."""
    parts = dotted_parts(expr)
    if parts is not None and len(parts) == 2 and parts[0] == "self":
        return parts[1]
    return None


def _kw_true(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


class LeakAnalysis:
    """All resource-lifecycle facts of one package, computed once."""

    MAX_PASSES = 4

    def __init__(self, package: Package):
        self.pkg = package
        self.acqs: List[Acq] = []
        self.attr_stores: Dict[Tuple[str, str], List[AttrStore]] = {}
        self.attr_events: Dict[Tuple[str, str], List[AttrEvent]] = {}
        self.fn_attr_events: Dict[FunctionInfo, List[AttrEvent]] = {}
        self.self_calls: Dict[FunctionInfo,
                              List[Tuple[str, int]]] = {}
        # constructor-wrapper summaries (the commlint fixpoint shape)
        self.returns_kind: Dict[FunctionInfo, str] = {}
        self.returns_daemon: Dict[FunctionInfo, bool] = {}
        # per-method released-attribute summaries (self-call closure)
        self.releases_attrs: Dict[FunctionInfo, Set[str]] = {}
        self._by_method: Dict[Tuple[str, str], List[FunctionInfo]] = {}

        for mod in self.pkg.modules.values():
            for fn in mod.functions:
                if fn.cls_name is not None:
                    self._by_method.setdefault(
                        (fn.cls_name, _method_name(fn)), []).append(fn)

        self._compute_wrapper_fixpoint()
        self._walk_functions()
        self._compute_release_summaries()
        self._mark_guarded_stores()

    # -- constructor kinds --------------------------------------------
    def ctor_kind(self, fn: Optional[FunctionInfo], mod: ModuleInfo,
                  call) -> Optional[Tuple[str, bool, bool]]:
        """A call that yields a FRESH resource -> (kind, daemon,
        shm_create), else None.  Wrapper summaries make in-package
        functions returning fresh resources constructors too."""
        if not isinstance(call, ast.Call):
            return None
        name = self.pkg.full_name(mod, fn, call.func)
        kind = None
        if name is not None:
            kind = RESOURCE_CTORS.get(name)
            if kind is None:
                for suffix, k in RESOURCE_CTOR_SUFFIXES.items():
                    if name == suffix[1:] or name.endswith(suffix):
                        kind = k
                        break
        if kind is None:
            res = self.pkg.resolve_callee(mod, fn, call.func)
            if res is not None and res[0] == "fn":
                wrapped = self.returns_kind.get(res[1])
                if wrapped is not None:
                    return (wrapped,
                            self.returns_daemon.get(res[1], False),
                            False)
            return None
        daemon = kind in _DAEMONIZABLE and _kw_true(call, "daemon")
        shm_create = kind == "shm" and _kw_true(call, "create")
        return kind, daemon, shm_create

    def _compute_wrapper_fixpoint(self):
        """Grow ``returns_kind``: a function returning a direct
        constructor result (or a local bound to one, or a call into an
        already-summarized wrapper) is a constructor itself."""
        for _ in range(self.MAX_PASSES):
            changed = False
            for fn in self.pkg.all_functions():
                if fn in self.returns_kind:
                    continue
                summary = self._returns_fresh(fn)
                if summary is not None:
                    self.returns_kind[fn] = summary[0]
                    self.returns_daemon[fn] = summary[1]
                    changed = True
            if not changed:
                break

    def _returns_fresh(self, fn: FunctionInfo):
        fresh: Dict[str, Tuple[str, bool]] = {}
        found = None
        for stmt in sorted(_own_stmts(fn),
                           key=lambda s: (s.lineno,
                                          getattr(s, "col_offset", 0))):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                summary = self.ctor_kind(fn, fn.module, stmt.value)
                if summary is not None:
                    fresh[stmt.targets[0].id] = (summary[0], summary[1])
                else:
                    fresh.pop(stmt.targets[0].id, None)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                summary = self.ctor_kind(fn, fn.module, stmt.value)
                if summary is not None:
                    found = (summary[0], summary[1])
                elif isinstance(stmt.value, ast.Name) \
                        and stmt.value.id in fresh:
                    found = fresh[stmt.value.id]
        return found

    # -- per-function walk --------------------------------------------
    def _walk_functions(self):
        for mod in self.pkg.modules.values():
            for fn in mod.functions:
                _FnWalker(self, fn).run()

    def record_attr_event(self, fn, attr, verb, line, depth,
                          in_finally):
        cls = _enclosing_class(fn)
        if cls is None:
            return
        ev = AttrEvent(cls, attr, fn, verb, line, depth, in_finally)
        self.attr_events.setdefault((cls, attr), []).append(ev)
        self.fn_attr_events.setdefault(fn, []).append(ev)

    def record_attr_store(self, fn, attr, node, kind, daemon,
                          shm_create, line):
        cls = _enclosing_class(fn)
        if cls is None:
            return
        self.attr_stores.setdefault((cls, attr), []).append(AttrStore(
            cls, attr, fn, node, kind, daemon, shm_create, line))

    # -- summaries & guards -------------------------------------------
    def _compute_release_summaries(self):
        """Per-method released-attribute sets, closed over self-method
        calls (``respawn() -> _teardown_sockets()`` releases the
        listener too)."""
        for fn, events in self.fn_attr_events.items():
            attrs = {e.attr for e in events if e.verb != "guard"}
            if attrs:
                self.releases_attrs[fn] = set(attrs)
        for _ in range(self.MAX_PASSES):
            changed = False
            for fn, calls in self.self_calls.items():
                cls = _enclosing_class(fn)
                if cls is None:
                    continue
                mine = self.releases_attrs.get(fn)
                for mname, _line in calls:
                    for callee in self._by_method.get((cls, mname), ()):
                        theirs = self.releases_attrs.get(callee)
                        if not theirs:
                            continue
                        if mine is None:
                            mine = self.releases_attrs.setdefault(
                                fn, set())
                        add = theirs - mine
                        if add:
                            mine |= add
                            changed = True
            if not changed:
                break

    def _precedes(self, fn: FunctionInfo, attr: str, line: int) -> bool:
        """A guard / release / clear / swap of ``self.attr`` (direct,
        or via a self-method call whose summary releases it) lexically
        before ``line`` in this function."""
        for e in self.fn_attr_events.get(fn, ()):
            if e.attr == attr and e.line < line:
                return True
        cls = _enclosing_class(fn)
        if cls is not None:
            for mname, cline in self.self_calls.get(fn, ()):
                if cline >= line:
                    continue
                for callee in self._by_method.get((cls, mname), ()):
                    if attr in self.releases_attrs.get(callee, ()):
                        return True
        return False

    def _mark_guarded_stores(self):
        sites: Dict[Tuple[str, str],
                    List[Tuple[FunctionInfo, int]]] = {}
        for fn, calls in self.self_calls.items():
            cls = _enclosing_class(fn)
            if cls is None:
                continue
            for mname, line in calls:
                sites.setdefault((cls, mname), []).append((fn, line))
        for (cls, attr), stores in self.attr_stores.items():
            for st in stores:
                if _in_ctor(st.fn):
                    st.guarded = True
                    continue
                if self._precedes(st.fn, attr, st.line):
                    st.guarded = True
                    continue
                # entry-guard idiom: every in-package caller of this
                # method checks/releases the attribute first (the WAL
                # ``append() -> _open_segment()`` shape)
                csites = sites.get((cls, _method_name(st.fn)), ())
                if csites and all(self._precedes(cf, attr, cl)
                                  for cf, cl in csites):
                    st.guarded = True


class _FnWalker:
    """Lexical walk of one function body tracking live local resources
    and per-class attribute lifecycle events."""

    def __init__(self, an: LeakAnalysis, fn: FunctionInfo):
        self.an = an
        self.fn = fn
        self.mod = fn.module
        self.live: Dict[str, Acq] = {}
        # (acq, exit line, enclosing try-with-finally ids)
        self.pending: List[Tuple[Acq, int, Tuple[int, ...]]] = []
        self.try_stack: List[int] = []

    def run(self):
        for stmt in _fn_body(self.fn):
            self._stmt(stmt, 0, False, False, None)
        end = getattr(self.fn.node, "end_lineno", None) \
            or self.fn.node.lineno
        for acq in self.live.values():
            self.pending.append((acq, end, ()))
        for acq, line, tries in self.pending:
            if acq.escaped or acq.via_with:
                continue
            covered = any(
                r.line <= line
                or (r.in_finally and r.finally_of in tries)
                for r in acq.releases)
            if not covered:
                acq.leak_exits.append(line)

    # -- acquisition / release plumbing -------------------------------
    def _acquire(self, call, kind, daemon, shm_create, name):
        acq = Acq(self.fn, call, kind, name, call.lineno,
                  daemon=daemon, shm_create=shm_create)
        self.an.acqs.append(acq)
        if name is not None:
            self.live[name] = acq
        return acq

    def _release_live(self, name, verb, line, depth, in_finally,
                      in_handler, finally_of):
        acq = self.live.get(name)
        if acq is None:
            return False
        acq.releases.append(Release(line, verb, depth, in_finally,
                                    in_handler, finally_of))
        return True

    def _escape(self, name):
        acq = self.live.pop(name, None)
        if acq is not None:
            acq.escaped = True

    def _mark_risky(self, skip: Optional[str] = None):
        for name, acq in self.live.items():
            if name != skip and not acq.releases:
                acq.risky = True

    # -- statements ----------------------------------------------------
    def _stmt(self, stmt, depth, in_finally, in_handler, finally_of):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value, depth, in_finally,
                         in_handler, finally_of)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign([stmt.target], stmt.value, depth,
                             in_finally, in_handler, finally_of)
        elif isinstance(stmt, ast.AugAssign):
            self._value(stmt.value, depth, in_finally, in_handler,
                        finally_of)
        elif isinstance(stmt, ast.Expr):
            self._value(stmt.value, depth, in_finally, in_handler,
                        finally_of)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._value(stmt.value, depth, in_finally, in_handler,
                            finally_of, escaping=True)
            for acq in self.live.values():
                self.pending.append((acq, stmt.lineno,
                                     tuple(self.try_stack)))
        elif isinstance(stmt, ast.If):
            self._guard_test(stmt.test, depth, in_finally)
            self._value(stmt.test, depth, in_finally, in_handler,
                        finally_of)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, depth + 1, in_finally, in_handler,
                           finally_of)
        elif isinstance(stmt, ast.While):
            self._guard_test(stmt.test, depth, in_finally)
            self._value(stmt.test, depth, in_finally, in_handler,
                        finally_of)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, depth + 1, in_finally, in_handler,
                           finally_of)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._value(stmt.iter, depth, in_finally, in_handler,
                        finally_of)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, depth + 1, in_finally, in_handler,
                           finally_of)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._with(stmt, depth, in_finally, in_handler, finally_of)
        elif isinstance(stmt, ast.Try):
            tid = id(stmt) if stmt.finalbody else None
            if tid is not None:
                self.try_stack.append(tid)
            for s in stmt.body:
                self._stmt(s, depth, in_finally, in_handler, finally_of)
            for handler in stmt.handlers:
                for s in handler.body:
                    self._stmt(s, depth + 1, in_finally, True,
                               finally_of)
            for s in stmt.orelse:
                self._stmt(s, depth, in_finally, in_handler, finally_of)
            if tid is not None:
                self.try_stack.pop()
            for s in stmt.finalbody:
                self._stmt(s, depth, True, in_handler, tid)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                attr = _self_attr2(tgt)
                if attr is not None:
                    self.an.record_attr_event(
                        self.fn, attr, "clear", stmt.lineno, depth,
                        in_finally)
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._value(child, depth, in_finally, in_handler,
                                finally_of)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._value(child, depth, in_finally, in_handler,
                                finally_of)
                elif isinstance(child, ast.stmt):
                    self._stmt(child, depth, in_finally, in_handler,
                               finally_of)

    def _guard_test(self, test, depth, in_finally):
        """``if self.X is None:`` / ``if not self.X:`` / ``if
        self.X:`` — a liveness check that precedes a re-store."""
        probes = [test]
        if isinstance(test, ast.UnaryOp) \
                and isinstance(test.op, ast.Not):
            probes.append(test.operand)
        if isinstance(test, ast.BoolOp):
            probes.extend(test.values)
        for probe in probes:
            attr = _self_attr2(probe)
            if attr is not None:
                self.an.record_attr_event(self.fn, attr, "guard",
                                          probe.lineno, depth,
                                          in_finally)

    def _with(self, stmt, depth, in_finally, in_handler, finally_of):
        for item in stmt.items:
            ce = item.context_expr
            summary = self.an.ctor_kind(self.fn, self.mod, ce)
            if summary is not None:
                # with socket.socket() as s: — released on exit
                self._acquire(ce, summary[0], summary[1], summary[2],
                              None).via_with = True
                continue
            if isinstance(ce, ast.Call):
                name = self.an.pkg.full_name(self.mod, self.fn, ce.func)
                if name in CLOSING_WRAPPERS and ce.args:
                    inner = ce.args[0]
                    inner_summary = self.an.ctor_kind(self.fn, self.mod,
                                                      inner)
                    if inner_summary is not None:
                        self._acquire(
                            inner, inner_summary[0], inner_summary[1],
                            inner_summary[2], None).via_with = True
                        continue
                    if isinstance(inner, ast.Name):
                        if self._release_live(
                                inner.id, "close", ce.lineno, depth,
                                True, False, None):
                            continue
            if isinstance(ce, ast.Name) and ce.id in self.live:
                # with sock: — the CM protocol closes it on exit
                self._release_live(ce.id, "close", ce.lineno, depth,
                                   True, False, None)
                continue
            self._value(ce, depth, in_finally, in_handler, finally_of)
        for s in stmt.body:
            self._stmt(s, depth, in_finally, in_handler, finally_of)

    def _assign(self, targets, value, depth, in_finally, in_handler,
                finally_of):
        # pairwise tuple assignment (the teardown swap idiom:
        # ``listener, self._listener = self._listener, None``)
        if len(targets) == 1 \
                and isinstance(targets[0], (ast.Tuple, ast.List)) \
                and isinstance(value, (ast.Tuple, ast.List)) \
                and len(targets[0].elts) == len(value.elts):
            for tgt, val in zip(targets[0].elts, value.elts):
                self._assign([tgt], val, depth, in_finally, in_handler,
                             finally_of)
            return
        summary = self.an.ctor_kind(self.fn, self.mod, value)
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                if summary is not None:
                    self._acquire(value, summary[0], summary[1],
                                  summary[2], tgt.id)
                    return
                if isinstance(value, ast.Name) \
                        and value.id in self.live:
                    self.live[tgt.id] = self.live.pop(value.id)
                    if self.live[tgt.id].name is not None:
                        self.live[tgt.id].name = tgt.id
                    return
                self.live.pop(tgt.id, None)
                attr = _self_attr2(value)
                if attr is not None:
                    # local takeover of an attribute-held resource
                    self.an.record_attr_event(
                        self.fn, attr, "swap", value.lineno, depth,
                        in_finally)
                self._value(value, depth, in_finally, in_handler,
                            finally_of)
                return
            attr = _self_attr2(tgt)
            if attr is not None:
                if summary is not None:
                    self.an.record_attr_store(
                        self.fn, attr, value, summary[0], summary[1],
                        summary[2], tgt.lineno)
                    return
                if isinstance(value, ast.Name) \
                        and value.id in self.live:
                    acq = self.live[value.id]
                    self.an.record_attr_store(
                        self.fn, attr, value, acq.kind, acq.daemon,
                        acq.shm_create, tgt.lineno)
                    self._escape(value.id)
                    return
                if isinstance(value, ast.Constant) \
                        and value.value is None:
                    self.an.record_attr_event(
                        self.fn, attr, "clear", tgt.lineno, depth,
                        in_finally)
                    return
                self._value(value, depth, in_finally, in_handler,
                            finally_of)
                return
            if isinstance(tgt, (ast.Subscript, ast.Attribute,
                                ast.Starred)):
                # container / foreign-object store transfers ownership
                if summary is None:
                    if isinstance(value, ast.Name) \
                            and value.id in self.live:
                        self._escape(value.id)
                    else:
                        self._value(value, depth, in_finally,
                                    in_handler, finally_of)
                if isinstance(tgt, ast.Subscript):
                    self._value(tgt.slice, depth, in_finally,
                                in_handler, finally_of)
                return
            self._value(value, depth, in_finally, in_handler,
                        finally_of)

    # -- expressions ---------------------------------------------------
    def _value(self, expr, depth, in_finally, in_handler, finally_of,
               escaping=False):
        if expr is None or isinstance(expr, (ast.Constant, ast.Lambda)):
            return
        release_calls = set()
        any_call = False
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                any_call = True
                func = node.func
                if isinstance(func, ast.Attribute):
                    parts = dotted_parts(func)
                    # conn.close() on a live local
                    if isinstance(func.value, ast.Name) \
                            and func.attr in RELEASE_VERBS \
                            and self._release_live(
                                func.value.id, func.attr, node.lineno,
                                depth, in_finally, in_handler,
                                finally_of):
                        release_calls.add(node)
                        continue
                    # self.X.close() — an attribute-lifecycle event
                    if parts is not None and len(parts) == 3 \
                            and parts[0] == "self" \
                            and parts[2] in RELEASE_VERBS:
                        self.an.record_attr_event(
                            self.fn, parts[1], parts[2], node.lineno,
                            depth, in_finally)
                        continue
                    # self.method() — recorded for release summaries
                    if parts is not None and len(parts) == 2 \
                            and parts[0] == "self":
                        self.an.self_calls.setdefault(
                            self.fn, []).append((parts[1], node.lineno))
            elif isinstance(node, ast.Compare) \
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in node.ops):
                for side in [node.left] + list(node.comparators):
                    attr = _self_attr2(side)
                    if attr is not None:
                        self.an.record_attr_event(
                            self.fn, attr, "guard", node.lineno, depth,
                            in_finally)
        # escapes: live names passed as call arguments (directly or in
        # literal containers), yielded, or — for return values — used
        # anywhere in the returned expression
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and node not in release_calls:
                for arg in list(node.args) \
                        + [kw.value for kw in node.keywords]:
                    for name in self._literal_names(arg):
                        self._escape(name)
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and node.value is not None:
                for name in self._literal_names(node.value):
                    self._escape(name)
        if escaping:
            for name in self._literal_names(expr):
                self._escape(name)
        if any_call:
            self._mark_risky()

    def _literal_names(self, expr) -> List[str]:
        """Names (possibly inside tuple/list/dict/set literals) whose
        VALUE flows to a new owner — ``f(conn)``, ``return (a, conn)``,
        ``lst.append((t, conn))``.  ``conn.fileno()`` or an f-string
        mention does not move ownership."""
        out: List[str] = []
        stack = [expr]
        while stack:
            e = stack.pop()
            if isinstance(e, ast.Name):
                if e.id in self.live:
                    out.append(e.id)
            elif isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                stack.extend(e.elts)
            elif isinstance(e, ast.Dict):
                stack.extend(v for v in e.values if v is not None)
            elif isinstance(e, ast.Starred):
                stack.append(e.value)
            elif isinstance(e, ast.IfExp):
                stack.extend([e.body, e.orelse])
        return out


def analyze_leaks(package: Package) -> LeakAnalysis:
    """Compute (or fetch the cached) resource-lifecycle analysis."""
    cached = getattr(package, "_leaklint_analysis", None)
    if cached is None:
        cached = LeakAnalysis(package)
        package._leaklint_analysis = cached
    return cached
