"""Golden parity tests against the reference implementation's math.

Identical synthetic episodes flow through the reference's
``make_batch`` / ``compute_loss`` (torch, imported from
/root/reference — never copied) and through our
``handyrl_tpu.batch.make_batch`` / ``handyrl_tpu.ops.losses``.  Batch
tensors must match exactly and loss components to float32 tolerance —
specifically covering the two paths SURVEY §7 flags as subtle:

  * turn-alternating policy gather (reference train.py:178-182):
    the (B,T,1,A) policy broadcast against the (B,T,P,1) turn mask and
    summed back to the acting seat;
  * two-player zero-sum value symmetrization (train.py:244-248).

A deterministic stub net (same fixed weights on both sides) isolates
the learner math from unrelated architecture differences.
"""

import bz2
import os
import pickle
import random
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

REFERENCE_ROOT = "/root/reference"

# these tests cross-check against the reference checkout + torch;
# skip cleanly where either is absent (e.g. public CI runners)
pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(REFERENCE_ROOT, "handyrl")),
    reason="reference checkout not available")
pytest.importorskip("torch")
MOMENT_KEYS = (
    "observation", "selected_prob", "action_mask", "action",
    "value", "reward", "return",
)

OBS_SHAPE = (3, 3, 2)
NUM_ACTIONS = 5


def _reference_train():
    if REFERENCE_ROOT not in sys.path:
        sys.path.insert(0, REFERENCE_ROOT)
    from handyrl import train as ref_train

    return ref_train


def base_cfg(**over):
    cfg = {
        "turn_based_training": True,
        "observation": False,
        "gamma": 0.9,
        "forward_steps": 8,
        "burn_in_steps": 0,
        "compress_steps": 3,
        "entropy_regularization": 0.3,
        "entropy_regularization_decay": 0.25,
        "lambda": 0.7,
        "policy_target": "VTRACE",
        "value_target": "VTRACE",
    }
    cfg.update(over)
    return cfg


def synth_episode(rng, T, P, turn_based):
    """One episode in the shared moment wire schema."""
    moments = []
    for t in range(T):
        turn = [t % P] if turn_based else list(range(P))
        m = {key: {p: None for p in range(P)} for key in MOMENT_KEYS}
        for p in range(P):
            acting = p in turn
            if acting:  # observation=False: only actors observe
                m["observation"][p] = rng.normal(
                    size=OBS_SHAPE).astype(np.float32)
                m["value"][p] = np.array(
                    [rng.uniform(-1, 1)], np.float32)
                mask = np.zeros(NUM_ACTIONS, np.float32)
                illegal = rng.choice(
                    NUM_ACTIONS, size=rng.integers(0, 3), replace=False)
                mask[illegal] = 1e32
                legal = np.flatnonzero(mask == 0)
                m["action_mask"][p] = mask
                m["action"][p] = int(rng.choice(legal))
                m["selected_prob"][p] = float(rng.uniform(0.2, 0.9))
            m["reward"][p] = float(rng.normal() * 0.1)
        m["turn"] = turn
        moments.append(m)

    gamma = 0.9
    for p in range(P):
        ret = 0.0
        for m in reversed(moments):
            ret = m["reward"][p] + gamma * ret
            m["return"][p] = ret

    outcome = {p: float(rng.choice([-1.0, 1.0])) for p in range(P)}
    return {
        "args": {"player": list(range(P))},
        "steps": T,
        "outcome": outcome,
        "moment": [
            bz2.compress(pickle.dumps(moments[i:i + 3]))
            for i in range(0, T, 3)
        ],
    }


def select_window(ep, cfg, train_start):
    st = max(0, train_start - cfg["burn_in_steps"])
    ed = min(train_start + cfg["forward_steps"], ep["steps"])
    cmp = cfg["compress_steps"]
    st_block, ed_block = st // cmp, (ed - 1) // cmp + 1
    return {
        "args": ep["args"], "outcome": ep["outcome"],
        "moment": ep["moment"][st_block:ed_block],
        "base": st_block * cmp,
        "start": st, "end": ed, "train_start": train_start,
        "total": ep["steps"],
    }


def make_selections(cfg, turn_based, P, n=6, seed=7):
    rng = np.random.default_rng(seed)
    sels = []
    for i in range(n):
        # mix of long and short episodes: exercise the padding path
        T = [12, 12, 5, 9, 3, 12][i % 6]
        ep = synth_episode(rng, T, P, turn_based)
        train_start = int(rng.integers(
            0, 1 + max(0, T - cfg["forward_steps"])))
        sels.append(select_window(ep, cfg, train_start))
    return sels


def both_batches(cfg, turn_based, P):
    from handyrl_tpu.batch import make_batch as our_make_batch

    ref_train = _reference_train()
    sels = make_selections(cfg, turn_based, P)
    # non-turn-based solo training picks a random player per episode;
    # same seed + same call order => same picks on both sides
    random.seed(123)
    ours = our_make_batch([dict(s) for s in sels], cfg)
    random.seed(123)
    theirs = ref_train.make_batch([dict(s) for s in sels], cfg)
    return ours, theirs


CONFIGS = {
    "turnbased_vtrace": (base_cfg(), True, 2),
    "turnbased_upgo_td": (
        base_cfg(policy_target="UPGO", value_target="TD"), True, 2),
    "turnbased_burnin": (
        base_cfg(burn_in_steps=3, forward_steps=6), True, 2),
    "simul_upgo_td": (
        base_cfg(turn_based_training=False, policy_target="UPGO",
                 value_target="TD"), False, 4),
    "simul_mc": (
        base_cfg(turn_based_training=False, policy_target="MC",
                 value_target="MC"), False, 4),
}


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_make_batch_parity(name):
    cfg, turn_based, P = CONFIGS[name]
    ours, theirs = both_batches(cfg, turn_based, P)

    for key in theirs:
        ref_val = theirs[key].detach().numpy()
        our_val = np.asarray(ours[key])
        assert our_val.shape == ref_val.shape, (
            f"{key}: shape {our_val.shape} vs reference {ref_val.shape}")
        np.testing.assert_allclose(
            our_val.astype(np.float64), ref_val.astype(np.float64),
            rtol=0, atol=1e-6, err_msg=key)


class _StubWeights:
    """Fixed stub-net weights shared verbatim by both frameworks."""

    def __init__(self):
        rng = np.random.default_rng(42)
        n_in = int(np.prod(OBS_SHAPE))
        self.w_p = rng.normal(size=(n_in, NUM_ACTIONS)).astype(np.float32)
        self.w_v = rng.normal(size=(n_in, 1)).astype(np.float32) * 0.5
        self.w_r = rng.normal(size=(n_in, 1)).astype(np.float32) * 0.5


def _torch_stub(weights):
    import torch

    class Stub(torch.nn.Module):
        def forward(self, x, hidden=None):
            f = x.flatten(1)
            return {
                "policy": f @ torch.from_numpy(weights.w_p),
                "value": torch.tanh(f @ torch.from_numpy(weights.w_v)),
                "return": torch.tanh(f @ torch.from_numpy(weights.w_r)),
            }

    return Stub()


def _jax_apply(weights):
    def apply_fn(params, obs, hidden):
        f = obs.reshape(obs.shape[0], -1)
        return {
            "policy": f @ jnp.asarray(weights.w_p),
            "value": jnp.tanh(f @ jnp.asarray(weights.w_v)),
            "return": jnp.tanh(f @ jnp.asarray(weights.w_r)),
        }

    return apply_fn


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_compute_loss_parity(name):
    from handyrl_tpu.ops.losses import LossConfig, compute_loss

    cfg, turn_based, P = CONFIGS[name]
    ours, theirs = both_batches(cfg, turn_based, P)
    weights = _StubWeights()

    ref_train = _reference_train()
    ref_losses, ref_dcnt = ref_train.compute_loss(
        theirs, _torch_stub(weights), None, cfg)

    batch = {k: jnp.asarray(v) for k, v in ours.items()}
    our_losses, our_dcnt = compute_loss(
        _jax_apply(weights), {}, batch, None, LossConfig.from_config(cfg))

    assert float(our_dcnt) == pytest.approx(float(ref_dcnt))
    for key in ("p", "v", "r", "ent", "total"):
        assert key in ref_losses, f"reference missing {key}"
        ref_val = float(ref_losses[key].detach())
        our_val = float(our_losses[key])
        assert our_val == pytest.approx(ref_val, rel=5e-4, abs=5e-4), (
            f"loss[{key}]: ours {our_val} vs reference {ref_val}")
