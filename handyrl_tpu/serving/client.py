"""Client SDK for the network serving frontend.

``ServeClient`` speaks the framed serving protocol (docs/serving.md)
over one TCP connection: one blocking ``infer`` round trip at a time —
throughput comes from the SERVER batching across many connections
(open several clients to pipeline), not from per-connection
multiplexing, which keeps the protocol trivially debuggable and the
failure model per-request.

Typed outcomes: a shed request raises :class:`ShedError` (admission
control spoke — back off or retry elsewhere), a serving failure raises
:class:`ServeError` (bad request, unroutable snapshot pin, reply
timeout); both carry the frontend's reason payload.  Transport-level
failures raise the usual ``ConnectionError``/``socket.timeout``.

No module-level jax import: a serving client is a plain consumer
process (``infer`` lazily uses ``jax.tree`` only to add the row dim
to structured observations).
"""

import numpy as np

from ..connection import DEFAULT_MAX_FRAME_BYTES, open_socket_connection


class ShedError(RuntimeError):
    """The frontend shed this request (typed admission reply)."""

    def __init__(self, info):
        super().__init__(f"request shed: {info.get('reason')}")
        self.info = info
        self.reason = info.get("reason")


class ServeError(RuntimeError):
    """The frontend answered a typed error for this request."""

    def __init__(self, info):
        super().__init__(f"serving error: {info.get('reason')}")
        self.info = info
        self.reason = info.get("reason")


class ServeClient:
    """One framed connection to a serving frontend."""

    def __init__(self, address, port, timeout=10.0,
                 max_frame_bytes=DEFAULT_MAX_FRAME_BYTES):
        self.timeout = float(timeout)
        self.conn = open_socket_connection(
            address, int(port), max_frame_bytes=max_frame_bytes)

    def _call(self, verb, payload):
        # per-request deadline: a dead/wedged server raises
        # socket.timeout out of the recv instead of parking this
        # client forever (the settimeout is what bounds the recv)
        self.conn.sock.settimeout(self.timeout)
        self.conn.send((verb, payload))
        reply = self.conn.recv()
        status = reply.get("status") if isinstance(reply, dict) else None
        if status == "ok":
            return reply
        if status == "shed":
            raise ShedError(reply)
        if status == "error":
            raise ServeError(reply)
        raise ServeError({"reason": f"malformed reply {reply!r}"})

    def infer_batch(self, obs_batch, epoch=None, seat=None):
        """Row-batched forward: ``obs_batch`` is an observation tree
        with a leading row dimension on every leaf.  Returns
        ``{"epoch": served_epoch, "outputs": {...row-batched...}}``
        (the reply's payload fields, status stripped).
        ``epoch`` pins the request to that exact snapshot (multi-model
        routing); None serves the live model.  ``seat`` is an opaque
        affinity key: a pool router with ``router.policy: hash`` sends
        every request carrying the same seat to the same replica (a
        single frontend ignores it)."""
        payload = {"obs": obs_batch, "epoch": epoch}
        if seat is not None:
            payload["seat"] = seat
        reply = self._call("infer", payload)
        return {"epoch": reply["epoch"], "outputs": reply["outputs"]}

    def infer(self, obs, epoch=None):
        """Single-observation forward (row dim added/stripped here)."""
        import jax

        batched = jax.tree.map(lambda a: np.asarray(a)[None], obs)
        reply = self.infer_batch(batched, epoch=epoch)
        return {
            "epoch": reply["epoch"],
            "outputs": {k: np.asarray(v)[0]
                        for k, v in reply["outputs"].items()},
        }

    def stats(self):
        """The frontend's cumulative counters (reconciliation,
        latency summary, shed reasons)."""
        return self._call("stats", None)

    def close(self):
        try:
            self.conn.close()
        except OSError:
            pass


__all__ = ["ServeClient", "ShedError", "ServeError"]
