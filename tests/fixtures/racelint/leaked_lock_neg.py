"""Negative: the release lives in a finally block, so any exception
still releases."""

import threading

GATE = threading.Lock()


def grab(work):
    GATE.acquire()
    try:
        return work()
    finally:
        GATE.release()
