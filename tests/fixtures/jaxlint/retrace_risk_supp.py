"""Fixture: suppressed inline jit (one-off cold path)."""

import jax


def relayout_once(buffers, sharding):
    # jaxlint: disable=retrace-risk -- runs once per ring growth; shapes differ each time anyway
    return jax.jit(lambda t: t, out_shardings=sharding)(buffers)
