"""Span-based tracing, trace-context propagation, and the flight recorder.

The guard counters (retrace_count / resharding_copies / stall_events /
fleet_*) say THAT a pathology happened; this module says WHERE THE TIME
WENT and WHAT HAPPENED JUST BEFORE — the two questions an IMPALA-style
learner's operator actually asks (Podracer, arXiv:2104.06272, treats
exactly this pipeline-bubble accounting as a first-class design input).
Three mechanisms, all cheap enough to stay armed in production:

  * **Spans** — ``with trace_span("batch.make"):`` records one
    ``{name, ts, dur, pid, tid, trace, span, parent}`` dict against an
    injectable monotonic clock.  Completed spans land in a per-thread
    buffer (no lock on the hot path; the flush takes one) and stream to
    a per-process ``spans-<pid>.jsonl`` in the run directory, which
    ``scripts/export_trace.py`` renders into a Chrome/Perfetto
    ``trace.json``.  When telemetry is off every entry point is a
    constant-time no-op.

  * **Trace context** — a compact ``(trace_id, span_id)`` pair rides the
    framed ``(verb, payload)`` control plane inside a backward-
    compatible envelope (:func:`wrap_trace` / :func:`unwrap_trace`, used
    by ``connection.TracedConnection`` and the ``QueueCommunicator``):
    a message from a pre-envelope peer passes through untouched, and an
    enveloped message adopts the sender's context into the receiving
    thread — so one episode can be followed worker -> gather -> learner
    -> batch -> update across processes in a single trace.

  * **Flight recorder** — a bounded ring of the last N spans/events
    that :func:`dump`\\ s to ``flightrec.json`` on stall_event, crash,
    SIGTERM, or chaos kill: the causal timeline of the 30 seconds
    before the wedge, where the PR 4 watchdog could only dump a stack.

Nothing here imports jax; worker/gather/batcher child processes
configure from the same args dict the learner ships them.
"""

import atexit
import json
import os
import signal
import sys
import threading
import time
from collections import deque

# the trace-context envelope head.  NOT a protocol verb: commlint is
# taught that wrap_trace/unwrap_trace are transparent codecs, and a
# receiver that predates the envelope still interoperates because
# senders only wrap when a context is actually set.
TRACE_HEAD = "!tr"

_SPAN_FLUSH_EVERY = 16      # spans buffered per thread before a file write
_DEFAULT_RING = 2048        # flight-recorder capacity (flightrec_spans)


class _State:
    """Process-wide telemetry state (one per process, configured from
    the args dict every child already receives)."""

    def __init__(self):
        self.enabled = False
        self.sample_rate = 1.0
        self.clock = time.monotonic
        self.role = ""
        self.primary = True
        self.log_dir = None          # None = no span log file
        self.ring = deque(maxlen=_DEFAULT_RING)
        self.dump_count = 0
        self.dump_path = None
        # REENTRANT on purpose: the SIGTERM dump handler runs on
        # whatever thread holds the interpreter, which may be mid-flush
        # inside this very lock — a plain Lock would deadlock the
        # dying process instead of letting it write its flight record
        self.lock = threading.RLock()
        self.buffers = []             # every thread's span buffer
        self.span_file = None
        self.rng = None               # lazy; seeded per process
        # name -> zero-arg callable whose result rides every flight-
        # recorder dump (the attribution snapshot hooks in here);
        # reset by configure() like the rest of the state
        self.dump_extras = {}


_state = _State()
_tls = threading.local()


# -- configuration ------------------------------------------------------

def configure(enabled=True, sample_rate=1.0, ring=_DEFAULT_RING,
              log_dir=None, role="", primary=True, clock=None):
    """(Re)arm this process's telemetry.  Resets the ring and buffers —
    call once at process start (learner init, child entry points)."""
    global _state
    state = _State()
    state.enabled = bool(enabled)
    state.sample_rate = float(sample_rate)
    state.clock = clock if clock is not None else time.monotonic
    state.role = role or f"pid-{os.getpid()}"
    state.primary = bool(primary)
    state.ring = deque(maxlen=max(1, int(ring or _DEFAULT_RING)))
    if enabled and log_dir is not None:
        state.log_dir = log_dir
        state.dump_path = os.path.join(
            log_dir,
            "flightrec.json" if primary
            else f"flightrec-{os.getpid()}.json")
    _state = state
    _tls.__dict__.clear()
    return state


def configure_from_args(args, role="", primary=True):
    """Configure from a train-args mapping (the dict the learner ships
    to every worker/gather/batcher child).  The span log lives next to
    ``metrics_path``; with no metrics sink configured, spans stay in
    the in-memory ring only (the flight recorder still works via an
    explicit dump path-less ring; dumps are skipped)."""
    metrics = str(args.get("metrics_path") or "")
    log_dir = os.path.dirname(metrics) or "." if metrics else None
    return configure(
        enabled=bool(args.get("telemetry", True)),
        sample_rate=float(args.get("trace_sample_rate", 1.0) or 0.0),
        ring=int(args.get("flightrec_spans", _DEFAULT_RING)
                 or _DEFAULT_RING),
        log_dir=log_dir, role=role, primary=primary)


def enabled():
    return _state.enabled


def now():
    """The telemetry clock's current stamp (injectable — tests drive
    it; production is CLOCK_MONOTONIC, shared across processes)."""
    return _state.clock()


def ring_snapshot():
    """A defensive copy of the flight-recorder ring (oldest first) —
    the attribution fold's input.  Hot-path appends don't take the
    lock, so retry a torn copy instead of crashing the reader."""
    for _ in range(4):
        try:
            return list(_state.ring.copy())
        except RuntimeError:  # deque mutated during iteration
            continue
    return []


def register_dump_extra(name, fn):
    """Attach ``fn()``'s result under ``doc[name]`` in every flight-
    recorder dump (e.g. the last attribution snapshot rides next to
    the span timeline).  A failing extra is skipped, never fatal;
    reserved doc fields cannot be shadowed."""
    if name in ("reason", "role", "pid", "dumped_at", "spans"):
        raise ValueError(f"dump extra name {name!r} is reserved")
    _state.dump_extras[name] = fn


def stats():
    """Counters for the status endpoint / tests."""
    return {
        "enabled": _state.enabled,
        "role": _state.role,
        "ring_spans": len(_state.ring),
        "dumps": _state.dump_count,
    }


# -- trace context ------------------------------------------------------

def _ids():
    state = _state
    if state.rng is None:
        import random

        # per-process seed: ids must differ across the spawned fleet
        state.rng = random.Random(
            (os.getpid() << 20) ^ int(time.time() * 1e3) & 0xFFFFFFFF)
    return state.rng.getrandbits(64)


def new_trace():
    """Fresh (trace_id, span_id) context pair."""
    return (_ids(), _ids())


def maybe_trace():
    """A fresh context with probability ``trace_sample_rate`` (the
    per-episode sampling decision), else None."""
    state = _state
    if not state.enabled or state.sample_rate <= 0.0:
        return None
    if state.sample_rate < 1.0:
        if state.rng is None:
            _ids()  # seed the rng
        if state.rng.random() >= state.sample_rate:
            return None
    return new_trace()


def current_trace():
    return getattr(_tls, "ctx", None)


def set_trace(ctx):
    _tls.ctx = tuple(ctx) if ctx is not None else None


def clear_trace():
    _tls.ctx = None


def wrap_trace(msg):
    """Envelope ``msg`` with the calling thread's trace context, or
    return it untouched when no context is set — the wire format stays
    byte-identical for untraced traffic, which is what makes the
    envelope backward compatible by construction."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return msg
    return (TRACE_HEAD, ctx, msg)


def unwrap_trace(msg):
    """Strip the envelope, adopting the sender's context into this
    thread; a raw pre-envelope message clears the context instead (a
    stale adopted context must not bleed into unrelated spans)."""
    if isinstance(msg, tuple) and len(msg) == 3 \
            and msg[0] == TRACE_HEAD:
        set_trace(msg[1])
        return msg[2]
    clear_trace()
    return msg


# -- span recording -----------------------------------------------------

def _buffer():
    buf = getattr(_tls, "buf", None)
    if buf is None:
        buf = _tls.buf = []
        with _state.lock:
            _state.buffers.append(buf)
    return buf


def record_span(name, t0, dur, **attrs):
    """Record one completed span with explicit times (the context
    manager and SectionTimers both funnel here).  Cheap: two dict
    builds, one ring append, one buffer append."""
    state = _state
    if not state.enabled:
        return
    ctx = getattr(_tls, "ctx", None)
    rec = {
        "name": name,
        "ts": round(t0, 6),
        "dur": round(dur, 6),
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0xFFFFFF,
        "role": state.role,
    }
    if ctx is not None:
        rec["trace"], rec["parent"] = ctx
    if attrs:
        rec["attrs"] = attrs
    state.ring.append(rec)  # deque append: atomic under the GIL
    if state.log_dir is not None:
        buf = _buffer()
        buf.append(rec)
        if len(buf) >= _SPAN_FLUSH_EVERY:
            _flush_buffer(buf)


def add_event(name, **attrs):
    """Zero-duration marker (rendered as an instant event in Perfetto;
    the flight recorder's way of noting 'a stall fired here')."""
    record_span(name, _state.clock(), 0.0, **attrs)


class trace_span:
    """``with trace_span("batch.make"):`` — records one span on exit.
    A plain class, not @contextmanager: when telemetry is off the
    whole enter/exit costs two attribute reads and no generator."""

    __slots__ = ("name", "attrs", "t0")

    def __init__(self, name, **attrs):
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0

    def __enter__(self):
        if _state.enabled:
            self.t0 = _state.clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        if _state.enabled:
            record_span(self.name, self.t0, _state.clock() - self.t0,
                        **self.attrs)
        return False


def span_begin():
    """Explicit-start form for spans that open and close in different
    calls (a rollout-pool slot's episode): returns the start stamp."""
    return _state.clock() if _state.enabled else 0.0


def span_end(name, t0, **attrs):
    if _state.enabled:
        record_span(name, t0, _state.clock() - t0, **attrs)


class payload_trace:
    """Adopt the trace context stamped inside a finished rollout
    payload (``payload["trace"]``) for the duration of its upstream
    send, so the envelope carries the episode's own context rather
    than whatever the worker thread last held."""

    __slots__ = ("ctx",)

    def __init__(self, payload):
        self.ctx = payload.get("trace") \
            if isinstance(payload, dict) else None

    def __enter__(self):
        if self.ctx is not None:
            set_trace(self.ctx)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.ctx is not None:
            clear_trace()
        return False


# -- span log file ------------------------------------------------------

def _flush_buffer(buf):
    state = _state
    if state.log_dir is None or not buf:
        del buf[:]
        return
    with state.lock:
        # copy then delete ONLY the drained prefix: record_span appends
        # from other threads without the lock, and an append landing
        # between these two statements must survive for the next flush
        drained = buf[:]
        del buf[:len(drained)]
        try:
            if state.span_file is None:
                os.makedirs(state.log_dir, exist_ok=True)
                path = os.path.join(state.log_dir,
                                    f"spans-{os.getpid()}.jsonl")
                state.span_file = open(path, "a")
                state.span_file.write(json.dumps(
                    {"meta": {"pid": os.getpid(),
                              "role": state.role}}) + "\n")
            for rec in drained:
                state.span_file.write(json.dumps(rec) + "\n")
            state.span_file.flush()
        except OSError:
            state.log_dir = None  # disk gone: stop trying, keep the ring


def flush():
    """Drain every thread's buffer to the span log (epoch boundaries,
    process exit)."""
    with _state.lock:
        buffers = list(_state.buffers)
    for buf in buffers:
        _flush_buffer(buf)


@atexit.register
def _flush_at_exit():  # pragma: no cover - interpreter teardown
    try:
        flush()
    except Exception:
        pass


# -- flight recorder ----------------------------------------------------

def dump(reason, path=None):
    """Write the ring's contents (oldest first) as ``flightrec.json``.
    Returns the path written, or None when there is nowhere to write
    (no run directory configured).  Each call overwrites: the LAST
    dump before death is the one the operator wants."""
    state = _state
    path = path or state.dump_path
    if not state.enabled or path is None:
        return None
    with state.lock:
        # hot-path appends don't take the lock, so snapshot the ring
        # defensively: a concurrent append mid-copy must not crash the
        # very dump that exists to capture the wedge
        for _ in range(4):
            try:
                spans = list(state.ring.copy())
                break
            except RuntimeError:  # deque mutated during iteration
                continue
        else:
            spans = []
        state.dump_count += 1
        doc = {
            "reason": reason,
            "role": state.role,
            "pid": os.getpid(),
            "dumped_at": round(state.clock(), 6),
            "spans": spans,
        }
        for name, fn in list(state.dump_extras.items()):
            try:
                doc[name] = fn()
            except Exception:
                pass  # a dead extra must not block the post-mortem
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            return None
    print(f"flight recorder: dumped {len(spans)} spans to {path} "
          f"({reason})")
    return path


def dump_count():
    return _state.dump_count


def stall_hook(loop, silent):
    """StallWatchdog ``on_stall`` callback: note the event in the ring,
    then dump — the wedge's causal timeline, not just its stack."""
    add_event("stall", loop=loop, silent_sec=round(silent, 3))
    flush()
    dump("stall_event")


def crash_dump(where, exc):
    """Crash-path dump (the trainer thread's except block)."""
    add_event("crash", where=where, error=repr(exc))
    flush()
    dump("crash")


def install_signal_dump(pre_dump=None):
    """Dump on SIGTERM — a preemption or chaos kill leaves its flight
    record behind.  Main-thread only (signal module restriction); the
    handler re-raises SystemExit so supervised children still exit
    nonzero and ride the normal failure -> respawn path.

    ``pre_dump`` runs FIRST, inside the grace window and regardless of
    whether telemetry is enabled: the learner hooks its emergency
    checkpoint + WAL seal here (durable state outranks the post-mortem
    record).  Exceptions from it are printed and swallowed — a failing
    emergency save must not block the dump or the exit."""
    if not _state.enabled and pre_dump is None:
        return False

    def _on_term(signum, frame):  # pragma: no cover - exercised live
        if pre_dump is not None:
            try:
                pre_dump()
            except Exception:
                import traceback

                traceback.print_exc()
        if _state.enabled:
            add_event("sigterm")
            flush()
            dump("sigterm")
        sys.exit(1)

    try:
        signal.signal(signal.SIGTERM, _on_term)
        return True
    except ValueError:  # not the main thread
        return False


# -- metrics helpers ----------------------------------------------------

def summarize_lags(lags):
    """Per-epoch policy-version-lag reduction: ``{policy_lag_mean,
    policy_lag_p95, policy_lag_max}`` over the episodes consumed this
    epoch (lag = learner epoch at intake - snapshot epoch that
    generated the episode — the central off-policy health signal of an
    IMPALA-style learner)."""
    if not lags:
        return {"policy_lag_mean": 0.0, "policy_lag_p95": 0.0,
                "policy_lag_max": 0.0}
    ordered = sorted(lags)
    p95 = ordered[min(len(ordered) - 1,
                      int(0.95 * (len(ordered) - 1) + 0.5))]
    return {
        "policy_lag_mean": round(sum(ordered) / len(ordered), 4),
        "policy_lag_p95": float(p95),
        "policy_lag_max": float(ordered[-1]),
    }
