"""Fixture: suppressed donated-reuse (metadata-only access)."""

import jax


def make_step():
    return jax.jit(lambda p, o, b: (p, o), donate_argnums=(0, 1))


def shape_after_donate(params, opt_state, batch):
    step = make_step()
    new_params, new_opt = step(params, opt_state, batch)
    # jaxlint: disable=donated-reuse -- debug logging of a dead buffer's repr only
    print(repr(params))
    return new_params, new_opt
