"""AST machinery behind jaxlint: package model + taint analyses.

jaxlint's rules need to answer three questions that plain per-line
pattern matching cannot:

  * which functions execute *inside* a jit trace?  (``jax.jit`` /
    ``shard_map`` entry points, plus everything reachable from them
    through direct calls, ``jax.tree.map``-style higher-order calls,
    ``lax.scan`` bodies and ``jax.grad`` closures);
  * which values are *tracers* there?  (entry parameters minus
    ``static_argnums``, propagated through assignments — but NOT
    through ``.shape`` / ``.dtype`` / ``len()`` / ``is None``, which
    are static at trace time and therefore safe to branch on);
  * which host-side values are *device arrays*?  (results of ``jnp.*``
    producers and of calling jit-compiled callables, propagated
    through containers, attributes and function-return summaries — so
    ``float(metrics["loss"])`` in an epoch loop is recognized as a
    device->host sync even though ``metrics`` crossed two functions).

Everything here is stdlib ``ast`` only — the linter never imports jax,
so it runs in CI and pre-commit in a few seconds for the whole
package, with no backend initialization.

The analyses are deliberately *monotone and approximate*: taint only
ever grows, locals are flow-insensitive within a function, and
unresolvable calls default to "tainted if any argument is tainted".
That bias keeps the engine small and the false-negative rate low; the
per-rule suppression syntax (see :mod:`.jaxlint`) is the escape hatch
for the rare intentional violation.
"""

import ast
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

# Attribute reads that yield static (trace-time) metadata, never a
# tracer/device value: branching on these is always safe.
SAFE_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "aval", "weak_type", "sharding",
    "itemsize", "nbytes", "is_fully_replicated", "is_deleted",
})

# Transform wrappers whose first argument becomes a jit entry point.
JIT_WRAPPERS = frozenset({
    "jax.jit", "jax.pmap", "pjit", "jax.experimental.pjit.pjit",
    "shard_map", "jax.experimental.shard_map.shard_map",
})

# Calls whose result is definitely host data (break device taint).
HOST_RESULT_FNS = frozenset({
    "jax.device_get", "numpy.asarray", "numpy.array", "numpy.shape",
    "float", "int", "bool", "len", "isinstance", "type", "str", "repr",
    "hasattr", "callable",
})

# Call-name prefixes whose results live on device even with host args.
DEVICE_PRODUCER_PREFIXES = (
    "jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.", "jax.scipy.",
    "jax.image.", "jax.ops.",
)
DEVICE_PRODUCER_FNS = frozenset({
    "jax.device_put", "jax.make_array_from_callback",
    "jax.make_array_from_process_local_data",
    "jax.make_array_from_single_device_arrays",
})

# Spelling normalization applied after import-alias expansion.
_CANON = {
    "jax.tree_util.tree_map": "jax.tree.map",
    "jax.tree_map": "jax.tree.map",
    "jax.tree_util.tree_leaves": "jax.tree.leaves",
}


def dotted_parts(node) -> Optional[List[str]]:
    """``a.b.c`` attribute chain -> ``["a", "b", "c"]`` (None if the
    chain bottoms out in anything but a plain Name)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    parts.reverse()
    return parts


@dataclass
class JitMeta:
    """Trace-relevant options of one ``jax.jit`` (or equivalent) call."""

    donate: Tuple[int, ...] = ()
    static_nums: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    constant_opts: bool = True  # False: options were not literals


def _const_ints(node) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)):
                return None
            vals.append(el.value)
        return tuple(vals)
    return None


def _const_strs(node) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            vals.append(el.value)
        return tuple(vals)
    return None


def jit_meta_from_call(call: ast.Call) -> JitMeta:
    """Parse donate/static options off a ``jax.jit(...)`` call node."""
    meta = JitMeta()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            vals = _const_ints(kw.value)
            if vals is None:
                meta.constant_opts = False
            else:
                meta.donate = vals
        elif kw.arg == "static_argnums":
            vals = _const_ints(kw.value)
            if vals is None:
                meta.constant_opts = False
            else:
                meta.static_nums = vals
        elif kw.arg == "static_argnames":
            vals = _const_strs(kw.value)
            if vals is None:
                meta.constant_opts = False
            else:
                meta.static_names = vals
    return meta


class FunctionInfo:
    """One function/method/lambda in the scanned package."""

    def __init__(self, qname, node, module, parent, cls_name):
        self.qname = qname
        self.node = node
        self.module = module
        self.parent = parent          # enclosing FunctionInfo or None
        self.cls_name = cls_name      # enclosing class name or None
        args = node.args
        self.pos_params = [a.arg for a in args.posonlyargs + args.args]
        self.all_params = list(self.pos_params)
        if args.vararg:
            self.all_params.append(args.vararg.arg)
        self.all_params += [a.arg for a in args.kwonlyargs]
        if args.kwarg:
            self.all_params.append(args.kwarg.arg)
        self.local_defs: Dict[str, "FunctionInfo"] = {}

        # tracer-taint state (grown by the interprocedural worklist)
        self.jit_reachable = False
        self.tainted_params: Set[str] = set()
        self.tracer_locals: Set[str] = set()

        # device-taint state (grown by the package fixpoint)
        self.device_params: Set[str] = set()
        self.device_locals: Set[str] = set()
        self.returns_device = False
        self.returns_jit: Optional[JitMeta] = None
        self.jit_locals: Dict[str, JitMeta] = {}

    @property
    def callable_params(self) -> List[str]:
        """Positional params as seen by callers (``self``/``cls``
        dropped for methods)."""
        if self.cls_name and self.pos_params[:1] in (["self"], ["cls"]):
            return self.pos_params[1:]
        return self.pos_params

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<fn {self.qname}>"


class ModuleInfo:
    """Parsed module + symbol tables."""

    def __init__(self, name: str, path: str, source: str):
        self.name = name
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.aliases: Dict[str, str] = {}         # name -> external dotted
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod, sym)
        self.functions: List[FunctionInfo] = []
        self.by_node: Dict[ast.AST, FunctionInfo] = {}
        self.toplevel: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, Dict[str, FunctionInfo]] = {}
        # per-class attribute facts discovered by the device fixpoint
        self.class_jit_attrs: Dict[str, Dict[str, JitMeta]] = {}
        self.class_device_attrs: Dict[str, Set[str]] = {}
        _Collector(self).visit(self.tree)


class _Collector(ast.NodeVisitor):
    """Builds the function/import tables of one module."""

    def __init__(self, module: ModuleInfo):
        self.m = module
        self.fn_stack: List[FunctionInfo] = []
        self.cls_stack: List[str] = []

    # -- imports -----------------------------------------------------
    def visit_Import(self, node):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            target = alias.name if alias.asname else alias.name.split(".")[0]
            self.m.aliases[name] = target

    def visit_ImportFrom(self, node):
        if node.level > 0:
            base = self.m.name.split(".")
            base = base[: len(base) - node.level]
            target_mod = ".".join(base + ([node.module] if node.module
                                          else []))
        else:
            target_mod = node.module or ""
        for alias in node.names:
            name = alias.asname or alias.name
            self.m.from_imports[name] = (target_mod, alias.name)

    # -- scopes ------------------------------------------------------
    def visit_ClassDef(self, node):
        self.cls_stack.append(node.name)
        self.m.classes.setdefault(node.name, {})
        self.generic_visit(node)
        self.cls_stack.pop()

    def _enter_function(self, node, name):
        parent = self.fn_stack[-1] if self.fn_stack else None
        cls = self.cls_stack[-1] if self.cls_stack else None
        scope = ".".join(
            ([cls] if cls else [])
            + [f.qname.rsplit(":", 1)[1] for f in self.fn_stack[-1:]]
        )
        qname = f"{self.m.name}:{scope + '.' if scope else ''}{name}"
        info = FunctionInfo(qname, node, self.m, parent, cls)
        self.m.functions.append(info)
        self.m.by_node[node] = info
        if parent is not None:
            parent.local_defs[name] = info
        elif cls is not None:
            self.m.classes[cls][name] = info
        else:
            self.m.toplevel[name] = info
        self.fn_stack.append(info)
        self.generic_visit(node)
        self.fn_stack.pop()

    def visit_FunctionDef(self, node):
        self._enter_function(node, node.name)

    def visit_AsyncFunctionDef(self, node):
        self._enter_function(node, node.name)

    def visit_Lambda(self, node):
        self._enter_function(node, f"<lambda:{node.lineno}>")


class Package:
    """All scanned modules + cross-module resolution."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = {m.name: m for m in modules}

    # -- name resolution --------------------------------------------
    def lookup(self, module_name: str, symbol: str):
        mod = self.modules.get(module_name)
        if mod is None:
            return None
        fn = mod.toplevel.get(symbol)
        if fn is not None:
            return fn
        # chase one re-export hop (``from .x import y`` in __init__)
        imp = mod.from_imports.get(symbol)
        if imp is not None:
            target, orig = imp
            target_mod = self.modules.get(target)
            if target_mod is not None:
                return target_mod.toplevel.get(orig)
        return None

    def resolve_name(self, module: ModuleInfo, scope: Optional[FunctionInfo],
                     name: str):
        """A bare name -> ("fn", FunctionInfo) | ("ext", dotted) | None."""
        fn_scope = scope
        while fn_scope is not None:
            if name in fn_scope.local_defs:
                return ("fn", fn_scope.local_defs[name])
            fn_scope = fn_scope.parent
        if name in module.toplevel:
            return ("fn", module.toplevel[name])
        if name in module.from_imports:
            target_mod, orig = module.from_imports[name]
            fn = self.lookup(target_mod, orig)
            if fn is not None:
                return ("fn", fn)
            return ("ext", f"{target_mod}.{orig}" if target_mod else orig)
        if name in module.aliases:
            return ("ext", module.aliases[name])
        return ("ext", name)  # builtins / globals: keep the raw name

    def full_name(self, module: ModuleInfo, scope, node) -> Optional[str]:
        """Dotted call-target name with import aliases expanded, e.g.
        ``jnp.where`` -> ``jax.numpy.where``.  None for computed
        targets (``f()()``, subscripts)."""
        parts = dotted_parts(node)
        if parts is None:
            return None
        head, rest = parts[0], parts[1:]
        resolved = self.resolve_name(module, scope, head)
        if resolved is not None and resolved[0] == "ext":
            head = resolved[1]
        name = ".".join([head] + rest)
        return _CANON.get(name, name)

    def resolve_callee(self, module: ModuleInfo, scope, func):
        """Call target -> ("fn", FunctionInfo) | ("ext", dotted) | None.

        Handles local defs (through enclosing scopes), module-level
        defs, package-relative imports (``from .ops.update import
        make_update_step``), module aliases (``from .parallel import
        multihost as mh`` -> ``mh.sync_epoch_code``), and
        ``self.method`` within a class.
        """
        if isinstance(func, ast.Name):
            return self.resolve_name(module, scope, func.id)
        parts = dotted_parts(func)
        if parts is None:
            return None
        if parts[0] == "self" and len(parts) == 2:
            cls = _enclosing_class(scope)
            if cls is not None:
                method = module.classes.get(cls, {}).get(parts[1])
                if method is not None:
                    return ("fn", method)
            return ("ext", f"self.{parts[1]}")
        # module alias: ``from .parallel import multihost as mh``
        if len(parts) == 2 and parts[0] in module.from_imports:
            target_mod, orig = module.from_imports[parts[0]]
            sub = f"{target_mod}.{orig}" if target_mod else orig
            fn = self.lookup(sub, parts[1])
            if fn is not None:
                return ("fn", fn)
        name = self.full_name(module, scope, func)
        return ("ext", name) if name else None

    def all_functions(self):
        for mod in self.modules.values():
            for fn in mod.functions:
                yield fn


def _enclosing_class(scope: Optional[FunctionInfo]) -> Optional[str]:
    while scope is not None:
        if scope.cls_name is not None:
            return scope.cls_name
        scope = scope.parent
    return None


def is_host_converter(pkg: "Package", module: ModuleInfo, scope,
                      fn_expr) -> bool:
    """Is this function VALUE a host converter?  ``jax.tree.map``
    applied over such a function returns host data even when its tree
    argument is on device — the ``tree.map(np.asarray, out)`` idiom
    every actor-facing boundary uses.  Shared by the device-taint
    lattice and commlint's payload scan so "what launders" has one
    definition."""
    if isinstance(fn_expr, ast.Lambda):
        body = fn_expr.body
        # unwrap trailing indexing: lambda a: np.asarray(a)[0]
        while isinstance(body, ast.Subscript):
            body = body.value
        if isinstance(body, ast.Call):
            inner = pkg.full_name(module, scope, body.func)
            return inner in HOST_RESULT_FNS \
                or (inner or "").startswith("numpy.")
        return False
    name = pkg.full_name(module, scope, fn_expr)
    return name in HOST_RESULT_FNS or (name or "").startswith("numpy.")


def launders_to_host(pkg: "Package", module: ModuleInfo, scope,
                     call: ast.Call) -> bool:
    """Does this CALL return host data regardless of its arguments'
    device placement?  True for the host-result builtins/numpy and for
    ``jax.tree.map`` over a host converter."""
    name = pkg.full_name(module, scope, call.func)
    if name is None:
        return False
    if name in HOST_RESULT_FNS or name.startswith("numpy."):
        return True
    return (name == "jax.tree.map" and bool(call.args)
            and is_host_converter(pkg, module, scope, call.args[0]))


# ---------------------------------------------------------------------
# taint evaluation
# ---------------------------------------------------------------------

_UNTAINT_CALLS = frozenset({
    "len", "isinstance", "type", "hasattr", "callable", "id", "repr",
    "print", "sorted" , "range", "enumerate", "zip", "min", "max",
})
# NOTE: float/int/bool are *not* here for tracer taint — calling them
# on a tracer is itself a violation (host-sync rule); their result
# taint is moot because the trace already failed.


class _TaintWalk(ast.NodeVisitor):
    """Shared statement walker: monotone name-taint over one function
    body, with the value logic supplied by a subclass.

    Runs the body to a fixpoint (loops make taint flow backward), then
    a final pass that records the facts rules consume (calls made,
    function-valued arguments, return taint).
    """

    MAX_PASSES = 4

    def __init__(self, fn: FunctionInfo, package: Package):
        self.fn = fn
        self.pkg = package
        self.module = fn.module
        self.tainted: Set[str] = set()
        self.calls: List[Tuple] = []      # (resolution, node, arg_taints, kw_taints)
        self.fn_args: List[Tuple] = []    # (FunctionInfo, call node, any_other_arg_tainted)
        self.return_tainted = False
        self.collect = False

    def run(self):
        body = self.fn.node.body
        if isinstance(self.fn.node, ast.Lambda):
            body = [ast.Expr(self.fn.node.body)]
        for _ in range(self.MAX_PASSES):
            before = set(self.tainted)
            for stmt in body:
                self.handle_stmt(stmt)
            if self.tainted == before:
                break
        self.collect = True
        for stmt in body:
            self.handle_stmt(stmt)
        return self

    # -- statements --------------------------------------------------
    def handle_stmt(self, stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs analyze as their own functions
        if isinstance(stmt, ast.Assign):
            t = self.taint(stmt.value)
            for tgt in stmt.targets:
                self.assign(tgt, stmt.value, t)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self.assign(stmt.target, stmt.value, self.taint(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            t = self.taint(stmt.value) or self.taint(stmt.target)
            self.assign(stmt.target, stmt.value, t)
        elif isinstance(stmt, ast.For):
            self.assign_iteration(stmt.target, stmt.iter)
            for s in stmt.body + stmt.orelse:
                self.handle_stmt(s)
        elif isinstance(stmt, ast.While):
            self.taint(stmt.test)
            for s in stmt.body + stmt.orelse:
                self.handle_stmt(s)
        elif isinstance(stmt, ast.If):
            self.taint(stmt.test)
            for s in stmt.body + stmt.orelse:
                self.handle_stmt(s)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                t = self.taint(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, item.context_expr, t)
            for s in stmt.body:
                self.handle_stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in (stmt.body + stmt.orelse + stmt.finalbody
                      + [h for hand in stmt.handlers for h in hand.body]):
                self.handle_stmt(s)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None and self.taint(stmt.value):
                self.return_tainted = True
            if stmt.value is not None:
                self.handle_return(stmt.value)
        elif isinstance(stmt, ast.Expr):
            self.taint(stmt.value)
            self.handle_expr_stmt(stmt.value)
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.taint(child)
        # Pass/Break/Continue/Import/Global/Delete: nothing to do

    def handle_return(self, value):
        """Hook for subclasses (device mode records jit-value returns)."""

    def handle_expr_stmt(self, value):
        """Hook for subclasses (device mode tracks ``lst.append(x)``)."""

    # -- assignment --------------------------------------------------
    def assign(self, target, value, tainted: bool):
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            self.assign_name(target.id, value, tainted)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, value, tainted)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) \
                    and len(value.elts) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self.assign(t, v, self.taint(v))
            else:
                for t in target.elts:
                    self.assign(t, value, tainted)
        elif isinstance(target, ast.Attribute):
            self.assign_attr(target, value, tainted)
        elif isinstance(target, ast.Subscript):
            # writing a tainted value into a container taints it
            if tainted and isinstance(target.value, ast.Name):
                self.tainted.add(target.value.id)

    def assign_name(self, name, value, tainted):
        """Hook for subclasses (device mode tracks jit-value names)."""

    def assign_attr(self, target, value, tainted):
        """Hook for subclasses (device mode tracks ``self.x`` facts)."""

    def assign_iteration(self, target, iter_expr):
        """``for target in iter_expr`` — dict ``.items()`` keys stay
        untainted (they are static strings in practice)."""
        t = self.taint(iter_expr)
        if (t and isinstance(iter_expr, ast.Call)
                and isinstance(iter_expr.func, ast.Attribute)
                and iter_expr.func.attr == "items"
                and isinstance(target, (ast.Tuple, ast.List))
                and len(target.elts) == 2):
            self.assign(target.elts[0], iter_expr, False)
            self.assign(target.elts[1], iter_expr, True)
            return
        if (isinstance(iter_expr, ast.Call)
                and isinstance(iter_expr.func, ast.Attribute)
                and iter_expr.func.attr == "keys"):
            t = False
        self.assign(target, iter_expr, t)

    # -- expressions -------------------------------------------------
    def taint(self, e) -> bool:
        if e is None or isinstance(e, (ast.Constant, ast.JoinedStr)):
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Attribute):
            if e.attr in SAFE_ATTRS:
                self.taint(e.value)
                return False
            return self.attr_taint(e)
        if isinstance(e, ast.Subscript):
            return self.taint(e.value) or self.taint(e.slice)
        if isinstance(e, (ast.BinOp,)):
            left, right = self.taint(e.left), self.taint(e.right)
            return left or right
        if isinstance(e, ast.UnaryOp):
            return self.taint(e.operand)
        if isinstance(e, ast.BoolOp):
            return any([self.taint(v) for v in e.values])
        if isinstance(e, ast.Compare):
            subs = [self.taint(e.left)] + [self.taint(c)
                                           for c in e.comparators]
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False  # ``x is None`` guards are static
            if any(isinstance(op, (ast.In, ast.NotIn)) for op in e.ops):
                return False  # dict/key membership idiom
            return any(subs)
        if isinstance(e, ast.Call):
            return self.call_taint(e)
        if isinstance(e, ast.IfExp):
            self.taint(e.test)
            body, orelse = self.taint(e.body), self.taint(e.orelse)
            return body or orelse
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any([self.taint(el) for el in e.elts])
        if isinstance(e, ast.Dict):
            keys = [self.taint(k) for k in e.keys if k is not None]
            vals = [self.taint(v) for v in e.values]
            return any(keys) or any(vals)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            self._comp_generators(e.generators)
            return self.taint(e.elt)
        if isinstance(e, ast.DictComp):
            self._comp_generators(e.generators)
            k, v = self.taint(e.key), self.taint(e.value)
            return k or v
        if isinstance(e, ast.Starred):
            return self.taint(e.value)
        if isinstance(e, (ast.Await, ast.YieldFrom)):
            return self.taint(e.value)
        if isinstance(e, ast.Yield):
            return self.taint(e.value) if e.value else False
        if isinstance(e, ast.NamedExpr):
            t = self.taint(e.value)
            self.assign(e.target, e.value, t)
            return t
        if isinstance(e, ast.Lambda):
            return False  # a function value, not a data value
        if isinstance(e, ast.Slice):
            for part in (e.lower, e.upper, e.step):
                self.taint(part)
            return False
        if isinstance(e, ast.FormattedValue):
            self.taint(e.value)
            return False
        return False

    def _comp_generators(self, generators):
        for gen in generators:
            self.assign_iteration(gen.target, gen.iter)
            for cond in gen.ifs:
                self.taint(cond)

    def attr_taint(self, e: ast.Attribute) -> bool:
        return self.taint(e.value)

    # -- calls -------------------------------------------------------
    def call_taint(self, call: ast.Call) -> bool:
        arg_taints = [self.taint(a) for a in call.args]
        kw_taints = {kw.arg: self.taint(kw.value) for kw in call.keywords}
        name = self.pkg.full_name(self.module, self.fn, call.func)
        resolution = self.pkg.resolve_callee(self.module, self.fn,
                                             call.func)
        if self.collect:
            self.calls.append((resolution, call, arg_taints, kw_taints))
            any_tainted = any(arg_taints) or any(kw_taints.values())
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                target = self._as_function_value(arg)
                if target is not None:
                    self.fn_args.append((target, call, any_tainted))
        return self.result_taint(name, resolution, call, arg_taints,
                                 kw_taints)

    def _as_function_value(self, expr) -> Optional[FunctionInfo]:
        """An argument that is itself a function (lambda or reference
        to a local/module def) — the higher-order propagation targets."""
        if isinstance(expr, ast.Lambda):
            return self.module.by_node.get(expr)
        if isinstance(expr, (ast.Name, ast.Attribute)):
            res = self.pkg.resolve_callee(self.module, self.fn, expr)
            if res is not None and res[0] == "fn":
                return res[1]
        return None

    def result_taint(self, name, resolution, call, arg_taints, kw_taints):
        raise NotImplementedError


class TracerTaint(_TaintWalk):
    """Taint = "is a tracer inside the jit trace"."""

    def __init__(self, fn, package):
        super().__init__(fn, package)
        self.tainted = set(fn.tainted_params)

    def result_taint(self, name, resolution, call, arg_taints, kw_taints):
        if name is not None:
            if name in _UNTAINT_CALLS:
                return False
            if (name == "getattr" and len(call.args) >= 2
                    and isinstance(call.args[1], ast.Constant)
                    and call.args[1].value in SAFE_ATTRS):
                return False
        func_tainted = (isinstance(call.func, ast.Attribute)
                        and self.taint(call.func.value)
                        and call.func.attr not in SAFE_ATTRS)
        return (any(arg_taints) or any(kw_taints.values())
                or func_tainted)


class DeviceTaint(_TaintWalk):
    """Taint = "is (or contains) a device array" on the host side.

    Runs on every function; cross-function facts (return summaries,
    ``self.X`` attribute facts, higher-order parameter injection) live
    on the FunctionInfo/ModuleInfo objects and are grown by the
    package-level fixpoint in :func:`compute_device_summaries`.
    """

    def __init__(self, fn, package):
        super().__init__(fn, package)
        self.tainted = set(fn.device_params)
        self.jit_names: Dict[str, JitMeta] = dict(fn.jit_locals)
        self.return_jit: Optional[JitMeta] = None

    # -- jit-value tracking ------------------------------------------
    def jit_value(self, e) -> Optional[JitMeta]:
        """Is this expression a jit-compiled callable?  Follows the
        wrapper idiom: any call with a jitted argument yields a jitted
        callable (``guard.wrap(jitted)``, ``functools.partial``)."""
        if isinstance(e, ast.Name):
            return self.jit_names.get(e.id)
        if isinstance(e, ast.Attribute):
            parts = dotted_parts(e)
            cls = _enclosing_class(self.fn)
            if (parts is not None and len(parts) == 2
                    and parts[0] == "self" and cls is not None):
                return self.module.class_jit_attrs.get(cls, {}).get(
                    parts[1])
            return None
        if isinstance(e, ast.Call):
            name = self.pkg.full_name(self.module, self.fn, e.func)
            if name in JIT_WRAPPERS:
                return jit_meta_from_call(e)
            res = self.pkg.resolve_callee(self.module, self.fn, e.func)
            if res is not None and res[0] == "fn" \
                    and res[1].returns_jit is not None:
                return res[1].returns_jit
            for arg in list(e.args) + [kw.value for kw in e.keywords]:
                meta = self.jit_value(arg)
                if meta is not None:
                    return meta
        return None

    def assign_name(self, name, value, tainted):
        # strong update: rebinding a name to a host value clears its
        # device taint, so the ``metrics = jax.device_get(metrics)``
        # laundering idiom works.  (Tracer taint stays monotone — a
        # tracer cannot be un-traced.)
        if not tainted:
            self.tainted.discard(name)
        meta = self.jit_value(value)
        if meta is not None:
            self.jit_names[name] = meta

    def assign_attr(self, target, value, tainted):
        parts = dotted_parts(target)
        cls = _enclosing_class(self.fn)
        if parts is None or len(parts) != 2 or parts[0] != "self" \
                or cls is None:
            return
        meta = self.jit_value(value)
        if meta is not None:
            self.module.class_jit_attrs.setdefault(cls, {})[parts[1]] = meta
        if tainted:
            self.module.class_device_attrs.setdefault(cls, set()).add(
                parts[1])

    def attr_taint(self, e: ast.Attribute) -> bool:
        parts = dotted_parts(e)
        cls = _enclosing_class(self.fn)
        if (parts is not None and len(parts) == 2 and parts[0] == "self"
                and cls is not None
                and parts[1] in self.module.class_device_attrs.get(
                    cls, ())):
            return True
        return super().attr_taint(e)

    def handle_return(self, value):
        meta = self.jit_value(value)
        if meta is not None:
            self.return_jit = meta

    def handle_expr_stmt(self, value):
        # ``lst.append(device_value)`` taints the container
        if (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr in ("append", "extend", "insert",
                                        "add", "appendleft")
                and isinstance(value.func.value, ast.Name)
                and any(self.taint(a) for a in value.args)):
            self.tainted.add(value.func.value.id)

    def result_taint(self, name, resolution, call, arg_taints, kw_taints):
        if name is not None:
            if name in HOST_RESULT_FNS or name.startswith("numpy."):
                return False
            if name == "jax.tree.map" and call.args \
                    and is_host_converter(self.pkg, self.module,
                                          self.fn, call.args[0]):
                return False
            if name in DEVICE_PRODUCER_FNS or name.startswith(
                    DEVICE_PRODUCER_PREFIXES):
                return True
        if self.jit_value(call.func) is not None:
            return True  # calling a jitted callable -> device result
        if resolution is not None and resolution[0] == "fn" \
                and resolution[1].returns_device:
            return True
        func_tainted = (isinstance(call.func, ast.Attribute)
                        and call.func.attr not in SAFE_ATTRS
                        and self.taint(call.func.value))
        return (any(arg_taints) or any(kw_taints.values())
                or func_tainted)


# ---------------------------------------------------------------------
# package-level drivers
# ---------------------------------------------------------------------

def find_jit_entries(package: Package):
    """Yield ``(FunctionInfo, static_param_names)`` for every function
    that is a direct jit/shard_map/pmap entry point (by decorator or by
    being passed to the wrapper), package-wide."""
    for mod in package.modules.values():
        # decorators
        for fn in mod.functions:
            if isinstance(fn.node, ast.Lambda):
                continue
            for dec in fn.node.decorator_list:
                meta = _decorator_jit_meta(package, mod, fn, dec)
                if meta is not None:
                    yield fn, _static_names(fn, meta,
                                            skip_self=False), meta
        # call sites: jax.jit(f, ...)
        for scope, call in _walk_calls(mod):
            name = package.full_name(mod, scope, call.func)
            if name not in JIT_WRAPPERS or not call.args:
                continue
            res = package.resolve_callee(mod, scope, call.args[0])
            if res is None or res[0] != "fn":
                target = call.args[0]
                if isinstance(target, ast.Lambda):
                    fn = mod.by_node.get(target)
                    if fn is not None:
                        meta = jit_meta_from_call(call)
                        yield fn, _static_names(fn, meta,
                                                skip_self=False), meta
                continue
            fn = res[1]
            meta = jit_meta_from_call(call)
            skip_self = (isinstance(call.args[0], ast.Attribute)
                         and dotted_parts(call.args[0]) is not None
                         and dotted_parts(call.args[0])[0] == "self")
            yield fn, _static_names(fn, meta, skip_self=skip_self), meta


def _decorator_jit_meta(package, mod, fn, dec):
    name = package.full_name(mod, fn.parent, dec)
    if name in JIT_WRAPPERS:
        return JitMeta()
    if isinstance(dec, ast.Call):
        dec_name = package.full_name(mod, fn.parent, dec.func)
        if dec_name in JIT_WRAPPERS:
            return jit_meta_from_call(dec)
        if dec_name == "functools.partial" and dec.args:
            inner = package.full_name(mod, fn.parent, dec.args[0])
            if inner in JIT_WRAPPERS:
                return jit_meta_from_call(dec)
    return None


def _static_names(fn: FunctionInfo, meta: JitMeta, skip_self: bool):
    params = fn.pos_params[1:] if (
        skip_self and fn.pos_params[:1] in (["self"], ["cls"])
    ) else fn.pos_params
    static = set(meta.static_names)
    for idx in meta.static_nums:
        if 0 <= idx < len(params):
            static.add(params[idx])
    return static


def _walk_calls(mod: ModuleInfo):
    """Every Call node with its enclosing FunctionInfo (or None)."""
    out = []

    def walk(node, scope):
        for child in ast.iter_child_nodes(node):
            child_scope = mod.by_node.get(child, scope)
            if isinstance(child, ast.Call):
                out.append((scope, child))
            walk(child, child_scope)

    walk(mod.tree, None)
    return out


def compute_tracer_taint(package: Package):
    """Interprocedural worklist: mark jit-reachable functions and the
    tracer taint of their parameters/locals."""
    work = deque()

    def seed(fn, tainted_params):
        new = tainted_params - fn.tainted_params
        if new or not fn.jit_reachable:
            fn.jit_reachable = True
            fn.tainted_params |= tainted_params
            work.append(fn)

    for fn, static, _meta in find_jit_entries(package):
        params = set(fn.all_params) - static - {"self", "cls"}
        seed(fn, params)

    seen_guard = 0
    while work and seen_guard < 10000:
        seen_guard += 1
        fn = work.popleft()
        tt = TracerTaint(fn, package).run()
        fn.tracer_locals = set(tt.tainted)
        for resolution, call, arg_taints, kw_taints in tt.calls:
            if resolution is None or resolution[0] != "fn":
                continue
            callee = resolution[1]
            params = callee.callable_params
            tainted = set()
            for idx, t in enumerate(arg_taints):
                if not t:
                    continue
                if isinstance(call.args[idx], ast.Starred):
                    # a tainted *splat can land anywhere from here on
                    tainted.update(params[idx:])
                elif idx < len(params):
                    tainted.add(params[idx])
            for kw, t in kw_taints.items():
                if t and kw in callee.all_params:
                    tainted.add(kw)
            if tainted:
                seed(callee, tainted)
        for target, _call, _any_tainted in tt.fn_args:
            # a function value passed around inside traced code will be
            # called with tracers (tree.map / scan / grad / cond ...)
            seed(target,
                 set(target.all_params) - {"self", "cls"})


def compute_device_summaries(package: Package, max_passes: int = 6):
    """Package fixpoint for the host-side device-value facts."""
    for _ in range(max_passes):
        changed = False
        for fn in package.all_functions():
            dt = DeviceTaint(fn, package).run()
            if dt.return_tainted and not fn.returns_device:
                fn.returns_device = True
                changed = True
            if dt.return_jit is not None and fn.returns_jit is None:
                fn.returns_jit = dt.return_jit
                changed = True
            if dt.jit_names != fn.jit_locals:
                fn.jit_locals = dict(dt.jit_names)
                changed = True
            if dt.tainted != fn.device_locals:
                fn.device_locals = set(dt.tainted)
                changed = True
            # higher-order injection: lambdas mapped over device trees
            for target, _call, any_tainted in dt.fn_args:
                if any_tainted:
                    params = set(target.all_params) - {"self", "cls"}
                    if params - target.device_params:
                        target.device_params |= params
                        changed = True
        if not changed:
            break
