"""Pipelined rollout dataflow: shm transport + batched inference service.

Three layers, matching the subsystem's own:

  * ring units — wraparound, full-ring backpressure, torn-write
    detection, reader-crash reclaim: the seqlock transport's whole
    failure contract, no processes needed (cursors live in the
    segment, so both endpoints can be mapped in one test process);
  * service units — the wait-or-timeout batching window under an
    INJECTED clock (a scripted sleep delivers the second worker's
    request mid-window), hot-swap, epoch pinning, fallback/respawn;
  * one deterministic tier-1 e2e — a real training run with the
    pipeline on whose inference service is chaos-killed mid-train
    (``chaos.infer_kill_epoch``): training must complete via the
    workers' local fallback plus the learner's supervised respawn.
"""

import json

import numpy as np
import pytest

from handyrl_tpu.pipeline import (
    PipelineClient,
    PipelineConfig,
    ShmBoard,
    ShmRing,
)
from handyrl_tpu.pipeline import shm as shm_mod


# ---------------------------------------------------------------------
# ring units
# ---------------------------------------------------------------------

def test_ring_wraparound_fifo():
    """20 items through 4 slots: FIFO order survives five laps."""
    ring = ShmRing.create(slots=4, slot_bytes=64)
    try:
        for i in range(20):
            assert ring.push(f"item-{i}".encode())
            assert ring.pop() == f"item-{i}".encode()
        assert ring.pop() is None  # drained
    finally:
        ring.close()


def test_ring_full_backpressure_counts():
    """A full ring refuses pushes (never overwrites) and counts the
    refusal in the shm header where the CONSUMER side can read it."""
    ring = ShmRing.create(slots=3, slot_bytes=64)
    try:
        for i in range(3):
            assert ring.push(b"x")
        assert len(ring) == 3
        assert not ring.push(b"overflow")
        assert ring.full_count == 1
        assert ring.pop() == b"x"   # drain one slot...
        assert ring.push(b"y")      # ...and the producer flows again
        assert ring.full_count == 1
    finally:
        ring.close()


def test_ring_oversize_item_refused():
    """An item larger than one slot is refused and counted — the
    producer's cue to spill to the control plane."""
    ring = ShmRing.create(slots=2, slot_bytes=16)
    try:
        assert not ring.push(b"z" * 17)
        assert ring.full_count == 1 and len(ring) == 0
        assert ring.push(b"z" * 16)  # exactly one slot fits
    finally:
        ring.close()


def _tear_slot(ring):
    """Simulate a producer dying mid-write: reserve the slot (odd
    seqlock stamp + head bump — exactly what push() publishes first)
    and never fill it."""
    head = ring._get(shm_mod._HEAD)
    off = ring._slot_off(head)
    shm_mod._Q.pack_into(ring._buf, off, 2 * head + 1)
    ring._set(shm_mod._HEAD, head + 1)


def test_ring_torn_write_detected_and_skipped():
    """A slot whose writer died mid-frame is never consumed as data;
    once the consumer has evidence the writer is gone, skip_torn
    reclaims the ring and later traffic flows."""
    ring = ShmRing.create(slots=4, slot_bytes=64)
    try:
        assert ring.push(b"good-1")
        _tear_slot(ring)
        assert ring.pop() == b"good-1"
        # the torn slot: pending but never readable
        assert ring.pending() and not ring.readable()
        assert ring.pop() is None
        # reclaim (the caller decided the writer is dead)
        assert ring.skip_torn()
        assert ring.torn_count == 1
        assert not ring.skip_torn()  # nothing torn anymore
        # the ring flows again past the reclaimed slot
        assert ring.push(b"good-2")
        assert ring.pop() == b"good-2"
    finally:
        ring.close()


def test_ring_reader_crash_reclaim():
    """All consumer state (tail cursor) lives in the segment: a
    successor attaching by name resumes exactly where the crashed
    reader stopped — nothing buffered in a lost process heap."""
    ring = ShmRing.create(slots=8, slot_bytes=64)
    try:
        for i in range(5):
            assert ring.push(f"m{i}".encode())
        reader1 = ShmRing.attach(**ring.descriptor())
        assert reader1.pop() == b"m0"
        assert reader1.pop() == b"m1"
        reader1.close()  # the "crash": the mapping goes away, cursors stay

        reader2 = ShmRing.attach(**ring.descriptor())
        assert reader2.pop() == b"m2"  # resumes, no loss, no replay
        assert len(reader2) == 2
        reader2.close()
    finally:
        ring.close()


def test_board_beat_age_epoch_generation():
    board = ShmBoard.create()
    try:
        assert board.age() == float("inf")  # never beaten
        board.beat(epoch=7, now=100.0)
        peer = ShmBoard.attach(board.name)
        assert peer.epoch == 7
        assert peer.age(now=100.5) == pytest.approx(0.5)
        board.bump_generation()
        assert peer.generation == 1
        peer.close()
    finally:
        board.close()


def test_request_codec_roundtrip():
    """The raw obs frame codec: leaves in, identical leaves out, laid
    out by the attach-time schema (no pickle on the hot path)."""
    leaves = [np.arange(12, dtype=np.float32).reshape(2, 6),
              np.array([[1], [0]], dtype=np.int32)]
    specs = [((6,), "float32"), ((1,), "int32")]
    parts = shm_mod.pack_request(3, 2, leaves)
    blob = b"".join(bytes(p) for p in parts)
    seq, rows, out = shm_mod.unpack_request(memoryview(blob), specs)
    assert (seq, rows) == (3, 2)
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------

def test_pipeline_config_defaults_on_and_validates():
    # the pipelined dataflow IS the mainline: an empty section runs
    # with the shm transport armed (remote workers and recurrent nets
    # auto-fall-back); `mode: off` restores the legacy path wholesale
    assert PipelineConfig.from_config({}).enabled
    assert PipelineConfig.from_config(None).enabled
    assert not PipelineConfig.from_config({"mode": "off"}).enabled
    assert PipelineConfig.from_config({"mode": "on"}).enabled
    with pytest.raises(ValueError, match="unknown pipeline keys"):
        PipelineConfig.from_config({"bogus": 1})
    with pytest.raises(ValueError, match="pipeline.mode"):
        PipelineConfig.from_config({"mode": "sideways"})
    with pytest.raises(ValueError, match="fallback"):
        PipelineConfig.from_config({"fallback": "explode"})
    with pytest.raises(ValueError, match="ring_slots"):
        PipelineConfig.from_config({"ring_slots": 0})
    with pytest.raises(ValueError, match="fallback_after"):
        PipelineConfig.from_config({"fallback_after": 0})


def test_train_config_validates_pipeline_section():
    from handyrl_tpu.config import Config

    raw = {"env_args": {"env": "TicTacToe"},
           "train_args": {"pipeline": {"mode": "on",
                                       "batch_window": 0.01}}}
    cfg = Config.from_dict(raw)
    assert cfg.train_args["pipeline"]["mode"] == "on"
    raw["train_args"]["pipeline"] = {"made_up": True}
    with pytest.raises(ValueError, match="unknown pipeline keys"):
        Config.from_dict(raw)


def test_chaos_infer_kill_epoch_validates():
    from handyrl_tpu.resilience import ChaosConfig

    cfg = ChaosConfig.from_config({"infer_kill_epoch": 2})
    assert cfg.infer_kill_enabled
    assert not ChaosConfig.from_config({}).infer_kill_enabled
    with pytest.raises(ValueError):
        ChaosConfig.from_config({"infer_kill_epoch": -1})


# ---------------------------------------------------------------------
# episode wire formats
# ---------------------------------------------------------------------

def test_raw_and_bz2_episode_blocks_are_interchangeable():
    """pack_episode(compress=False) produces raw pickle blocks that
    every consumer (batch maker, device-replay ingest) decodes
    identically to the legacy bz2 format — the two mix freely in one
    replay buffer (blocks are magic-sniffed)."""
    import random

    from handyrl_tpu.batch import decompress_moments
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.generation import Generator
    from handyrl_tpu.models import RandomModel, TPUModel
    from handyrl_tpu.staging import _decompress_episode

    random.seed(0)
    env = make_env({"env": "TicTacToe"})
    env.reset()
    model = TPUModel(env.net())
    obs0 = env.observation(env.players()[0])
    model.init_params(obs0, seed=0)
    rollout = RandomModel(model, obs0)
    players = env.players()
    job = {"player": players, "model_id": {p: 0 for p in players}}

    cfg = {"turn_based_training": True, "observation": False,
           "gamma": 0.8, "compress_steps": 4}
    raw_ep = None
    while raw_ep is None:
        raw_ep = Generator(env, dict(cfg, episode_compress=False)
                           ).generate({p: rollout for p in players}, job)
    assert all(b[:2] != b"BZ" for b in raw_ep["moment"])

    # re-pack the SAME moments compressed, decode both ways
    from handyrl_tpu.generation import pack_episode

    moments = decompress_moments(
        {**raw_ep, "start": 0, "end": raw_ep["steps"], "base": 0})
    bz_ep = pack_episode(moments, raw_ep["outcome"], raw_ep["args"], 4,
                         compress=True)
    assert all(b[:2] == b"BZ" for b in bz_ep["moment"])

    a = _decompress_episode(raw_ep)
    b = _decompress_episode(bz_ep)
    np.testing.assert_array_equal(a["prob"], b["prob"])
    np.testing.assert_array_equal(a["act"], b["act"])
    for la, lb in zip(np.asarray(a["obs"]).ravel(),
                      np.asarray(b["obs"]).ravel()):
        assert la == lb


# ---------------------------------------------------------------------
# batching-window units (injected clock)
# ---------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.now = 0.0
        self.on_advance = None  # callable(now) hook (scripted arrivals)

    def __call__(self):
        return self.now

    def sleep(self, dt):
        self.now += dt
        if self.on_advance is not None:
            self.on_advance(self.now)


class _StubModel:
    """Counts forwards; policy = row index so replies are checkable."""

    module = "stub"

    def __init__(self):
        self.calls = []

    def inference_batch(self, obs, hidden=None):
        rows = obs.shape[0]
        self.calls.append(rows)
        return {"policy": np.tile(
            np.arange(rows, dtype=np.float32)[:, None], (1, 3))}


def _make_service(window=1.0, max_batch=64):
    from handyrl_tpu.pipeline.service import InferenceService

    cfg = PipelineConfig.from_config({
        "mode": "on", "batch_window": window, "max_batch": max_batch,
        "ring_slots": 8, "slot_bytes": 4096,
        "traj_slots": 4, "traj_slot_mb": 1})
    clock = _FakeClock()
    model = _StubModel()
    svc = InferenceService(model, cfg, epoch=1,
                           clock=clock, sleep=clock.sleep)
    return svc, clock, model


def _push_request(svc, desc, seq, rows):
    req = ShmRing.attach(**desc["req"])
    leaves = [np.full((rows, 2), float(seq), np.float32)]
    assert req.push(shm_mod.pack_request(seq, rows, leaves))
    req.close()


def _pop_reply(desc):
    rsp = ShmRing.attach(**desc["rsp"])
    out = rsp.pop(loads=shm_mod.loads_view)
    rsp.close()
    return out


def test_batching_window_waits_for_batch_mates():
    """The wait-or-timeout window: a second worker's request arriving
    mid-window joins the SAME dispatch; the wait is accounted into
    infer_queue_wait_sec."""
    svc, clock, model = _make_service(window=1.0)
    try:
        spec = {"leaves": [((2,), "float32")],
                "example": np.zeros(2, np.float32), "rows_max": 4}
        d1 = svc.attach(spec)
        d2 = svc.attach(spec)
        _push_request(svc, d1, seq=1, rows=2)

        # scripted arrival: worker 2's request lands 0.4s into the window
        def arrive(now):
            if now >= 0.4 and not arrive.done:
                arrive.done = True
                _push_request(svc, d2, seq=1, rows=3)
        arrive.done = False
        clock.on_advance = arrive

        assert svc.step()
        assert model.calls == [8]          # 5 rows bucket-padded to 8
        r1 = _pop_reply(d1)
        r2 = _pop_reply(d2)
        assert r1[0] == 1 and r2[0] == 1   # both answered, matching seq
        assert r1[2]["policy"].shape == (2, 3)
        assert r2[2]["policy"].shape == (3, 3)
        # rows sliced in arrival order: d1 rows 0-1, d2 rows 2-4
        np.testing.assert_array_equal(r1[2]["policy"][:, 0], [0, 1])
        np.testing.assert_array_equal(r2[2]["policy"][:, 0], [2, 3, 4])
        stats = svc.epoch_stats()
        assert stats["infer_batches"] == 1
        assert stats["infer_requests"] == 2
        assert stats["infer_batch_size_mean"] == 5.0
        # dispatched at the window deadline: the wait is the window
        assert stats["infer_queue_wait_sec"] == pytest.approx(1.0,
                                                              abs=0.01)
    finally:
        svc.close()


def test_full_batch_short_circuits_the_window():
    """max_batch staged rows dispatch immediately — the window is a
    ceiling on latency, not a floor."""
    svc, clock, model = _make_service(window=5.0, max_batch=4)
    try:
        spec = {"leaves": [((2,), "float32")],
                "example": np.zeros(2, np.float32), "rows_max": 4}
        d1 = svc.attach(spec)
        _push_request(svc, d1, seq=1, rows=4)
        assert svc.step()
        assert clock.now < 5.0             # did not wait out the window
        assert model.calls == [4]          # no padding needed at cap
        assert svc.epoch_stats()["infer_batches"] == 1
    finally:
        svc.close()


def test_hot_swap_between_batches_answers_with_new_epoch():
    svc, clock, model = _make_service(window=0.0)
    try:
        spec = {"leaves": [((2,), "float32")],
                "example": np.zeros(2, np.float32), "rows_max": 4}
        d = svc.attach(spec)
        _push_request(svc, d, seq=1, rows=1)
        assert svc.step()
        assert _pop_reply(d)[1] == 1       # epoch 1 answered

        model2 = _StubModel()
        svc.set_model(model2, 2)           # the learner's hot swap
        _push_request(svc, d, seq=2, rows=1)
        assert svc.step()
        reply = _pop_reply(d)
        assert reply[1] == 2               # new snapshot, no drop
        assert model2.calls == [8]         # served BY the new model
    finally:
        svc.close()


# ---------------------------------------------------------------------
# served-model round trip + fallback/respawn (real service thread)
# ---------------------------------------------------------------------

def _real_service(mesh=None, fsdp=False, **cfg_over):
    import jax

    from handyrl_tpu.environment import make_env
    from handyrl_tpu.models import TPUModel
    from handyrl_tpu.pipeline import InferenceService, PipelineClient
    from handyrl_tpu.pipeline.client import build_obs_spec

    env = make_env({"env": "TicTacToe"})
    env.reset()
    model = TPUModel(env.net())
    model.init_params(env.observation(0), seed=0)
    cfg = PipelineConfig.from_config({
        "mode": "on", "batch_window": 0.001, "fallback_after": 0.4,
        **cfg_over})
    svc = InferenceService(model, cfg, epoch=1, mesh=mesh, fsdp=fsdp)
    svc.start()
    desc = svc.attach(build_obs_spec(env, 4))
    client = PipelineClient(desc, cfg)
    obs = env.observation(0)
    batch = jax.tree.map(lambda a: np.stack([np.asarray(a)] * 4), obs)
    return env, model, svc, client, obs, batch


def _wait_healthy(client, svc=None, timeout=10.0):
    """Wait for the first beat — and, when the service is given, for
    the attach-time jit warmup to finish, so the first served request
    is answered inside its reply deadline deterministically."""
    import time

    t0 = time.monotonic()
    while not client.healthy() or (svc is not None
                                   and svc.warm_pending):
        assert time.monotonic() - t0 < timeout, "service never warmed"
        time.sleep(0.01)


def test_served_inference_matches_local():
    """The served forward is bit-compatible with the local one (same
    params, same jit) across the batch, rows-selected, and single-obs
    entry points."""
    env, model, svc, client, obs, batch = _real_service()
    try:
        _wait_healthy(client, svc)
        served = client.wrap(model, epoch=1)
        local = model.inference_batch(batch, None)

        out = served.inference_batch(batch, None)
        np.testing.assert_allclose(out["policy"], local["policy"],
                                   rtol=1e-5)
        rows = np.array([0, 2])
        out = served.inference_batch(batch, None, rows=rows)
        np.testing.assert_allclose(out["policy"][rows],
                                   local["policy"][rows], rtol=1e-5)
        assert (out["policy"][1] == 0).all()  # unasked rows untouched

        single = served.inference(obs, None)
        np.testing.assert_allclose(
            single["policy"], model.inference(obs, None)["policy"],
            rtol=1e-5)
        assert svc.stats()["requests"] >= 3
        assert client.fallbacks == 0
    finally:
        svc.close()
        client.close()


def test_served_inference_on_multi_device_mesh():
    """served==local compatibility when the dispatch runs as ONE GSPMD
    program over the virtual 8-device mesh (dp4 x tp2 + fsdp): the
    real shm round trip answers within float32 epsilon of the local
    forward (row-sharded backend kernels reassociate — cross-PATH
    comparison is epsilon, not bitwise; the unsharded test above keeps
    the bitwise contract), the dispatch itself is deterministic
    (repeat requests bit-match each other), the snapshot was placed
    onto the param shardings exactly once, and the sharding-contract
    guard saw zero resharding copies."""
    import jax

    if len(jax.devices()) < 8:
        import pytest

        pytest.skip("needs 8 virtual devices")
    from handyrl_tpu.parallel import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices()[:8])
    env, model, svc, client, obs, batch = _real_service(
        mesh=mesh, fsdp=True)
    try:
        _wait_healthy(client, svc)
        served = client.wrap(model, epoch=1)
        local = model.inference_batch(batch, None)

        out1 = served.inference_batch(batch, None)
        out2 = served.inference_batch(batch, None)
        # tp-partitioned contractions drift 3e-6..6e-6 run-to-run on
        # this CPU stack (thread-count dependent): the bound matches
        # the dry-run's TP_ATOL headroom, not the smallest observed
        np.testing.assert_allclose(out1["policy"], local["policy"],
                                   rtol=0, atol=5e-5)
        np.testing.assert_array_equal(out1["policy"], out2["policy"])
        assert client.fallbacks == 0

        stats = svc.stats()
        assert stats["mesh_devices"] == 8
        assert stats["infer_resharding_copies"] == 0
        assert stats["infer_compiles"] >= 1
        # the snapshot rode ONE device_put onto the param shardings
        # (cached on the model object keyed by the sharding set: the
        # routed-LRU contract), and fsdp genuinely distributed at
        # least one leaf
        cached = getattr(model, "_infer_placed", None)
        assert cached is not None and cached[0] is svc._infer_sh
        assert any("dp" in tuple(l.sharding.spec)
                   for l in jax.tree.leaves(cached[1]))
    finally:
        svc.close()
        client.close()


def test_single_device_mesh_dispatch_is_bit_identical():
    """The tentpole's compatibility floor: a 1-device mesh compiles
    the SAME program as the mesh-less dispatch — outputs bit-match
    both the no-mesh service forward and plain local inference."""
    import jax

    from handyrl_tpu.environment import make_env
    from handyrl_tpu.models import TPUModel
    from handyrl_tpu.parallel import MeshSpec, make_mesh
    from handyrl_tpu.pipeline.service import InferenceService

    env = make_env({"env": "TicTacToe"})
    env.reset()
    model = TPUModel(env.net())
    model.init_params(env.observation(0), seed=0)
    cfg = PipelineConfig.from_config({"mode": "on"})
    batch = jax.tree.map(
        lambda a: np.stack([np.asarray(a)] * 8), env.observation(0))

    one = make_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
    svc_mesh = InferenceService(model, cfg, epoch=1, mesh=one)
    svc_plain = InferenceService(model, cfg, epoch=1)
    try:
        # no cache scrub needed: _placed_params keys its cache by the
        # service's sharding set, so crossing services re-places
        out_mesh = svc_mesh._forward(model, batch)
        out_plain = svc_plain._forward(model, batch)
        local = model.inference_batch(batch, None)
        for key, ref in local.items():
            if ref is None:
                continue
            np.testing.assert_array_equal(
                np.asarray(out_mesh[key]), np.asarray(ref))
            np.testing.assert_array_equal(
                np.asarray(out_plain[key]), np.asarray(ref))
        assert svc_mesh.shard_guard.copies == 0
    finally:
        svc_mesh.close()
        svc_plain.close()


def test_epoch_pinned_wrapper_skips_a_mismatched_service():
    """A wrapper pinned to another epoch answers locally WITHOUT a
    transport round trip — pinned eval seats and league opponents can
    never act on the newest policy by accident."""
    env, model, svc, client, obs, batch = _real_service()
    try:
        _wait_healthy(client, svc)
        pinned = client.wrap(model, epoch=99)   # service holds epoch 1
        before = svc.stats()["requests"]
        out = pinned.inference_batch(batch, None)
        np.testing.assert_allclose(
            out["policy"], model.inference_batch(batch, None)["policy"],
            rtol=1e-5)
        assert svc.stats()["requests"] == before  # no request shipped
    finally:
        svc.close()
        client.close()


def test_service_death_falls_back_and_respawn_resumes():
    """The supervised-fault contract end to end, in-process: kill the
    service (chaos shape: no parting beat) -> the client detects the
    stale board and answers locally; respawn -> the client returns to
    the served path on its own."""
    import time

    env, model, svc, client, obs, batch = _real_service()
    try:
        _wait_healthy(client, svc)
        served = client.wrap(model, epoch=1)
        local = model.inference_batch(batch, None)

        svc.inject_kill()
        deadline = time.monotonic() + 3.0
        while svc.alive:
            assert time.monotonic() < deadline, "kill never landed"
            time.sleep(0.01)
        time.sleep(0.5)  # past fallback_after: the board is stale now
        assert not client.healthy()
        out = served.inference_batch(batch, None)  # local fallback
        np.testing.assert_allclose(out["policy"], local["policy"],
                                   rtol=1e-5)
        assert client.fallbacks >= 1

        svc.respawn()
        _wait_healthy(client, svc)
        assert svc.board.generation == 1
        before = svc.stats()["rows_served"]
        out = served.inference_batch(batch, None)  # served again
        np.testing.assert_allclose(out["policy"], local["policy"],
                                   rtol=1e-5)
        assert svc.stats()["rows_served"] > before
    finally:
        svc.close()
        client.close()


def test_client_degrades_after_repeated_reply_timeouts():
    """A service that BEATS but never lands replies (reply slot too
    small for the output frame, a mistakenly-reaped client) must cost
    a few timed-out steps, not one full deadline per step forever:
    the client degrades itself, short-circuits further requests, and
    re-probes only on the service's next incarnation."""
    import time as _time

    svc, clock, model = _make_service(window=0.0)
    try:
        spec = {"leaves": [((2,), "float32")],
                "example": np.zeros(2, np.float32), "rows_max": 4}
        desc = svc.attach(spec)
        cfg = PipelineConfig.from_config(
            {"mode": "on", "batch_window": 0.001,
             "fallback_after": 0.05})
        client = PipelineClient(desc, cfg)
        svc.board.beat(epoch=1)  # alive — but nothing serves requests

        def beat_and_wait():
            # keep the board fresh while the client waits out its
            # reply deadline (the service "is up", replies never come)
            svc.board.beat(epoch=1)
            _time.sleep(1e-3)
        client.sleep = lambda dt: beat_and_wait()

        leaves = [np.zeros((1, 2), np.float32)]
        for _ in range(client.DEGRADE_AFTER):
            assert client.request(leaves) is None
        assert client.degraded
        t0 = _time.monotonic()
        assert client.request(leaves) is None   # short-circuits now
        assert _time.monotonic() - t0 < 0.04    # no deadline burned
        svc.board.bump_generation()             # "respawn"
        assert client.usable()                  # re-probes next time
        assert not client.degraded
        client.close()
    finally:
        svc.close()


def test_idle_clients_are_reaped_and_rings_reclaimed():
    """A client silent on both rings past CLIENT_IDLE_REAP (dead
    worker) leaves the live set immediately and its rings close after
    the graveyard grace — later pushes from a stale mapping are
    refused, never crash."""
    svc, clock, model = _make_service(window=0.0)
    try:
        spec = {"leaves": [((2,), "float32")],
                "example": np.zeros(2, np.float32), "rows_max": 4}
        desc = svc.attach(spec)
        stale = ShmRing.attach(**desc["req"])  # the dead worker's map
        clock.now = svc.CLIENT_IDLE_REAP + 1.0
        assert svc._reap_idle()                # removed from live set
        assert svc.stats()["clients"] == 0
        assert svc.stats()["clients_reaped"] == 1
        clock.now += svc.GRAVE_GRACE + 1.0
        svc._reap_idle()                       # graveyard close
        # the learner-side (owner) ring is closed; the dead worker's
        # own mapping pushes into a torn-down segment harmlessly —
        # owner-side accessors read as empty/refused
        assert stale.push(b"x")  # its own mapping still writes...
        stale.close()
        # ...but a fresh attach by name must now fail: unlinked
        with pytest.raises(FileNotFoundError):
            ShmRing.attach(**desc["req"])
        svc.attach(spec)                       # new clients still fine
        assert svc.stats()["clients"] == 1
    finally:
        svc.close()


def test_trajectory_ring_feeds_intake_and_spills_when_full():
    env, model, svc, client, obs, batch = _real_service(
        traj_slots=2, traj_slot_mb=1)
    try:
        ep = {"steps": 5, "moment": [b"\x80blob"], "outcome": {0: 1.0}}
        assert client.push_episode(ep)
        assert client.push_episode(ep)
        assert not client.push_episode(ep)   # ring full: spill signal
        assert client.episodes_spilled == 1
        drained = svc.drain_trajectories()
        assert len(drained) == 2 and drained[0]["steps"] == 5
        assert svc.ring_full_count() >= 1    # worker-side count, shm-read
        assert client.push_episode(ep)       # flows again after drain
    finally:
        svc.close()
        client.close()


# ---------------------------------------------------------------------
# shm chaos layer: ChaosRing / ChaosBoard fault injection
# ---------------------------------------------------------------------

def test_chaos_config_validates_shm_keys():
    from handyrl_tpu.resilience import ChaosConfig

    cfg = ChaosConfig.from_config({"shm_tear_prob": 0.5,
                                   "shm_stall_prob": 1.0})
    assert cfg.shm_faults_enabled
    assert not ChaosConfig.from_config({}).shm_faults_enabled
    assert ChaosConfig.from_config(
        {"shm_beat_drop_prob": 0.1}).shm_beat_faults_enabled
    with pytest.raises(ValueError, match="shm_tear_prob"):
        ChaosConfig.from_config({"shm_tear_prob": 1.5})
    with pytest.raises(ValueError, match="shm_beat_delay"):
        ChaosConfig.from_config({"shm_beat_delay": -1.0})
    with pytest.raises(ValueError, match="shm push"):
        ChaosConfig.from_config({"shm_tear_prob": 0.6,
                                 "shm_truncate_prob": 0.6})
    with pytest.raises(ValueError, match="shm beat"):
        ChaosConfig.from_config({"shm_beat_drop_prob": 0.7,
                                 "shm_beat_delay_prob": 0.7})


def test_chaos_ring_tear_injection_leaves_a_real_torn_slot():
    """An injected tear is indistinguishable from a producer SIGKILLed
    mid-RESERVE-THEN-FILL: reservation published (odd stamp, head
    past it), payload absent — and the standard reclaim applies."""
    from handyrl_tpu.resilience import ChaosConfig, ChaosRing

    ring = ShmRing.create(slots=4, slot_bytes=64)
    chaos = ChaosRing(ring, ChaosConfig.from_config(
        {"shm_tear_prob": 1.0, "seed": 1}))
    try:
        assert chaos.push(b"doomed")       # the "producer" died
        assert chaos.torn_injected == 1
        assert ring.pending() and not ring.readable()
        assert ring.pop() is None          # never consumed as data
        assert ring.skip_torn()            # reclaim
        assert ring.torn_count == 1
    finally:
        ring.close()


def test_chaos_ring_full_injection_counts_in_the_header():
    """Forced backpressure looks exactly like a full ring: refused AND
    counted where the consumer side reads it (shm header)."""
    from handyrl_tpu.resilience import ChaosConfig, ChaosRing

    ring = ShmRing.create(slots=4, slot_bytes=64)
    chaos = ChaosRing(ring, ChaosConfig.from_config(
        {"shm_full_prob": 1.0, "seed": 1}))
    try:
        assert not chaos.push(b"refused")
        assert chaos.full_injected == 1
        assert ring.full_count == 1        # consumer-visible
        assert len(ring) == 0              # nothing landed
    finally:
        ring.close()


def test_chaos_ring_truncated_payload_is_skipped_not_crashed():
    """Payload truncation under a complete-looking stamp: the consumer
    decode fails, the slot is skipped (counted torn) and the ring
    flows — at the ring level and through the service's drain."""
    from handyrl_tpu.resilience import ChaosConfig, ChaosRing

    ring = ShmRing.create(slots=4, slot_bytes=1024)
    chaos = ChaosRing(ring, ChaosConfig.from_config(
        {"shm_truncate_prob": 1.0, "seed": 1}))
    try:
        blob = shm_mod.dumps({"payload": list(range(64))})
        assert chaos.push(blob)
        assert chaos.truncated_injected == 1
        assert ring.readable()             # looks complete...
        with pytest.raises(Exception):
            ring.pop(loads=shm_mod.loads_view)  # ...but will not decode
        assert ring.skip_one()             # the consumer's escape
        assert ring.torn_count == 1
        assert ring.push(blob)             # clean producer resumes
        assert ring.pop(loads=shm_mod.loads_view)["payload"][3] == 3
    finally:
        ring.close()

    # RAW request frames detect truncation too: the short view makes
    # np.frombuffer raise (schema demands more bytes than the slot
    # holds) — truncation can never decode silently into garbage obs
    reqring = ShmRing.create(slots=2, slot_bytes=1024)
    try:
        chaos2 = ChaosRing(reqring, ChaosConfig.from_config(
            {"shm_truncate_prob": 1.0, "seed": 1}))
        assert chaos2.push(shm_mod.pack_request(
            1, 2, [np.zeros((2, 4), np.float32)]))
        with pytest.raises(Exception):
            reqring.pop(loads=lambda v: shm_mod.unpack_request(
                v, [((4,), "float32")]))
        assert reqring.skip_one()
        assert reqring.torn_count == 1
    finally:
        reqring.close()


def test_service_drain_skips_corrupt_trajectory_slots():
    """The learner-side degradation ladder for a poisoned slot: the
    drain counts + skips it and later episodes still arrive — one bad
    frame never takes the server loop down."""
    from handyrl_tpu.resilience import ChaosConfig, ChaosRing

    svc, clock, model = _make_service(window=0.0)
    try:
        spec = {"leaves": [((2,), "float32")],
                "example": np.zeros(2, np.float32), "rows_max": 4}
        desc = svc.attach(spec)
        traj = ShmRing.attach(**desc["traj"])
        poison = ChaosRing(traj, ChaosConfig.from_config(
            {"shm_truncate_prob": 1.0, "seed": 1}))
        assert poison.push(shm_mod.dumps({"steps": 1}))   # corrupt
        assert traj.push(shm_mod.dumps({"steps": 2}))     # clean
        drained = svc.drain_trajectories()
        assert [ep["steps"] for ep in drained] == [2]
        assert svc.corrupt == 1
        assert svc.stats()["corrupt_slots"] == 1
        assert svc.epoch_stats()["shm_torn_slots"] == 1
        traj.close()
    finally:
        svc.close()


def test_chaos_ring_stalled_consumer_backs_the_ring_up():
    from handyrl_tpu.resilience import ChaosConfig, ChaosRing

    ring = ShmRing.create(slots=4, slot_bytes=64)
    chaos = ChaosRing(ring, ChaosConfig.from_config(
        {"shm_stall_prob": 1.0, "seed": 1}))
    try:
        assert ring.push(b"waiting")
        assert ring.readable()
        assert chaos.pop() is None         # stalled: item stays queued
        assert chaos.stalls_injected == 1
        assert len(ring) == 1              # nothing consumed
        assert ring.pop() == b"waiting"    # a healthy consumer drains
    finally:
        ring.close()


def test_chaos_board_withholds_and_backdates_beats():
    from handyrl_tpu.resilience import ChaosBoard, ChaosConfig

    board = ShmBoard.create()
    try:
        drop = ChaosBoard(board, ChaosConfig.from_config(
            {"shm_beat_drop_prob": 1.0, "seed": 1}))
        drop.beat(epoch=3, now=100.0)
        assert drop.beats_dropped == 1
        assert board.age(now=100.0) == float("inf")  # never landed

        delay = ChaosBoard(board, ChaosConfig.from_config(
            {"shm_beat_delay_prob": 1.0, "shm_beat_delay": 0.5,
             "seed": 1}))
        delay.beat(epoch=3, now=100.0)
        assert delay.beats_delayed == 1
        assert board.age(now=100.0) == pytest.approx(0.5)  # backdated
        assert delay.epoch == 3            # reads delegate untouched
    finally:
        board.close()


# ---------------------------------------------------------------------
# surge brownout: the worker-side hold / paced drain / spill ladder
# ---------------------------------------------------------------------

def test_client_surge_hold_stages_paced_drain_and_overflow_spill():
    """The shm half of `surge_hold_uploads`: during the hold episodes
    stage in the bounded backlog (overflow spills, stamped + counted);
    after the hold the drain is paced FIFO (stale first, a small
    block per shipped episode); the exit flush ships everything —
    and every episode is accounted for (zero loss)."""
    from handyrl_tpu.pipeline.config import PipelineConfig
    from handyrl_tpu.resilience import ChaosConfig

    svc, svc_clock, model = _make_service(window=0.0)
    try:
        spec = {"leaves": [((2,), "float32")],
                "example": np.zeros(2, np.float32), "rows_max": 4}
        desc = svc.attach(spec)
        cfg = PipelineConfig.from_config(
            {"mode": "on", "traj_slots": 4, "traj_slot_mb": 1})
        chaos = ChaosConfig.from_config(
            {"surge_epoch": 2, "surge_hold_uploads": 30.0})
        clock = _FakeClock()
        client = PipelineClient(desc, cfg, clock=clock,
                                sleep=clock.sleep, chaos=chaos)
        try:
            # pre-surge jobs do not trigger (opponent seats are -1)
            client.note_jobs([{"model_id": {0: 1, 1: -1}}, None])
            assert not client.holding()
            client.note_jobs([{"model_id": {0: 2, 1: 2}}])
            assert client.holding()

            # 7 episodes during the hold: backlog caps at traj_slots
            # (4); the 3 oldest spill — stamped, counted, never lost
            spills = []
            for i in range(7):
                spills += client.ship_episode({"i": i})
            assert [e["i"] for e in spills] == [0, 1, 2]
            assert all(e["shm_spilled"] for e in spills)
            assert client.episodes_spilled == 3
            assert client.episodes_held == 7
            assert svc.drain_trajectories() == []   # nothing shipped

            # hold passes: the drain is paced FIFO — current episode
            # joins the tail, a small block ships from the head
            clock.now = 31.0
            assert client.ship_episode({"i": 7}) == []
            drained = svc.drain_trajectories()
            assert [e["i"] for e in drained] == [3, 4, 5]
            # shipped-while-backlogged episodes carry the live depth
            assert drained[0]["upload_backlog"] == 4

            # exit flush: remaining backlog ships over the ring where
            # it fits, spills the rest — zero loss either way
            spills2 = client.flush_backlog()
            drained2 = svc.drain_trajectories()
            shipped = {e["i"] for e in drained + drained2}
            spilled = {e["i"] for e in spills + spills2}
            assert shipped | spilled == set(range(8))
            assert not shipped & spilled
            assert (client.episodes_shipped + client.episodes_spilled
                    == 8)
        finally:
            client.close()
    finally:
        svc.close()


def test_spill_path_under_sustained_full_ring_pressure():
    """Satellite: the trajectory ring pinned full for a whole epoch —
    every episode arrives via the control-plane spill with ZERO loss
    (counts reconcile exactly), `shm_ring_full_count` and
    `episodes_spilled` both advance, and the drain restores ring
    shipping."""
    from handyrl_tpu.resilience import ChaosConfig, ChaosRing

    env, model, svc, client, obs, batch = _real_service()
    try:
        # pin the ring "full" for the epoch: every push refused and
        # counted, exactly what a consumer that never drains causes
        real_traj = client.traj
        client.traj = ChaosRing(real_traj, ChaosConfig.from_config(
            {"shm_full_prob": 1.0, "seed": 3}))
        spilled = []
        for i in range(20):
            spilled += client.ship_episode({"i": i})
        assert [e["i"] for e in spilled] == list(range(20))
        assert all(e["shm_spilled"] for e in spilled)
        assert client.episodes_spilled == 20
        assert svc.ring_full_count() >= 20       # backpressure, visible
        assert svc.drain_trajectories() == []    # nothing rode shm

        # the pressure lifts: ring shipping resumes on its own
        client.traj = real_traj
        for i in range(20, 30):
            assert client.ship_episode({"i": i}) == []
        drained = svc.drain_trajectories()
        assert [e["i"] for e in drained] == list(range(20, 30))
        # zero loss: every episode took exactly one of the two paths
        assert client.episodes_shipped + client.episodes_spilled == 30
    finally:
        svc.close()
        client.close()


def test_status_snapshot_exposes_shm_counters():
    """The status endpoint's pipeline section carries the brownout /
    degradation counters (torn slots, corrupt slots, shm-vs-spill
    episode split, hold backlog) next to the serving stats."""
    from types import SimpleNamespace

    from handyrl_tpu.learner import Learner

    svc, clock, model = _make_service(window=0.0)
    try:
        learner = Learner.__new__(Learner)
        learner.model_epoch = 3
        learner.episodes_received = 10
        learner.worker = SimpleNamespace(connection_count=lambda: 0)
        learner._run_t0 = 0.0
        learner.fleet = SimpleNamespace(snapshot=lambda: {})
        learner._last_record = None
        learner.infer_service = svc
        learner.episodes_shm = 7
        learner.episodes_spilled = 3
        snap = learner._status_snapshot()
        pipe = snap["pipeline"]
        assert pipe["episodes_shm"] == 7
        assert pipe["episodes_spilled"] == 3
        assert pipe["upload_backlog_peak"] == 0
        assert pipe["shm_torn_slots"] == 0
        assert pipe["corrupt_slots"] == 0
        assert "torn_reclaimed" in pipe and "clients_reaped" in pipe
    finally:
        svc.close()


# ---------------------------------------------------------------------
# real-kill torn-slot regression: SIGKILL a producer mid-slot-write
# ---------------------------------------------------------------------

class _StallingParts:
    """A parts sequence for ShmRing.push whose SECOND iteration (the
    write loop — the first computes the length) writes one chunk,
    signals the parent, then blocks: push is left mid-RESERVE-THEN-
    FILL (odd stamp down, head bumped, payload half-written) at the
    exact moment the parent's SIGKILL lands.  No crafted headers: the
    REAL producer code path dies a REAL death mid-slot-write."""

    def __init__(self, ready):
        self.ready = ready
        self.chunks = [b"A" * 8, b"B" * 8]
        self.iterations = 0

    def __iter__(self):
        self.iterations += 1
        if self.iterations == 1:
            return iter(self.chunks)       # push's length pass
        return self._write_pass()

    def _write_pass(self):
        import time

        yield self.chunks[0]               # half the payload lands
        self.ready.set()                   # mid-slot-write: kill me
        time.sleep(600)                    # SIGKILL lands here
        yield self.chunks[1]               # pragma: no cover


def _doomed_producer(desc, ready):
    """Child process: one complete episode, then a push that stalls
    mid-slot-write forever (until the parent SIGKILLs it)."""
    from handyrl_tpu.pipeline import ShmRing
    from handyrl_tpu.pipeline import shm as child_shm

    ring = ShmRing.attach(**desc)
    ring.push(child_shm.dumps({"steps": 5}))
    ring.push(_StallingParts(ready))       # never returns


def test_real_producer_sigkill_mid_slot_write_is_reclaimed():
    """The PR 9 seqlock claim proven against a REAL death: an actual
    producer process is SIGKILLed mid-slot-write (not a crafted
    header), and the consumer detects the odd stamp, skips the slot
    after the grace, counts it, and keeps serving later traffic."""
    import multiprocessing
    import os
    import signal

    ctx = multiprocessing.get_context("spawn")
    svc, clock, model = _make_service(window=0.0)
    try:
        spec = {"leaves": [((2,), "float32")],
                "example": np.zeros(2, np.float32), "rows_max": 4}
        desc = svc.attach(spec)
        ready = ctx.Event()
        proc = ctx.Process(target=_doomed_producer,
                           args=(desc["traj"], ready))
        proc.start()
        try:
            assert ready.wait(60), "producer never reached mid-write"
            os.kill(proc.pid, signal.SIGKILL)   # a real death
        finally:
            proc.join(30)
        assert proc.exitcode == -signal.SIGKILL

        # the complete episode drains; the torn slot stalls the ring
        drained = svc.drain_trajectories()
        assert [ep["steps"] for ep in drained] == [5]
        traj = ShmRing.attach(**desc["traj"])
        assert traj.pending() and not traj.readable()  # odd stamp

        # within the grace the slot is left alone (a live writer may
        # still be mid-frame); past it, the reclaim fires and counts
        assert svc.drain_trajectories() == []
        assert svc.reclaimed == 0
        clock.now = svc.TORN_GRACE + 1.0
        svc.drain_trajectories()
        assert svc.reclaimed == 1
        assert traj.torn_count == 1
        assert svc.epoch_stats()["shm_torn_slots"] == 1

        # training continues: a successor producer ships through the
        # reclaimed ring and the episode arrives intact
        assert traj.push(shm_mod.dumps({"steps": 9}))
        assert [ep["steps"]
                for ep in svc.drain_trajectories()] == [9]
        traj.close()
    finally:
        svc.close()


# ---------------------------------------------------------------------
# tier-1 e2e: chaos-kill the inference server mid-train
# ---------------------------------------------------------------------

def test_pipelined_training_survives_inference_server_kill(
        tmp_path, monkeypatch):
    """DELIBERATELY IN TIER-1 (deterministic, ~2 min): a full local
    training run with the pipeline ON whose inference service is
    chaos-killed at epoch 1 (``chaos.infer_kill_epoch``).  Training
    must complete every epoch anyway — workers bridge the gap on
    local CPU fallback, the learner respawns the service behind its
    backoff, and workers return to the served path (proven by served
    batches AFTER the respawn epoch)."""
    monkeypatch.chdir(tmp_path)

    args = {
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "turn_based_training": True, "observation": False,
            "gamma": 0.8, "forward_steps": 4, "burn_in_steps": 0,
            "compress_steps": 4, "entropy_regularization": 0.1,
            "entropy_regularization_decay": 0.1,
            "update_episodes": 15, "batch_size": 4,
            "minimum_episodes": 10, "maximum_episodes": 200,
            "epochs": 3, "num_batchers": 1, "eval_rate": 0.1,
            "worker": {"num_parallel": 2}, "lambda": 0.7,
            "policy_target": "VTRACE", "value_target": "VTRACE",
            "seed": 1, "max_update_compiles": 1,
            "metrics_path": "metrics.jsonl",
            # the subsystem under test: pipelined inference + shm
            # trajectories (mode deliberately OMITTED — the repo-wide
            # default is `on`, and this e2e proves the default, not a
            # per-test opt-in), with the service killed at epoch 1 and
            # a fast fallback so the gap is actually exercised
            "pipeline": {"fallback_after": 0.3},
            "chaos": {"infer_kill_epoch": 1},
            "respawn_backoff": 0.5,
        },
        "worker_args": {"num_parallel": 2, "server_address": ""},
    }

    from handyrl_tpu.learner import Learner

    learner = Learner(args)
    learner.run()

    assert learner.model_epoch == 3
    assert learner.trainer.failure is None
    assert learner._infer_killed           # the chaos actually fired
    assert learner._infer_respawns >= 1    # and the respawn recovered it

    with open("metrics.jsonl") as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert len(records) == 3
    for record in records:
        # the pipeline metric contract (docs/observability.md): every
        # epoch reports, even the served-nothing warmup epoch
        assert "infer_batches" in record
        assert "infer_requests" in record
        assert "shm_ring_full_count" in record
        assert "infer_respawns" in record
        assert record["stall_events"] == 0
        assert record["unknown_verbs"] == 0
    # served inference resumed after the kill: the respawn epoch (or a
    # later one) dispatched real batches with their size/wait stats
    post = [r for r in records if r["infer_respawns"] >= 1]
    assert post and sum(r["infer_batches"] for r in post) > 0
    served = [r for r in records if r["infer_batches"] > 0]
    assert served
    for r in served:
        assert r["infer_batch_size_mean"] >= 1
        assert r["infer_batch_size_p95"] >= 1
        assert r["infer_queue_wait_sec"] >= 0
