"""Static analysis + runtime guards for JAX/TPU correctness.

Two halves, one goal — keep the learner hot path device-bound and
trace-stable as the codebase grows:

  * :mod:`handyrl_tpu.analysis.jaxlint` — an AST-based analyzer (stdlib
    ``ast`` only, no runtime jax import) that enforces the classic JAX
    invariants repo-wide: no PRNG key reuse, no Python branching on
    tracers inside jitted code, no host syncs in hot loops, no
    use-after-donation, no retrace-forcing jit patterns, no leftover
    debug calls.  CLI: ``python -m handyrl_tpu.analysis.jaxlint``.
  * :mod:`handyrl_tpu.analysis.guards` — runtime context managers that
    measure what the linter cannot prove: ``RetraceGuard`` (compile
    counts of the update step) and ``HostTransferGuard``
    (device->host transfer counts per epoch).

Guard classes are re-exported lazily (PEP 562) so importing the
analysis package — e.g. from the jaxlint CLI — never pulls in jax.
"""

_GUARD_EXPORTS = ("RetraceGuard", "RetraceError", "HostTransferGuard",
                  "HostTransferError")

__all__ = list(_GUARD_EXPORTS)


def __getattr__(name):
    if name in _GUARD_EXPORTS:
        from . import guards

        return getattr(guards, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
