"""racelint — thread-spawn graph + lock environment for the control plane.

jaxlint (PR 1) made the jit layer mechanical, shardlint (PR 2) the mesh
layer, commlint (PR 4) the wire protocol; this module covers the layer
every review pass has found bugs in by hand: *thread interleavings*.
The learner is a dense multi-threaded system — server loop, inference-
service thread, serving-frontend handler threads, status HTTP threads,
StallWatchdog sampler, QueueCommunicator reader/writer, supervisor
sweeps — and its failure classes (PR 8's live-dict iteration from the
status thread, PR 13's unreserved ``inflight < max_inflight`` check)
are all instances of a few shapes the rules in :mod:`.racerules`
detect.  This module computes the package-level facts they consume:

  * the **thread-spawn graph**: which functions are thread roots
    (``Thread(target=...)`` / ``Timer``, resolved through spawn
    wrappers by fixpoint the way commlint resolves send wrappers, plus
    ``ThreadingHTTPServer``-style per-connection handler classes), and
    which *context set* every function runs on — the set of roots that
    reach it through resolvable calls, or ``{"main"}`` when nothing
    spawned reaches it;
  * the **lock environment**: which ``threading.Lock``-valued
    attributes exist (``self._lock = threading.Lock()`` in a method,
    class-level ``_admit_lock = threading.Lock()``, module-level
    locks), which of them every attribute access lexically holds via
    ``with``-statement scoping, and helper-method *entry summaries*
    ("every in-package call site of ``_live_count`` holds
    ``FleetRegistry._lock``, so its accesses are guarded too");
  * per-class **shared-attribute tables**: every ``self.X`` read /
    write / read-modify-write / container-mutation / iteration with
    its effective lock set and thread contexts;
  * the **lock-acquisition-order graph** (nested ``with`` blocks plus
    calls-under-lock into the transitive may-acquire summary) for
    cycle detection, and blocking-call / acquire-without-release facts.

Everything is stdlib ``ast`` only — like its three siblings the
analyzer never imports jax (or threading).  The abstraction is
deliberately approximate in the quiet direction: only ``self.X``
state, resolvable lock expressions, and resolvable calls participate;
a store of a plain constant (``self._stop = True``) is recognized as
the GIL-atomic flag idiom and stays quiet.  The per-line suppression
syntax is the escape hatch for intentionally lock-free designs (the
telemetry ring's atomic deque appends, say).
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .astutil import (
    FunctionInfo,
    ModuleInfo,
    Package,
    _enclosing_class,
    dotted_parts,
)

# -- name tables ------------------------------------------------------

LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
})
RLOCK_CTORS = frozenset({"threading.RLock", "multiprocessing.RLock"})
THREAD_CTORS = frozenset({"threading.Thread", "threading.Timer"})
# server classes that run each handler-class method on its own thread
THREADED_SERVERS = frozenset({
    "http.server.ThreadingHTTPServer",
    "socketserver.ThreadingTCPServer",
    "socketserver.ThreadingUDPServer",
    "socketserver.ThreadingMixIn",
})
# calls that park the holding thread: full dotted names...
BLOCKING_FNS = frozenset({
    "time.sleep", "select.select", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output", "os.system",
    "os.waitpid",
})
# ...and attribute-call names (socket/queue/thread/event verbs)
BLOCKING_ATTRS = frozenset({
    "recv", "accept", "join", "sleep", "wait", "select", "connect",
    "send", "sendall", "recv_exact", "send_recv", "serve_forever",
})
# full-name prefixes whose trailing attr coincides with a blocking verb
# but never parks a thread (``os.path.join`` is string glue)
_SAFE_BLOCK_PREFIXES = ("os.path.", "posixpath.", "ntpath.", "shlex.")

# container-method calls that mutate the receiver in place
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "setdefault",
})
# builtins that iterate their (single) argument to completion
ITER_WRAPPERS = frozenset({
    "sum", "list", "tuple", "set", "dict", "frozenset", "max", "min",
    "sorted", "any", "all",
})
_VIEW_METHODS = frozenset({"values", "items", "keys"})


# -- facts ------------------------------------------------------------

@dataclass
class LockInfo:
    """One lock object the package constructs."""

    key: str                     # "Class.attr" or "module:NAME"
    module: ModuleInfo
    line: int
    reentrant: bool


@dataclass
class ThreadRoot:
    """One function that runs on a spawned thread."""

    fn: FunctionInfo
    kind: str                    # "thread" | "timer" | "handler" | "wrapped"
    name: Optional[str]          # literal name= kwarg when present
    module: ModuleInfo
    line: int


@dataclass
class Access:
    """One ``self.X`` touch with its lexical lock set."""

    cls: str                     # canonical owning class name
    attr: str
    kind: str                    # read|write|rmw|mutate|iterate
    fn: FunctionInfo
    node: ast.AST
    locks: FrozenSet[str]        # effective (lexical + entry) lock keys
    const_value: bool = False    # write of a plain constant (flag idiom)


@dataclass
class CallSite:
    """One resolved in-package call with the caller's held locks."""

    caller: FunctionInfo
    callee: FunctionInfo
    node: ast.AST
    locks: FrozenSet[str]


@dataclass
class BlockSite:
    """One potentially-blocking call."""

    fn: FunctionInfo
    node: ast.AST
    desc: str
    locks: FrozenSet[str]


@dataclass
class LockOp:
    """One explicit ``.acquire()`` / ``.release()`` on a known lock."""

    fn: FunctionInfo
    node: ast.AST
    key: str
    op: str                      # "acquire" | "release"
    in_finally: bool


@dataclass
class OrderEdge:
    """Lock B acquired while lock A is held."""

    src: str
    dst: str
    fn: FunctionInfo
    node: ast.AST
    via: Optional[str] = None    # callee qname when the edge crosses a call


@dataclass
class FnRace:
    """Per-function concurrency summary."""

    may_acquire: Set[str] = field(default_factory=set)
    blocking: Optional[Tuple[str, int]] = None   # (desc, line), transitive
    entry_locks: FrozenSet[str] = frozenset()


def _walk_calls(mod: ModuleInfo):
    """Every Call node with its enclosing FunctionInfo (or None)."""
    out = []

    def walk(node, scope):
        for child in ast.iter_child_nodes(node):
            child_scope = mod.by_node.get(child, scope)
            if isinstance(child, ast.Call):
                out.append((scope, child))
            walk(child, child_scope)

    walk(mod.tree, None)
    return out


def _fn_body(fn: FunctionInfo) -> List[ast.stmt]:
    if isinstance(fn.node, ast.Lambda):
        return [ast.Expr(fn.node.body)]
    return fn.node.body


def _in_ctor(fn: FunctionInfo) -> bool:
    """Is this function ``__init__`` (or nested inside it)?  Writes
    there happen before any thread this object spawns exists."""
    probe = fn
    while probe is not None:
        if probe.qname.rsplit(":", 1)[-1].split(".")[-1] == "__init__":
            return True
        probe = probe.parent
    return False


def _const_write(value) -> bool:
    """A stored value whose write is a single atomic bytecode under the
    GIL *and* carries no derived state: the ``self._stop = True`` flag
    idiom."""
    if isinstance(value, ast.Constant):
        return True
    return (isinstance(value, ast.UnaryOp)
            and isinstance(value.operand, ast.Constant))


class RaceAnalysis:
    """All thread/lock facts of one package, computed once."""

    MAX_PASSES = 4

    def __init__(self, package: Package):
        self.pkg = package
        self.locks: Dict[str, LockInfo] = {}
        self._lock_attr_index: Dict[str, List[str]] = {}
        self._class_bases: Dict[str, List[str]] = {}
        self._class_methods: Dict[str, Set[str]] = {}
        self.thread_roots: Dict[str, ThreadRoot] = {}
        self.contexts: Dict[FunctionInfo, FrozenSet[str]] = {}
        self.accesses: Dict[Tuple[str, str], List[Access]] = {}
        self.call_sites: List[CallSite] = []
        self.block_sites: List[BlockSite] = []
        self.lock_ops: List[LockOp] = []
        self.order_edges: List[OrderEdge] = []
        self.summaries: Dict[FunctionInfo, FnRace] = {}
        self._with_acquires: Dict[FunctionInfo, Set[str]] = {}

        self._collect_classes()
        self._collect_locks()
        self._collect_thread_roots()
        self._walk_functions()
        self._compute_entry_locks()
        self._compute_contexts()
        self._compute_summaries()
        self._add_transitive_edges()

    # -- class / lock tables ------------------------------------------
    def _collect_classes(self):
        for mod in self.pkg.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = []
                for b in node.bases:
                    parts = dotted_parts(b)
                    if parts:
                        bases.append(parts[-1])
                self._class_bases[node.name] = bases
            for cls, methods in mod.classes.items():
                self._class_methods.setdefault(cls, set()).update(methods)
            for fn in mod.functions:
                if fn.cls_name is not None:
                    self._class_methods.setdefault(
                        fn.cls_name, set()).add(
                            fn.qname.rsplit(":", 1)[-1].split(".")[-1])

    def _class_chain(self, cls: str) -> List[str]:
        """``cls`` plus its (transitive, by-name) base classes."""
        chain, seen = [cls], {cls}
        i = 0
        while i < len(chain):
            for base in self._class_bases.get(chain[i], ()):
                if base not in seen:
                    seen.add(base)
                    chain.append(base)
            i += 1
        return chain

    def _collect_locks(self):
        for mod in self.pkg.modules.values():
            # module-level: LOCK = threading.Lock()
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) \
                        and isinstance(stmt.value, ast.Call):
                    name = self.pkg.full_name(mod, None, stmt.value.func)
                    if name in LOCK_CTORS:
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                self._add_lock(
                                    f"{mod.name}:{tgt.id}", mod,
                                    stmt.lineno, name in RLOCK_CTORS,
                                    attr=None)
            # class-level: _admit_lock = threading.Lock() in a class body
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign) \
                            and isinstance(stmt.value, ast.Call):
                        name = self.pkg.full_name(mod, None,
                                                  stmt.value.func)
                        if name in LOCK_CTORS:
                            for tgt in stmt.targets:
                                if isinstance(tgt, ast.Name):
                                    self._add_lock(
                                        f"{node.name}.{tgt.id}", mod,
                                        stmt.lineno,
                                        name in RLOCK_CTORS,
                                        attr=tgt.id)
            # instance: self.X = threading.Lock() anywhere in a method
            for fn in mod.functions:
                cls = _enclosing_class(fn)
                if cls is None:
                    continue
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Assign) \
                            or not isinstance(node.value, ast.Call):
                        continue
                    name = self.pkg.full_name(mod, fn, node.value.func)
                    if name not in LOCK_CTORS:
                        continue
                    for tgt in node.targets:
                        parts = dotted_parts(tgt)
                        if parts and len(parts) == 2 \
                                and parts[0] == "self":
                            self._add_lock(
                                f"{cls}.{parts[1]}", mod, node.lineno,
                                name in RLOCK_CTORS, attr=parts[1])

    def _add_lock(self, key, mod, line, reentrant, attr):
        if key not in self.locks:
            self.locks[key] = LockInfo(key, mod, line, reentrant)
        if attr is not None:
            keys = self._lock_attr_index.setdefault(attr, [])
            if key not in keys:
                keys.append(key)

    def _is_lock_attr(self, cls: Optional[str], attr: str) -> bool:
        if cls is not None:
            for c in self._class_chain(cls):
                if f"{c}.{attr}" in self.locks:
                    return True
        return False

    def resolve_lock(self, fn: Optional[FunctionInfo], mod: ModuleInfo,
                     expr) -> Optional[str]:
        """A lock-valued expression -> its lock key, or None.

        ``self.X`` resolves through the enclosing class (and its
        bases); a bare name through module-level locks (including
        ``from .x import LOCK``); ``obj.X`` resolves when exactly one
        class in the package owns a lock attribute named ``X`` (the
        ``state.lock`` idiom for module-singleton state objects).
        """
        parts = dotted_parts(expr)
        if parts is None:
            return None
        if len(parts) == 1:
            key = f"{mod.name}:{parts[0]}"
            if key in self.locks:
                return key
            imp = mod.from_imports.get(parts[0])
            if imp is not None:
                key = f"{imp[0]}:{imp[1]}"
                if key in self.locks:
                    return key
            return None
        attr = parts[-1]
        if parts[0] == "self" and len(parts) == 2 and fn is not None:
            cls = _enclosing_class(fn)
            if cls is not None:
                for c in self._class_chain(cls):
                    key = f"{c}.{attr}"
                    if key in self.locks:
                        return key
        candidates = self._lock_attr_index.get(attr, [])
        if len(candidates) == 1:
            return candidates[0]
        return None

    # -- thread roots --------------------------------------------------
    def _collect_thread_roots(self):
        spawn_params: Dict[FunctionInfo, Set[str]] = {}

        def add_root(fi, kind, name, mod, line):
            if fi.qname not in self.thread_roots:
                self.thread_roots[fi.qname] = ThreadRoot(
                    fi, kind, name, mod, line)

        def target_expr(call, ctor_name):
            kw_name = "function" if ctor_name.endswith("Timer") \
                else "target"
            for kw in call.keywords:
                if kw.arg == kw_name:
                    return kw.value
            if len(call.args) >= 2:
                return call.args[1]
            return None

        def literal_name(call):
            for kw in call.keywords:
                if kw.arg == "name" and isinstance(kw.value,
                                                   ast.Constant):
                    return str(kw.value.value)
            return None

        for mod in self.pkg.modules.values():
            for scope, call in _walk_calls(mod):
                name = self.pkg.full_name(mod, scope, call.func)
                if name in THREAD_CTORS:
                    tgt = target_expr(call, name)
                    if tgt is None:
                        continue
                    res = self.pkg.resolve_callee(mod, scope, tgt)
                    if res is not None and res[0] == "fn":
                        kind = "timer" if name.endswith("Timer") \
                            else "thread"
                        add_root(res[1], kind, literal_name(call), mod,
                                 call.lineno)
                    elif isinstance(tgt, ast.Name) and scope is not None \
                            and tgt.id in scope.all_params:
                        spawn_params.setdefault(scope, set()).add(tgt.id)
                elif name in THREADED_SERVERS and len(call.args) >= 2 \
                        and isinstance(call.args[1], ast.Name):
                    handler_cls = call.args[1].id
                    if handler_cls in mod.classes \
                            or handler_cls in self._class_methods:
                        for fi in mod.functions:
                            if fi.cls_name == handler_cls:
                                add_root(fi, "handler", handler_cls,
                                         mod, call.lineno)

        # fixpoint: calls into spawn wrappers make their function-valued
        # arguments thread roots too (and propagate wrapper-of-wrapper)
        for _ in range(self.MAX_PASSES):
            changed = False
            for mod in self.pkg.modules.values():
                for scope, call in _walk_calls(mod):
                    res = self.pkg.resolve_callee(mod, scope, call.func)
                    if res is None or res[0] != "fn" \
                            or res[1] not in spawn_params:
                        continue
                    wrapper = res[1]
                    names = wrapper.callable_params
                    exprs = []
                    for idx, arg in enumerate(call.args):
                        if idx < len(names) \
                                and names[idx] in spawn_params[wrapper]:
                            exprs.append(arg)
                    for kw in call.keywords:
                        if kw.arg in spawn_params[wrapper]:
                            exprs.append(kw.value)
                    for expr in exprs:
                        tres = self.pkg.resolve_callee(mod, scope, expr)
                        if tres is not None and tres[0] == "fn":
                            if tres[1].qname not in self.thread_roots:
                                add_root(tres[1], "wrapped", None, mod,
                                         call.lineno)
                                changed = True
                        elif isinstance(expr, ast.Name) \
                                and scope is not None \
                                and expr.id in scope.all_params:
                            before = spawn_params.setdefault(scope,
                                                             set())
                            if expr.id not in before:
                                before.add(expr.id)
                                changed = True
            if not changed:
                break

    # -- per-function walk ---------------------------------------------
    def _walk_functions(self):
        for mod in self.pkg.modules.values():
            for fn in mod.functions:
                _FnWalker(self, fn).run()

    def _record_access(self, fn, attr, kind, node, locks,
                       const_value=False):
        cls = _enclosing_class(fn)
        if cls is None:
            return
        if self._is_lock_attr(cls, attr):
            return
        if attr in self._class_methods.get(cls, ()):  # method refs
            return
        owner = cls
        for c in self._class_chain(cls)[1:]:
            if attr in self._class_methods.get(c, ()):
                return
        self.accesses.setdefault((owner, attr), []).append(Access(
            owner, attr, kind, fn, node, frozenset(locks), const_value))

    # -- entry-lock summaries ------------------------------------------
    def _compute_entry_locks(self):
        """Locks held at EVERY in-package call site of a function —
        the ``_live_count`` "called with the lock held" helper idiom.
        Two relaxation passes: direct site locks, then one level of
        caller-entry chaining (enough for helper-of-helper)."""
        sites: Dict[FunctionInfo, List[CallSite]] = {}
        for cs in self.call_sites:
            sites.setdefault(cs.callee, []).append(cs)
        entry: Dict[FunctionInfo, FrozenSet[str]] = {}
        for fn, fn_sites in sites.items():
            if fn.qname in self.thread_roots:
                continue  # the spawner's locks are NOT held on the thread
            common = None
            for cs in fn_sites:
                common = cs.locks if common is None \
                    else common & cs.locks
            if common:
                entry[fn] = common
        for fn, fn_sites in sites.items():
            if fn.qname in self.thread_roots or fn in entry:
                continue
            common = None
            for cs in fn_sites:
                eff = cs.locks | entry.get(cs.caller, frozenset())
                common = eff if common is None else common & eff
            if common:
                entry[fn] = common
        for fn, locks in entry.items():
            self.summaries.setdefault(fn, FnRace()).entry_locks = locks
        # fold entry locks into the recorded facts
        if entry:
            for sites_list in self.accesses.values():
                for acc in sites_list:
                    extra = entry.get(acc.fn)
                    if extra:
                        acc.locks = acc.locks | extra
            for bs in self.block_sites:
                extra = entry.get(bs.fn)
                if extra:
                    bs.locks = bs.locks | extra
            for cs in self.call_sites:
                extra = entry.get(cs.caller)
                if extra:
                    cs.locks = cs.locks | extra

    # -- thread contexts -----------------------------------------------
    def _compute_contexts(self):
        ctx: Dict[FunctionInfo, Set[str]] = {}
        callers: Dict[FunctionInfo, Set[FunctionInfo]] = {}
        for cs in self.call_sites:
            callers.setdefault(cs.callee, set()).add(cs.caller)
        for fn in self.pkg.all_functions():
            ctx[fn] = set()
            if fn.qname in self.thread_roots:
                ctx[fn].add(fn.qname)
        for fn in self.pkg.all_functions():
            if not ctx[fn] and not callers.get(fn):
                ctx[fn].add("main")
        for _ in range(16):
            changed = False
            for cs in self.call_sites:
                add = ctx.get(cs.caller, set()) - ctx[cs.callee]
                if add:
                    ctx[cs.callee] |= add
                    changed = True
            if not changed:
                break
        self.contexts = {fn: frozenset(c or {"main"})
                         for fn, c in ctx.items()}

    def context_of(self, fn: FunctionInfo) -> FrozenSet[str]:
        return self.contexts.get(fn, frozenset({"main"}))

    # -- may-acquire / blocking summaries ------------------------------
    def _compute_summaries(self):
        direct_block: Dict[FunctionInfo, Tuple[str, int]] = {}
        for bs in self.block_sites:
            direct_block.setdefault(bs.fn,
                                    (bs.desc, bs.node.lineno))
        for edge in self.order_edges:
            self.summaries.setdefault(edge.fn, FnRace()).may_acquire.add(
                edge.dst)
        acquired_in: Dict[FunctionInfo, Set[str]] = {}
        for mod in self.pkg.modules.values():
            for fn in mod.functions:
                acquired_in[fn] = set()
        for op in self.lock_ops:
            if op.op == "acquire":
                acquired_in.setdefault(op.fn, set()).add(op.key)
        for (fn, keys) in self._with_acquires.items():
            acquired_in.setdefault(fn, set()).update(keys)
        for fn, keys in acquired_in.items():
            if keys:
                self.summaries.setdefault(fn,
                                          FnRace()).may_acquire |= keys
        for fn, desc in direct_block.items():
            self.summaries.setdefault(fn, FnRace()).blocking = desc
        calls_of: Dict[FunctionInfo, List[CallSite]] = {}
        for cs in self.call_sites:
            calls_of.setdefault(cs.caller, []).append(cs)
        for _ in range(self.MAX_PASSES):
            changed = False
            for fn, sites in calls_of.items():
                sm = self.summaries.setdefault(fn, FnRace())
                for cs in sites:
                    callee_sm = self.summaries.get(cs.callee)
                    if callee_sm is None:
                        continue
                    add = callee_sm.may_acquire - sm.may_acquire
                    if add:
                        sm.may_acquire |= add
                        changed = True
                    if sm.blocking is None \
                            and callee_sm.blocking is not None:
                        sm.blocking = (
                            f"{callee_sm.blocking[0]} (via "
                            f"{cs.callee.qname})", cs.node.lineno)
                        changed = True
            if not changed:
                break

    def summary(self, fn: FunctionInfo) -> FnRace:
        return self.summaries.setdefault(fn, FnRace())

    # -- transitive lock-order edges -----------------------------------
    def _add_transitive_edges(self):
        seen = {(e.src, e.dst, e.fn.module.name)
                for e in self.order_edges}
        for cs in self.call_sites:
            if not cs.locks:
                continue
            callee_sm = self.summaries.get(cs.callee)
            if callee_sm is None or not callee_sm.may_acquire:
                continue
            for held in cs.locks:
                for acq in callee_sm.may_acquire:
                    if held == acq \
                            and self.locks.get(held) is not None \
                            and self.locks[held].reentrant:
                        continue
                    key = (held, acq, cs.caller.module.name)
                    if key in seen:
                        continue
                    seen.add(key)
                    self.order_edges.append(OrderEdge(
                        held, acq, cs.caller, cs.node,
                        via=cs.callee.qname))

    # -- gate helpers --------------------------------------------------
    def dominating_lock(self, cls: str, attr: str,
                        kinds: Optional[Tuple[str, ...]] = None,
                        ) -> Optional[str]:
        """The lock key held at every (non-ctor) access of
        ``cls.attr`` — the repo gate's "known guarded attrs resolve"
        proof.  None when any access is bare or the attr is unknown."""
        sites = [a for a in self.accesses.get((cls, attr), [])
                 if not _in_ctor(a.fn)
                 and (kinds is None or a.kind in kinds)]
        if not sites:
            return None
        common = None
        for a in sites:
            common = set(a.locks) if common is None \
                else common & set(a.locks)
        if not common:
            return None
        return sorted(common)[0]


class _FnWalker:
    """Lexical walk of one function body carrying the held-lock set."""

    def __init__(self, an: RaceAnalysis, fn: FunctionInfo):
        self.an = an
        self.fn = fn
        self.mod = fn.module
        self.cls = _enclosing_class(fn)
        self.with_acquires: Set[str] = set()

    def run(self):
        for stmt in _fn_body(self.fn):
            self._stmt(stmt, (), False)
        if self.with_acquires:
            self.an._with_acquires.setdefault(
                self.fn, set()).update(self.with_acquires)

    # -- helpers -------------------------------------------------------
    def _self_attr(self, expr) -> Optional[str]:
        parts = dotted_parts(expr)
        if parts is not None and len(parts) >= 2 and parts[0] == "self":
            return parts[1]
        return None

    def _container_attr(self, expr) -> Optional[Tuple[str, ast.AST]]:
        """``self.X`` or ``self.X.values()/items()/keys()`` -> X."""
        if isinstance(expr, ast.Attribute):
            attr = self._self_attr(expr)
            if attr is not None and dotted_parts(expr) is not None \
                    and len(dotted_parts(expr)) == 2:
                return attr, expr
        if isinstance(expr, ast.Call) and not expr.args \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in _VIEW_METHODS:
            inner = expr.func.value
            parts = dotted_parts(inner)
            if parts is not None and len(parts) == 2 \
                    and parts[0] == "self":
                return parts[1], expr
        return None

    def _reads_attr(self, expr, attr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) \
                    and node.attr == attr \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                return True
        return False

    def _access(self, attr, kind, node, held, const_value=False):
        self.an._record_access(self.fn, attr, kind, node, held,
                               const_value)

    # -- statements ----------------------------------------------------
    def _stmt(self, stmt, held, in_finally):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs run later, not under these locks
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                self._assign_target(tgt, stmt.value, stmt, held)
            self._expr(stmt.value, held, in_finally)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign_target(stmt.target, stmt.value, stmt, held)
                self._expr(stmt.value, held, in_finally)
        elif isinstance(stmt, ast.AugAssign):
            attr = self._self_attr(stmt.target)
            if attr is not None and isinstance(stmt.target,
                                               ast.Attribute):
                self._access(attr, "rmw", stmt, held)
            elif isinstance(stmt.target, ast.Subscript):
                base = self._self_attr(stmt.target.value)
                if base is not None:
                    self._access(base, "rmw", stmt, held)
            self._expr(stmt.value, held, in_finally)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Attribute):
                    attr = self._self_attr(tgt)
                    if attr is not None:
                        self._access(attr, "write", stmt, held)
                elif isinstance(tgt, ast.Subscript):
                    base = self._self_attr(tgt.value)
                    if base is not None:
                        self._access(base, "mutate", stmt, held)
                    self._expr(tgt.slice, held, in_finally)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                key = self.an.resolve_lock(self.fn, self.mod,
                                           item.context_expr)
                if key is None:
                    self._expr(item.context_expr, new_held, in_finally)
                    continue
                self.with_acquires.add(key)
                info = self.an.locks.get(key)
                for h in new_held:
                    if h == key and info is not None \
                            and info.reentrant:
                        continue
                    self.an.order_edges.append(OrderEdge(
                        h, key, self.fn, item.context_expr))
                if key not in new_held:
                    new_held = new_held + (key,)
            for s in stmt.body:
                self._stmt(s, new_held, in_finally)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = stmt.iter
            cont = self._container_attr(it)
            if cont is not None:
                self._access(cont[0], "iterate", it, held)
            else:
                self._expr(it, held, in_finally)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, held, in_finally)
        elif isinstance(stmt, ast.While):
            self._expr(stmt.test, held, in_finally)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, held, in_finally)
        elif isinstance(stmt, ast.If):
            self._expr(stmt.test, held, in_finally)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, held, in_finally)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse:
                self._stmt(s, held, in_finally)
            for handler in stmt.handlers:
                for s in handler.body:
                    self._stmt(s, held, in_finally)
            for s in stmt.finalbody:
                self._stmt(s, held, True)
        elif isinstance(stmt, (ast.Return, ast.Expr, ast.Raise,
                               ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, held, in_finally)
        else:
            # anything newer (Match, ...): scan expressions, recurse
            # into statement children with the same held set
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, held, in_finally)
                elif isinstance(child, ast.stmt):
                    self._stmt(child, held, in_finally)

    def _assign_target(self, tgt, value, stmt, held):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._assign_target(el, value, stmt, held)
            return
        if isinstance(tgt, ast.Starred):
            self._assign_target(tgt.value, value, stmt, held)
            return
        if isinstance(tgt, ast.Attribute):
            attr = self._self_attr(tgt)
            if attr is not None:
                if self._reads_attr(value, attr):
                    self._access(attr, "rmw", stmt, held)
                else:
                    self._access(attr, "write", stmt, held,
                                 const_value=_const_write(value))
            return
        if isinstance(tgt, ast.Subscript):
            base = self._self_attr(tgt.value)
            if base is not None:
                kind = "rmw" if self._reads_attr(value, base) \
                    else "mutate"
                self._access(base, kind, stmt, held)
            else:
                self._expr(tgt.value, held, False)
            self._expr(tgt.slice, held, False)

    # -- expressions ---------------------------------------------------
    def _expr(self, e, held, in_finally):
        if e is None or isinstance(e, (ast.Constant, ast.Lambda)):
            return
        if isinstance(e, ast.Call):
            self._call(e, held, in_finally)
            return
        if isinstance(e, ast.Attribute):
            attr = self._self_attr(e)
            parts = dotted_parts(e)
            if attr is not None and parts is not None:
                # self.a.b.c reads self.a; record the closest-to-self
                self._access(attr, "read", e, held)
                return
            self._expr(e.value, held, in_finally)
            return
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            for gen in e.generators:
                cont = self._container_attr(gen.iter)
                if cont is not None:
                    self._access(cont[0], "iterate", gen.iter, held)
                else:
                    self._expr(gen.iter, held, in_finally)
                for cond in gen.ifs:
                    self._expr(cond, held, in_finally)
            if isinstance(e, ast.DictComp):
                self._expr(e.key, held, in_finally)
                self._expr(e.value, held, in_finally)
            else:
                self._expr(e.elt, held, in_finally)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, held, in_finally)

    def _blocking_desc(self, call, full_name) -> Optional[str]:
        if full_name in BLOCKING_FNS:
            return full_name
        if full_name is not None and full_name.startswith(
                _SAFE_BLOCK_PREFIXES):
            return None
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in BLOCKING_ATTRS:
            if isinstance(call.func.value, ast.Constant):
                return None  # "sep".join(...) string glue
            return f".{call.func.attr}()"
        return None

    def _call(self, call, held, in_finally):
        full_name = self.an.pkg.full_name(self.mod, self.fn, call.func)
        res = self.an.pkg.resolve_callee(self.mod, self.fn, call.func)
        if res is not None and res[0] == "fn":
            self.an.call_sites.append(CallSite(
                self.fn, res[1], call, frozenset(held)))
        else:
            desc = self._blocking_desc(call, full_name)
            if desc is not None:
                self.an.block_sites.append(BlockSite(
                    self.fn, call, desc, frozenset(held)))
        # explicit acquire / release on a known lock
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("acquire", "release"):
            key = self.an.resolve_lock(self.fn, self.mod,
                                       call.func.value)
            if key is not None:
                self.an.lock_ops.append(LockOp(
                    self.fn, call, key, call.func.attr, in_finally))
                if call.func.attr == "acquire":
                    info = self.an.locks.get(key)
                    for h in held:
                        if h == key and info is not None \
                                and info.reentrant:
                            continue
                        self.an.order_edges.append(OrderEdge(
                            h, key, self.fn, call))
        # iteration wrappers: sum(self.d.values()), list(self.conns)...
        if isinstance(call.func, ast.Name) \
                and call.func.id in ITER_WRAPPERS \
                and len(call.args) == 1 and not call.keywords:
            cont = self._container_attr(call.args[0])
            if cont is not None:
                self._access(cont[0], "iterate", call, held)
                return
        # method call on a self attribute: mutator or plain read
        if isinstance(call.func, ast.Attribute):
            base = call.func.value
            battr = self._self_attr(base)
            bparts = dotted_parts(base)
            if battr is not None and bparts is not None \
                    and len(bparts) == 2:
                kind = "mutate" if call.func.attr in MUTATORS \
                    else "read"
                self._access(battr, kind, call, held)
            else:
                self._expr(base, held, in_finally)
        elif not isinstance(call.func, ast.Name):
            self._expr(call.func, held, in_finally)
        for arg in call.args:
            self._expr(arg, held, in_finally)
        for kw in call.keywords:
            self._expr(kw.value, held, in_finally)


def analyze_race(package: Package) -> RaceAnalysis:
    """Compute (or fetch the cached) thread/lock analysis."""
    cached = getattr(package, "_racelint_analysis", None)
    if cached is None:
        cached = RaceAnalysis(package)
        package._racelint_analysis = cached
    return cached
