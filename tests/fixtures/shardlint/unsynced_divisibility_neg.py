"""Fixture: the constraint rides behind an explicit divisibility
check (and symbolic specs stay quiet)."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), ("dp", "sp"))


def shard_batch(mesh, batch, sp_size):
    sharded = NamedSharding(mesh, P("dp", "sp"))
    if batch.shape[0] % sp_size == 0:
        return jax.lax.with_sharding_constraint(batch, sharded)
    return batch


def shard_opaque(batch, sharding):
    # the spec is the caller's problem: unresolvable, stays quiet
    return jax.lax.with_sharding_constraint(batch, sharding)


def shard_batch_truthiness_guard(mesh, batch, dp_size):
    # the `if dim % n: raise` spelling counts as a guard too
    sharded = NamedSharding(mesh, P("dp"))
    if batch.shape[0] % dp_size:
        raise ValueError("batch must divide dp")
    return jax.lax.with_sharding_constraint(batch, sharded)
