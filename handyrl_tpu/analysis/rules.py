"""jaxlint's rule registry: the six JAX/TPU correctness rules.

Each rule is a function ``(Package, ModuleInfo) -> Iterable[Finding]``
registered under a stable kebab-case id (the id is what suppression
comments name).  Rules consume the package model + taint facts built
by :mod:`.astutil`; none of them import jax.

The rules, and the TPU failure mode each one prevents:

  ``prng-reuse``      same PRNG key consumed twice -> correlated
                      "random" streams (silently wrong math).
  ``tracer-branch``   Python ``if``/``while`` on a tracer inside
                      jit-traced code -> trace-time concretization
                      error, or one silent recompile per branch value.
  ``host-sync``       ``.item()`` / ``float()`` / ``np.asarray()`` /
                      ``jax.device_get`` on device values inside a loop
                      -> the learner blocks on a device round trip
                      every iteration (the #1 TPU throughput killer).
  ``donated-reuse``   reading a buffer after passing it to a
                      ``donate_argnums`` jit -> garbage data or a
                      runtime "buffer deleted" error.
  ``retrace-risk``    jit-in-a-loop / inline ``jax.jit(f)(x)`` /
                      non-literal static options / non-hashable
                      static arguments -> compile on every call.
  ``debug-leftover``  ``jax.debug.print`` / ``breakpoint`` left in
                      production code -> host callbacks serialized
                      into the compiled program.
"""

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .astutil import (
    JIT_WRAPPERS,
    DeviceTaint,
    FunctionInfo,
    ModuleInfo,
    Package,
    TracerTaint,
    dotted_parts,
    jit_meta_from_call,
)


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}"


@dataclass
class Rule:
    rule_id: str
    summary: str
    doc: str
    check: "object"


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, summary: str):
    def deco(fn):
        RULES[rule_id] = Rule(rule_id, summary, fn.__doc__ or "", fn)
        return fn
    return deco


# ---------------------------------------------------------------------
# shared walking helpers
# ---------------------------------------------------------------------

_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_COMP_NODES = (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)


def walk_with_context(mod: ModuleInfo) -> Iterator[Tuple[ast.AST,
                                                         Optional[FunctionInfo],
                                                         int]]:
    """Yield every node with its enclosing function and loop depth.

    Depths respect evaluation semantics: a ``for``'s iterable (and a
    comprehension's FIRST iterable) evaluates once, outside the loop it
    opens; a ``while`` test re-evaluates every iteration; comprehension
    element/filter expressions run once per item.  Nested function
    bodies restart the depth (they execute at their call site).
    """
    out = []

    def child_of(node, scope, depth):
        child_scope = mod.by_node.get(node, scope)
        if isinstance(node, _FN_NODES):
            depth = 0
        out.append((node, child_scope, depth))
        descend(node, child_scope, depth)

    def descend(node, scope, depth):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            child_of(node.iter, scope, depth)        # evaluates once
            child_of(node.target, scope, depth + 1)
            for sub in node.body + node.orelse:
                child_of(sub, scope, depth + 1)
            return
        if isinstance(node, ast.While):
            child_of(node.test, scope, depth + 1)    # per iteration
            for sub in node.body + node.orelse:
                child_of(sub, scope, depth + 1)
            return
        if isinstance(node, _COMP_NODES):
            first = node.generators[0]
            child_of(first.iter, scope, depth)       # evaluates once
            child_of(first.target, scope, depth + 1)
            for cond in first.ifs:
                child_of(cond, scope, depth + 1)
            for gen in node.generators[1:]:
                for sub in ast.iter_child_nodes(gen):
                    child_of(sub, scope, depth + 1)
            for field in ("elt", "key", "value"):
                sub = getattr(node, field, None)
                if sub is not None:
                    child_of(sub, scope, depth + 1)
            return
        for sub in ast.iter_child_nodes(node):
            child_of(sub, scope, depth)

    descend(mod.tree, None, 0)
    return iter(out)


def own_statements(fn: FunctionInfo) -> List[ast.stmt]:
    body = fn.node.body
    if isinstance(fn.node, ast.Lambda):
        return [ast.Expr(fn.node.body)]
    return body


def own_nodes(fn: FunctionInfo) -> Iterator[ast.AST]:
    """All nodes of ``fn``'s body, excluding nested function bodies."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FN_NODES):
                continue
            yield child
            yield from walk(child)

    for stmt in own_statements(fn):
        yield stmt
        yield from walk(stmt)


def _tracer_eval(fn: FunctionInfo, pkg: Package) -> TracerTaint:
    ev = TracerTaint(fn, pkg)
    ev.tainted = set(fn.tracer_locals) | set(fn.tainted_params)
    return ev


def _device_eval(fn: FunctionInfo, pkg: Package) -> DeviceTaint:
    ev = DeviceTaint(fn, pkg)
    ev.tainted = set(fn.device_locals) | set(fn.device_params)
    ev.jit_names = dict(fn.jit_locals)
    return ev


def _tainted_names(ev, expr) -> List[str]:
    names = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in ev.tainted \
                and node.id not in names:
            names.append(node.id)
    return names


# ---------------------------------------------------------------------
# prng-reuse
# ---------------------------------------------------------------------

_KEY_PRODUCERS = frozenset({
    "jax.random.PRNGKey", "jax.random.key", "jax.random.split",
    "jax.random.fold_in", "jax.random.wrap_key_data",
})


@rule("prng-reuse",
      "a PRNG key is consumed more than once (or re-consumed every "
      "loop iteration)")
def check_prng_reuse(pkg: Package, mod: ModuleInfo):
    """Tracks names bound from ``jax.random.PRNGKey`` / ``split`` /
    ``fold_in`` within each function.  A key passed to two consuming
    calls — or created outside a loop and consumed inside it — yields
    correlated samples; ``jax.random.split`` it instead.  Parameters
    count as keys once ``jax.random.*`` consumes them.
    """
    for fn in mod.functions:
        yield from _check_prng_fn(pkg, mod, fn)


def _check_prng_fn(pkg: Package, mod: ModuleInfo, fn: FunctionInfo):
    keys: Dict[str, Tuple[Tuple[int, ...], int]] = {}  # name -> (loops, uses)
    param_uses: Dict[str, int] = {}
    findings = []

    def bind(name: str, loops):
        keys[name] = (loops, 0)

    def bind_target(target, loops):
        if isinstance(target, ast.Name):
            bind(target.id, loops)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                bind_target(el, loops)

    def consume(name: str, node, loops, via_random: bool,
                deriving: bool):
        if name in keys:
            bound_loops, uses = keys[name]
            if uses >= 1:
                findings.append(Finding(
                    "prng-reuse", mod.path, node.lineno, node.col_offset,
                    f"PRNG key '{name}' is consumed more than once — "
                    f"derive fresh keys with jax.random.split/fold_in"))
            elif len(loops) > len(bound_loops) and not deriving:
                # split/fold_in INSIDE the loop is the derivation idiom
                # (fold_in(base, i) / key, sub = split(key)) — only
                # direct sampling from an outer key is the bug
                findings.append(Finding(
                    "prng-reuse", mod.path, node.lineno, node.col_offset,
                    f"PRNG key '{name}' was created outside this loop "
                    f"but is consumed inside it — every iteration "
                    f"reuses the same randomness"))
            keys[name] = (bound_loops, uses + 1)
        elif via_random and name in fn.all_params:
            param_uses[name] = param_uses.get(name, 0) + 1
            if param_uses[name] == 2:
                findings.append(Finding(
                    "prng-reuse", mod.path, node.lineno, node.col_offset,
                    f"PRNG key parameter '{name}' is consumed by two "
                    f"jax.random calls — split it first"))

    def handle_call(call: ast.Call, loops):
        name = pkg.full_name(mod, fn, call.func)
        via_random = bool(name and name.startswith("jax.random.")
                          and name not in ("jax.random.PRNGKey",
                                           "jax.random.key"))
        deriving = name in ("jax.random.split", "jax.random.fold_in")
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            inner = arg.value if isinstance(arg, ast.Starred) else arg
            if isinstance(inner, ast.Name):
                if inner.id in keys or via_random:
                    consume(inner.id, call, loops, via_random, deriving)

    def is_key_expr(value) -> bool:
        if isinstance(value, ast.Call):
            name = pkg.full_name(mod, fn, value.func)
            return name in _KEY_PRODUCERS
        if isinstance(value, ast.Subscript):
            base = value.value
            return isinstance(base, ast.Name) and base.id in keys
        return False

    def scan_calls(node, loops):
        if isinstance(node, _FN_NODES):
            return  # nested defs consume in their own scope
        if isinstance(node, ast.Call):
            handle_call(node, loops)
        inner = loops + (id(node),) if isinstance(node, _COMP_NODES) \
            else loops
        for child in ast.iter_child_nodes(node):
            scan_calls(child, inner)

    def walk_stmt(stmt, loops):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        is_loop = isinstance(stmt, (ast.For, ast.AsyncFor, ast.While))
        inner_loops = loops + (id(stmt),) if is_loop else loops
        for expr in _stmt_exprs(stmt):
            # a For header evaluates once, outside the loop it opens; a
            # While test re-evaluates every iteration
            depth = loops if (isinstance(stmt, (ast.For, ast.AsyncFor))
                              and expr is stmt.iter) else inner_loops
            scan_calls(expr, depth)
        if isinstance(stmt, ast.Assign) and is_key_expr(stmt.value):
            for tgt in stmt.targets:
                bind_target(tgt, loops)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)) \
                and is_key_expr(stmt.iter):
            bind_target(stmt.target, inner_loops)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                walk_stmt(child, inner_loops)

    for stmt in own_statements(fn):
        walk_stmt(stmt, ())
    return findings


def _stmt_exprs(stmt) -> List[ast.expr]:
    """The expressions evaluated by this statement itself (not by its
    nested sub-statements)."""
    out = []
    for field, value in ast.iter_fields(stmt):
        if isinstance(value, ast.expr):
            out.append(value)
        elif isinstance(value, list):
            out += [v for v in value if isinstance(v, ast.expr)]
    return out


# ---------------------------------------------------------------------
# tracer-branch
# ---------------------------------------------------------------------

@rule("tracer-branch",
      "Python if/while branches on a traced value inside jit-compiled "
      "code")
def check_tracer_branch(pkg: Package, mod: ModuleInfo):
    """Inside functions reachable from a ``jax.jit``/``shard_map``
    entry point, a Python ``if``/``while``/conditional expression whose
    test involves a traced value either fails to trace or silently
    bakes one branch into the compiled program.  Shape/dtype/None
    guards (``x.shape[0] > 1``, ``x is None``) are static and stay
    quiet; use ``jnp.where``/``lax.cond`` for value-dependent control
    flow.
    """
    for fn in mod.functions:
        if not fn.jit_reachable:
            continue
        ev = _tracer_eval(fn, pkg)
        for node in own_nodes(fn):
            test = None
            kind = None
            if isinstance(node, (ast.If, ast.While)):
                test, kind = node.test, (
                    "if" if isinstance(node, ast.If) else "while")
            elif isinstance(node, ast.IfExp):
                test, kind = node.test, "conditional expression"
            elif isinstance(node, ast.comprehension):
                for cond in node.ifs:
                    if ev.taint(cond):
                        yield Finding(
                            "tracer-branch", mod.path, cond.lineno,
                            cond.col_offset,
                            "comprehension filter on a traced value "
                            "inside jit-compiled code")
                continue
            if test is None or not ev.taint(test):
                continue
            names = _tainted_names(ev, test)
            what = f" ({', '.join(names)})" if names else ""
            yield Finding(
                "tracer-branch", mod.path, test.lineno, test.col_offset,
                f"Python {kind} branches on a traced value{what} inside "
                f"jit-compiled code — use jnp.where/lax.cond, or mark "
                f"the argument static")


# ---------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------

_SYNC_CASTS = frozenset({"float", "int", "bool", "complex"})
_NP_SINKS = frozenset({"numpy.asarray", "numpy.array"})


@rule("host-sync",
      "a device value is synced to the host inside a loop (or inside "
      "jit-traced code)")
def check_host_sync(pkg: Package, mod: ModuleInfo):
    """``.item()``, ``float()``/``int()``/``bool()``, ``np.asarray()``
    and ``jax.device_get`` on device values block on a device->host
    round trip.  Once per epoch that is fine; inside a loop (including
    comprehensions) it serializes the hot path — fetch the whole tree
    once with ``jax.device_get`` instead.  Inside jit-traced code the
    same calls are trace errors and are flagged at any depth.
    """
    evals: Dict[FunctionInfo, DeviceTaint] = {}
    tracer_evals: Dict[FunctionInfo, TracerTaint] = {}
    for node, scope, depth in walk_with_context(mod):
        if not isinstance(node, ast.Call) or scope is None:
            continue
        ev = evals.get(scope)
        if ev is None:
            ev = evals[scope] = _device_eval(scope, pkg)
        name = pkg.full_name(mod, scope, node.func)
        in_jit = scope.jit_reachable
        tev = None
        if in_jit:
            tev = tracer_evals.get(scope)
            if tev is None:
                tev = tracer_evals[scope] = _tracer_eval(scope, pkg)

        def arg_hits(evaluator):
            return any(evaluator.taint(a) for a in node.args)

        if name == "jax.device_get":
            if depth > 0:
                yield Finding(
                    "host-sync", mod.path, node.lineno, node.col_offset,
                    "jax.device_get inside a loop — hoist it out and "
                    "fetch the whole tree in one transfer")
            elif in_jit:
                yield Finding(
                    "host-sync", mod.path, node.lineno, node.col_offset,
                    "jax.device_get inside jit-traced code")
        elif name in _SYNC_CASTS or name in _NP_SINKS:
            label = name.replace("numpy.", "np.")
            if depth > 0 and arg_hits(ev):
                yield Finding(
                    "host-sync", mod.path, node.lineno, node.col_offset,
                    f"{label}() on a device value inside a loop — each "
                    f"call blocks on a device->host transfer; "
                    f"jax.device_get the whole tree once instead")
            elif in_jit and arg_hits(tev):
                yield Finding(
                    "host-sync", mod.path, node.lineno, node.col_offset,
                    f"{label}() on a traced value inside jit-compiled "
                    f"code — this fails (or constant-folds) at trace "
                    f"time")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args):
            base = node.func.value
            if depth > 0 and ev.taint(base):
                yield Finding(
                    "host-sync", mod.path, node.lineno, node.col_offset,
                    ".item() on a device value inside a loop — each "
                    "call is a blocking device->host sync")
            elif in_jit and tev is not None and tev.taint(base):
                yield Finding(
                    "host-sync", mod.path, node.lineno, node.col_offset,
                    ".item() on a traced value inside jit-compiled code")


# ---------------------------------------------------------------------
# donated-reuse
# ---------------------------------------------------------------------

@rule("donated-reuse",
      "an argument buffer is read after being donated to a jit call")
def check_donated_reuse(pkg: Package, mod: ModuleInfo):
    """Arguments at ``donate_argnums`` positions are invalidated by the
    call: XLA reuses their memory for the outputs.  Reading the old
    name afterwards (or on the next loop iteration, when the call did
    not rebind it) sees deleted buffers.  Rebind the donated name from
    the call's results, as in ``params, opt = step(params, opt, x)``.
    """
    for fn in mod.functions:
        yield from _check_donated_fn(pkg, mod, fn)


def _check_donated_fn(pkg: Package, mod: ModuleInfo, fn: FunctionInfo):
    ev = _device_eval(fn, pkg)
    findings = []

    def as_dotted(expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return expr.id
        parts = dotted_parts(expr)
        if parts is not None and len(parts) == 2 and parts[0] == "self":
            return f"self.{parts[1]}"
        return None

    def loads_in(stmt) -> Set[str]:
        names = set()
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                names.add(node.id)
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                d = as_dotted(node)
                if d is not None:
                    names.add(d)
        return names

    def targets_in(stmt) -> Set[str]:
        names = set()
        nodes = []
        if isinstance(stmt, ast.Assign):
            nodes = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            nodes = [stmt.target]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            nodes = [stmt.target]
        elif isinstance(stmt, ast.With):
            nodes = [i.optional_vars for i in stmt.items
                     if i.optional_vars is not None]
        for tnode in nodes:
            for node in ast.walk(tnode):
                d = as_dotted(node)
                if d is not None:
                    names.add(d)
        # walrus assignments anywhere in the statement
        for node in ast.walk(stmt):
            if isinstance(node, ast.NamedExpr):
                d = as_dotted(node.target)
                if d is not None:
                    names.add(d)
        return names

    def donations_in(stmt) -> Dict[str, ast.Call]:
        out = {}
        for node in ast.walk(stmt):
            if isinstance(node, _FN_NODES):
                continue
            if not isinstance(node, ast.Call):
                continue
            meta = ev.jit_value(node.func)
            if meta is None or not meta.donate:
                continue
            for pos in meta.donate:
                if pos < len(node.args):
                    d = as_dotted(node.args[pos])
                    if d is not None:
                        out[d] = node
        return out

    def process_block(stmts, donated: Dict[str, ast.Call]):
        block_donates: Dict[str, ast.Call] = {}
        block_assigns: Set[str] = set()
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            sub_blocks = [getattr(stmt, f, None)
                          for f in ("body", "orelse", "finalbody")]
            sub_stmts = [s for block in sub_blocks if block
                         for s in block]
            if isinstance(stmt, ast.Try):
                for handler in stmt.handlers:
                    sub_stmts += handler.body
            own = [n for n in _stmt_exprs(stmt)]
            # 1. loads of currently-donated names -> findings
            if sub_stmts:
                header_loads = set()
                for expr in own:
                    header_loads |= loads_in(expr)
            else:
                header_loads = loads_in(stmt)
            for name in sorted(header_loads):
                if name in donated:
                    findings.append(Finding(
                        "donated-reuse", mod.path, stmt.lineno,
                        stmt.col_offset,
                        f"'{name}' was donated to the jit call on line "
                        f"{donated[name].lineno} and must not be read "
                        f"afterwards — rebind it from the call's "
                        f"outputs"))
                    del donated[name]  # report once
            # 2. this statement's own donations
            if sub_stmts:
                stmt_donations = {}
                for expr in own:
                    stmt_donations.update(donations_in(expr))
            else:
                stmt_donations = donations_in(stmt)
            # 3. recurse into sub-blocks
            if sub_stmts:
                is_loop = isinstance(stmt, (ast.For, ast.AsyncFor,
                                            ast.While))
                sub_don, sub_asn = process_block(sub_stmts, donated)
                if is_loop:
                    for name, call in sub_don.items():
                        if name not in sub_asn:
                            findings.append(Finding(
                                "donated-reuse", mod.path, call.lineno,
                                call.col_offset,
                                f"'{name}' is donated inside this loop "
                                f"but never rebound — the next "
                                f"iteration reads a deleted buffer"))
                block_donates.update(sub_don)
                block_assigns |= sub_asn
            # 4. record donations, then clear assigned names
            donated.update(stmt_donations)
            block_donates.update(stmt_donations)
            assigns = targets_in(stmt)
            block_assigns |= assigns
            for name in assigns:
                donated.pop(name, None)
        return block_donates, block_assigns

    process_block(own_statements(fn), {})
    return findings


# ---------------------------------------------------------------------
# retrace-risk
# ---------------------------------------------------------------------

_NONHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
                ast.DictComp)


@rule("retrace-risk",
      "a jit pattern that forces re-compilation on every call")
def check_retrace_risk(pkg: Package, mod: ModuleInfo):
    """Flags (a) ``jax.jit(f)(x)`` compiled inline — the compile cache
    dies with the expression, so every execution recompiles; (b)
    ``jax.jit`` created inside a loop — same failure, one wrapper (and
    cache) per iteration; (c) ``static_argnums``/``static_argnames``/
    ``donate_argnums`` that are not literals — the linter (and the
    reader) can no longer see the contract; (d) list/dict/set literals
    passed at a static position — non-hashable statics raise, and a
    fresh literal per call retraces even when hashable.
    """
    evals: Dict[FunctionInfo, DeviceTaint] = {}
    for node, scope, depth in walk_with_context(mod):
        if not isinstance(node, ast.Call):
            continue
        name = pkg.full_name(mod, scope, node.func)
        if name in JIT_WRAPPERS:
            if depth > 0:
                yield Finding(
                    "retrace-risk", mod.path, node.lineno,
                    node.col_offset,
                    f"{name.rsplit('.', 1)[-1]} created inside a loop "
                    f"— each iteration builds a fresh wrapper and "
                    f"compile cache; build it once outside")
            if not jit_meta_from_call(node).constant_opts:
                yield Finding(
                    "retrace-risk", mod.path, node.lineno,
                    node.col_offset,
                    "static_argnums/static_argnames/donate_argnums "
                    "should be literal ints/strings so the trace "
                    "contract is auditable")
        if isinstance(node.func, ast.Call):
            inner = pkg.full_name(mod, scope, node.func.func)
            if inner in JIT_WRAPPERS:
                yield Finding(
                    "retrace-risk", mod.path, node.lineno,
                    node.col_offset,
                    f"{inner.rsplit('.', 1)[-1]}(...)(...) compiles "
                    f"inline and discards the cache — every call "
                    f"recompiles; bind the jitted function once")
        # (d) non-hashable literals at static positions
        if scope is not None:
            ev = evals.get(scope)
            if ev is None:
                ev = evals[scope] = _device_eval(scope, pkg)
            meta = ev.jit_value(node.func)
            if meta is not None and meta.static_nums:
                for pos in meta.static_nums:
                    if pos < len(node.args) and isinstance(
                            node.args[pos], _NONHASHABLE):
                        yield Finding(
                            "retrace-risk", mod.path,
                            node.args[pos].lineno,
                            node.args[pos].col_offset,
                            f"non-hashable literal at static argument "
                            f"position {pos} — static args must be "
                            f"hashable, and a fresh value per call "
                            f"forces a retrace")


# ---------------------------------------------------------------------
# debug-leftover
# ---------------------------------------------------------------------

_DEBUG_CALLS = frozenset({
    "jax.debug.print", "jax.debug.breakpoint", "breakpoint",
    "pdb.set_trace", "ipdb.set_trace",
})


@rule("debug-leftover",
      "a debugging call (jax.debug.print / breakpoint) left in "
      "production code")
def check_debug_leftover(pkg: Package, mod: ModuleInfo):
    """``jax.debug.print``/``jax.debug.breakpoint`` serialize host
    callbacks into the compiled program (and breakpoints hang headless
    runs).  Fine while debugging; never in merged code.
    """
    for node, scope, _depth in walk_with_context(mod):
        if not isinstance(node, ast.Call):
            continue
        name = pkg.full_name(mod, scope, node.func)
        if name in _DEBUG_CALLS:
            yield Finding(
                "debug-leftover", mod.path, node.lineno, node.col_offset,
                f"leftover {name}() — remove before merging")
