"""GRFProxy: a football-drill env at Google-Research-Football scale.

Capability proof for BASELINE.json config #5 ("Google Research
Football, LSTM policy, large-scale distributed workers").  The real
GRF env cannot ship here — the reference snapshot lacks it (SURVEY
§2.2) and the package is not installable — so this drill reproduces
the parts of the workload that stress the FRAMEWORK, at the real
geometry:

  * (72, 96, 16) binary observation planes — the GRF SMM raster size,
    ~110 KB/step/player as uint8 wire format vs the flagship's 1.3 KB;
  * long episodes (default 1000 steps, configurable to 3000) that
    exercise ring ``t_max`` sizing, bz2 wire cost, and burn-in replay
    at GRF horizons;
  * a recurrent policy (models/grf_net.py) carrying ConvLSTM state;
  * a scripted chaser (``rule_based_action``) as the drill opponent.

The game itself is simple keepaway-to-goal: two players on a 72x96
field, a ball that is picked up by proximity, goals at the left/right
field ends; a goal scores and resets positions.  Outcome is the sign
of the final score difference.  Rules are intentionally light — the
env exists to generate GRF-shaped traffic, not to model football.
"""

import random

import numpy as np

from ..environment import BaseEnvironment

ROWS, COLS = 72, 96
PLANES = 16
NUM_AGENTS = 2
SPEED = 2            # cells per move
PICKUP = 3           # possession radius (chebyshev)
DEFAULT_STEPS = 1000

# action -> (drow, dcol): 0 stay, then 8 compass directions
MOVES = [(0, 0), (-1, 0), (-1, 1), (0, 1), (1, 1),
         (1, 0), (1, -1), (0, -1), (-1, -1)]
# player 0 attacks the right goal column, player 1 the left
GOAL_COL = {0: COLS - 1, 1: 0}


class Environment(BaseEnvironment):
    def __init__(self, args=None):
        super().__init__(args)
        self.args = args or {}
        self.max_steps = int(self.args.get("max_steps", DEFAULT_STEPS))
        self.reset()

    def reset(self, args=None):
        self.pos = {0: [ROWS // 2, COLS // 4],
                    1: [ROWS // 2, 3 * COLS // 4]}
        self.ball = [ROWS // 2, COLS // 2]
        self.owner = -1
        self.score = [0, 0]
        self.last_scores = {}
        self.step_count = 0
        return False

    # -- simultaneous transition -------------------------------------
    def turns(self):
        return [0, 1]

    def step(self, actions):
        self.last_scores = {}
        for p in (0, 1):
            dr, dc = MOVES[actions.get(p) or 0]
            pos = self.pos[p]
            pos[0] = min(ROWS - 1, max(0, pos[0] + dr * SPEED))
            pos[1] = min(COLS - 1, max(0, pos[1] + dc * SPEED))
        if self.owner >= 0:
            self.ball = list(self.pos[self.owner])
        # possession: closest player within the pickup radius; on an
        # exact tie the ball stays loose (symmetric)
        dists = {p: max(abs(self.pos[p][0] - self.ball[0]),
                        abs(self.pos[p][1] - self.ball[1]))
                 for p in (0, 1)}
        if self.owner < 0:
            close = [p for p in (0, 1) if dists[p] <= PICKUP]
            if len(close) == 1:
                self.owner = close[0]
            elif len(close) == 2 and dists[0] != dists[1]:
                self.owner = 0 if dists[0] < dists[1] else 1
        else:
            rival = 1 - self.owner
            if (dists[rival] <= PICKUP
                    and dists[rival] < dists[self.owner]):
                self.owner = rival
        # goal: the owner carries the ball over the attacked column
        if self.owner >= 0 \
                and self.ball[1] == GOAL_COL[self.owner]:
            scorer = self.owner
            self.score[scorer] += 1
            self.last_scores = {scorer: 1.0, 1 - scorer: -1.0}
            self.reset_positions()
        self.step_count += 1

    def reset_positions(self):
        self.pos = {0: [ROWS // 2, COLS // 4],
                    1: [ROWS // 2, 3 * COLS // 4]}
        self.ball = [ROWS // 2, COLS // 2]
        self.owner = -1

    # -- scoring ----------------------------------------------------
    def terminal(self):
        return self.step_count >= self.max_steps

    def reward(self):
        return dict(self.last_scores)

    def outcome(self):
        diff = self.score[0] - self.score[1]
        s = 0.0 if diff == 0 else (1.0 if diff > 0 else -1.0)
        return {0: s, 1: -s}

    # -- actions & players ------------------------------------------
    def legal_actions(self, player=None):
        return list(range(len(MOVES)))

    def players(self):
        return [0, 1]

    # -- scripted opponent ------------------------------------------
    def rule_based_action(self, player, key=None):
        """Chase the ball; with possession, run at the goal."""
        me = self.pos[player]
        target = ([me[0], GOAL_COL[player]]
                  if self.owner == player else self.ball)

        def sign(v):
            return 0 if v == 0 else (1 if v > 0 else -1)

        want = (sign(target[0] - me[0]), sign(target[1] - me[1]))
        for a, move in enumerate(MOVES):
            if move == want:
                return a
        return 0

    # -- neural-net interface ---------------------------------------
    def observation(self, player=None):
        """16 binary planes at GRF SMM geometry, channel-last and
        integer-valued (uint8 wire eligible): my/opp/ball position
        disks, possession flags, carried flag, goal columns, field
        halves, score-lead flags, and 4 binary-coded phase planes."""
        if player is None:
            player = 0
        me, opp = player, 1 - player
        planes = np.zeros((ROWS, COLS, PLANES), np.float32)

        def disk(plane, pos, r=1):
            r0, r1 = max(0, pos[0] - r), min(ROWS, pos[0] + r + 1)
            c0, c1 = max(0, pos[1] - r), min(COLS, pos[1] + r + 1)
            planes[r0:r1, c0:c1, plane] = 1.0

        disk(0, self.pos[me])
        disk(1, self.pos[opp])
        disk(2, self.ball)
        if self.owner == me:
            planes[:, :, 3] = 1.0
        elif self.owner == opp:
            planes[:, :, 4] = 1.0
        if self.owner >= 0:
            disk(5, self.pos[self.owner])
        planes[:, GOAL_COL[me], 6] = 1.0
        planes[:, GOAL_COL[opp], 7] = 1.0
        half = COLS // 2
        if GOAL_COL[me] == COLS - 1:
            planes[:, :half, 8] = 1.0
            planes[:, half:, 9] = 1.0
        else:
            planes[:, half:, 8] = 1.0
            planes[:, :half, 9] = 1.0
        if self.score[me] > self.score[opp]:
            planes[:, :, 10] = 1.0
        elif self.score[me] < self.score[opp]:
            planes[:, :, 11] = 1.0
        phase = (self.step_count * 16) // max(1, self.max_steps)
        for bit in range(4):
            if (phase >> bit) & 1:
                planes[:, :, 12 + bit] = 1.0
        return planes

    def net(self):
        from ..models.grf_net import GRFNet

        return GRFNet()

    # -- delta-sync protocol ----------------------------------------
    def diff_info(self, player=None):
        return {
            "pos": {p: list(v) for p, v in self.pos.items()},
            "ball": list(self.ball),
            "owner": self.owner,
            "score": list(self.score),
            "last": dict(self.last_scores),
            "step": self.step_count,
        }

    def update(self, info, reset):
        self.pos = {int(p): list(v) for p, v in info["pos"].items()}
        self.ball = list(info["ball"])
        self.owner = info["owner"]
        self.score = list(info["score"])
        self.last_scores = dict(info["last"])
        self.step_count = info["step"]

    def __str__(self):
        return (f"step {self.step_count} score {self.score} "
                f"ball {self.ball} owner {self.owner}")


if __name__ == "__main__":
    e = Environment({"max_steps": 200})
    while not e.terminal():
        e.step({0: e.rule_based_action(0),
                1: random.choice(e.legal_actions(1))})
    print(e, e.outcome())
