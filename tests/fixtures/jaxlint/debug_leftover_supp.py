"""Fixture: suppressed debug print (a sanctioned trace hook)."""

import jax


@jax.jit
def step(x):
    # jaxlint: disable=debug-leftover -- NaN tripwire, enabled by a debug config flag
    jax.debug.print("step input norm = {}", x.sum())
    return x * 2
