"""handyrl_tpu.pipeline — Sebulba-style pipelined rollout dataflow.

The actor/learner split, re-split (Podracer, arXiv:2104.06272): env
stepping stays in CPU worker processes, but inference for every worker
runs as ONE batched, jitted forward in the learner-side
:class:`~.service.InferenceService` (wait-or-timeout request batching,
snapshot hot-swap), and finished trajectories travel over the
zero-copy shared-memory transport of :mod:`.shm` instead of
bz2-pickle frames on the socket control plane — which keeps carrying
control verbs (jobs, model fetches, heartbeats, the ``"shm"``
handshake itself) only.

Public surface:

  * :class:`PipelineConfig` — validated ``pipeline.*`` config;
  * :class:`ShmRing` / :class:`ShmBoard` — the SPSC seqlock transport;
  * :class:`InferenceService` — the learner-side batched server;
  * :class:`PipelineClient` / :class:`ServedModel` /
    :func:`attach_pipeline` — the worker-side endpoint.
"""

from .config import PipelineConfig  # noqa: F401
from .shm import ShmBoard, ShmRing  # noqa: F401
from .service import InferenceService  # noqa: F401
from .client import (  # noqa: F401
    PipelineClient,
    ServedModel,
    attach_pipeline,
)
