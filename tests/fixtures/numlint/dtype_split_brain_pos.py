"""POS: one returned pytree mixes bf16 and fp32 leaves."""
import jax.numpy as jnp


def pack(x):
    return {"hidden": x.astype(jnp.bfloat16),
            "value": x.astype(jnp.float32)}
