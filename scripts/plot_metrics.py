"""Plot training curves from a learner stdout log (or metrics jsonl).

Role parity with /root/reference/scripts/win_rate_plot.py,
loss_plot.py and stats_plot.py, merged into one tool: the learner's
stdout format (``updated model(N)``, ``win rate ... = W (w / n)``,
``loss = k:v ...``, ``generation stats = m +- s``, ``epoch N``) is the
same public API the reference plot scripts parse, and the structured
``metrics_path`` jsonl is the TPU-native alternative.

Usage:
  python scripts/plot_metrics.py train.log [out_prefix]
  python scripts/plot_metrics.py metrics.jsonl [out_prefix]
"""

import json
import os
import sys


def parse_stdout_log(path):
    """Parse learner stdout into a list of per-epoch records."""
    epochs = []
    current = None
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line.startswith("epoch "):
                try:
                    current = {"epoch": int(line.split()[1])}
                except (IndexError, ValueError):
                    current = {"epoch": len(epochs)}
                epochs.append(current)
            elif current is None:
                continue
            elif line.startswith("win rate"):
                parts = line.split()
                name = "win_rate"
                if parts[2] != "=":
                    name += "_" + parts[2].strip("()")
                try:
                    games = int(parts[-1].strip("()"))
                    wp = float(parts[-4]) if games > 0 else 0.0
                    current[name] = wp
                    current[name + "_games"] = games
                except (IndexError, ValueError):
                    pass
            elif line.startswith("loss = "):
                for item in line[len("loss = "):].split():
                    k, _, v = item.partition(":")
                    try:
                        current["loss_" + k] = float(v)
                    except ValueError:
                        pass
            elif line.startswith("generation stats"):
                parts = line.split()
                try:
                    current["generation_mean"] = float(parts[3])
                    current["generation_std"] = float(parts[5])
                except (IndexError, ValueError):
                    pass
            elif line.startswith("updated"):
                try:
                    current["steps"] = int(
                        line.split("(")[1].rstrip().rstrip(")"))
                except (IndexError, ValueError):
                    pass
    return epochs


RAW_LOSS_KEYS = ("p", "v", "r", "ent", "total")


def parse_jsonl(path):
    """Load metrics jsonl, normalizing the learner's raw per-epoch loss
    keys (p/v/r/ent/total) to the loss_ prefix the plots expect."""
    epochs = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            for k in RAW_LOSS_KEYS:
                if k in rec:
                    rec["loss_" + k] = rec.pop(k)
            epochs.append(rec)
    return epochs


def moving_average(xs, n):
    if n <= 1 or len(xs) < n:
        return xs
    out = []
    for i in range(len(xs)):
        lo, hi = max(0, i - n // 2), min(len(xs), i + n // 2 + 1)
        out.append(sum(xs[lo:hi]) / (hi - lo))
    return out


def series(xs, epochs, key):
    """(x, y) points for one metric, skipping records that lack the
    key — older metrics.jsonl files predate newer metric keys and must
    still plot instead of raising KeyError."""
    return [(x, e[key]) for x, e in zip(xs, epochs)
            if key in e and e[key] is not None]


def plot(epochs, out_prefix):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs = [e.get("epoch", i) for i, e in enumerate(epochs)]

    # win rates (every win_rate* series)
    wr_keys = sorted({
        k for e in epochs for k in e
        if k.startswith("win_rate") and not k.endswith("_games")})
    if wr_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in wr_keys:
            ys = [e.get(k) for e in epochs]
            pts = [(x, y) for x, y in zip(xs, ys) if y is not None]
            if pts:
                ax.plot(*zip(*pts), label=k, alpha=0.35)
                ax.plot(
                    [p[0] for p in pts],
                    moving_average([p[1] for p in pts], 9),
                    label=k + " (avg)")
        ax.set_xlabel("epoch")
        ax.set_ylabel("win rate")
        ax.set_ylim(0, 1)
        ax.legend()
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_win_rate.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_win_rate.png")

    # loss components
    loss_keys = sorted({
        k for e in epochs for k in e if k.startswith("loss_")})
    if loss_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in loss_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k)
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss / data count")
        ax.legend()
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_loss.png", dpi=120, bbox_inches="tight")
        print(f"wrote {out_prefix}_loss.png")

    # guard counters (analysis.guards via the metrics jsonl):
    # retrace_count is cumulative and must stay FLAT after epoch 1;
    # host_transfers is the per-epoch delta and must not grow with the
    # step count — a rising line on either is a hot-path regression.
    # The resource-ledger populations ride here too: fd/thread/shm
    # counts must PLATEAU after bring-up — a staircase is a per-epoch
    # leak compounding
    guard_keys = [k for k in ("retrace_count", "host_transfers",
                              "resharding_copies", "stall_events",
                              "lock_contention_sec",
                              "lock_order_inversions",
                              "nonfinite_steps",
                              "numerics_contract_breaks",
                              "weak_upcasts",
                              "fd_count", "thread_count",
                              "shm_segments", "resource_growth")
                  if any(k in e for e in epochs)]
    if guard_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in guard_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("count")
        ax.legend()
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_guards.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_guards.png")

    # fleet health (resilience.FleetRegistry via the metrics jsonl):
    # fleet_size should sit flat at the configured gather count —
    # dips are crashes, and matching respawn increments mean the
    # supervisor brought the fleet back; a climbing heartbeat_misses
    # or conn_drops line means gathers are wedging or dying faster
    # than they respawn
    fleet_keys = [k for k in ("fleet_size", "fleet_workers", "respawns",
                              "heartbeat_misses", "conn_drops",
                              "unknown_verbs")
                  if any(k in e for e in epochs)]
    if fleet_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in fleet_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("count")
        ax.legend()
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_fleet.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_fleet.png")

    # pipeline telemetry (handyrl_tpu.telemetry via the metrics jsonl):
    # policy_lag_* is the off-policy staleness of the consumed episodes
    # (an IMPALA learner's central health signal — a climbing lag means
    # the actors cannot keep up with the update rate); batch_wait_sec
    # vs device_step_sec splits each epoch's wall time into feed
    # starvation vs device work, and queue_depth is the feed backlog at
    # the epoch boundary
    lag_keys = [k for k in ("policy_lag_mean", "policy_lag_p95",
                            "policy_lag_max", "queue_depth")
                if any(k in e for e in epochs)]
    sec_keys = [k for k in ("batch_wait_sec", "device_step_sec",
                            "epoch_wall_sec")
                if any(k in e for e in epochs)]
    if lag_keys or sec_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in lag_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("episodes (lag) / batches (depth)")
        ax2 = ax.twinx()
        for k in sec_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax2.plot(*zip(*pts), label=k, linestyle="--")
        ax2.set_ylabel("seconds per epoch")
        lines, labels = ax.get_legend_handles_labels()
        lines2, labels2 = ax2.get_legend_handles_labels()
        ax.legend(lines + lines2, labels + labels2, fontsize=8)
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_pipeline.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_pipeline.png")

    # off-policy robustness (IMPACT / lag-aware intake via the metrics
    # jsonl): episodes_rejected_stale counts arrivals the staleness
    # budget dropped, target_net_age is steps since the target net
    # last synced (or the Polyak horizon), and is_clip_frac (right
    # axis, a fraction) is how often the importance-ratio clip engaged
    # — rising together with policy_lag_p95 means the learner is
    # actually absorbing stale data rather than silently training on it
    off_cnt_keys = [k for k in ("episodes_rejected_stale",
                                "target_net_age", "policy_lag_p95")
                    if any(k in e for e in epochs)]
    off_frac_keys = [k for k in ("is_clip_frac",)
                     if any(k in e for e in epochs)]
    if off_cnt_keys or off_frac_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in off_cnt_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("episodes (rejected/lag) / steps (age)")
        ax2 = ax.twinx()
        for k in off_frac_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax2.plot(*zip(*pts), label=k, linestyle="--")
        ax2.set_ylabel("clipped-IS fraction")
        ax2.set_ylim(0, 1)
        lines, labels = ax.get_legend_handles_labels()
        lines2, labels2 = ax2.get_legend_handles_labels()
        ax.legend(lines + lines2, labels + labels2, fontsize=8)
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_offpolicy.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_offpolicy.png")

    # pipelined inference (handyrl_tpu.pipeline via the metrics jsonl):
    # infer_batch_size_{mean,p95} shows how well the batching window
    # coalesces requests across workers (pinned at one worker's rows =
    # the window never spans processes), shm_ring_full_count is the
    # transport's backpressure (climbing = rings undersized, episodes
    # spilling to the control plane), and infer_queue_wait_sec (right
    # axis) is what the window costs in latency.  The brownout /
    # degradation triple rides the same panel: episodes_shm vs
    # episodes_spilled splits each epoch's intake between the ring
    # and the control-plane spill (a surge hold shows as a spill
    # burst, never a dip in their sum), upload_backlog is the deepest
    # worker-side hold backlog observed, and shm_torn_slots counts
    # slots reclaimed from producers that died mid-write (flat at 0
    # outside churn).  The GSPMD dispatch guard pair rides here too:
    # infer_resharding_copies must stay flat at 0 (a climb = snapshots
    # landing on the wrong layout, one silent copy per dispatch) and
    # infer_compiles must plateau at the bucket-geometry count (a
    # climb = snapshots recompiling the forward).  All render through
    # series(), so pre-PR-11 metrics files still plot
    inf_cnt_keys = [k for k in ("infer_batch_size_mean",
                                "infer_batch_size_p95",
                                "infer_batches",
                                "shm_ring_full_count",
                                "shm_torn_slots",
                                "episodes_shm",
                                "episodes_spilled",
                                "upload_backlog",
                                "infer_respawns",
                                "infer_resharding_copies",
                                "infer_compiles")
                    if any(k in e for e in epochs)]
    inf_sec_keys = [k for k in ("infer_queue_wait_sec",)
                    if any(k in e for e in epochs)]
    if inf_cnt_keys or inf_sec_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in inf_cnt_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("rows (batch size) / count")
        ax2 = ax.twinx()
        for k in inf_sec_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax2.plot(*zip(*pts), label=k, linestyle="--")
        ax2.set_ylabel("window wait, seconds")
        lines, labels = ax.get_legend_handles_labels()
        lines2, labels2 = ax2.get_legend_handles_labels()
        ax.legend(lines + lines2, labels + labels2, fontsize=8)
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_inference.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_inference.png")

    # anakin throughput (handyrl_tpu.anakin via the metrics jsonl):
    # anakin_frames_per_sec / anakin_games_per_sec are the fused
    # on-device rollout's production rate — the raw-speed number the
    # architecture exists to move; a dip means the fused step slowed
    # (retrace/reshard regressions show on the guards plot) or the
    # epoch boundary stretched.  steps ride the right axis so the
    # update cadence is visible next to the frame rate
    ank_rate_keys = [k for k in ("anakin_frames_per_sec",
                                 "anakin_games_per_sec")
                     if any(k in e for e in epochs)]
    ank_cnt_keys = [k for k in ("anakin_frames",)
                    if any(k in e for e in epochs)]
    if ank_rate_keys or ank_cnt_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in ank_rate_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("frames / games per second")
        ax2 = ax.twinx()
        for k in ank_cnt_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax2.plot(*zip(*pts), label=k, linestyle="--")
        ax2.set_ylabel("frames per epoch")
        lines, labels = ax.get_legend_handles_labels()
        lines2, labels2 = ax2.get_legend_handles_labels()
        ax.legend(lines + lines2, labels + labels2, fontsize=8)
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_anakin.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_anakin.png")

    # serving tier (handyrl_tpu.serving via the metrics jsonl): the
    # request/shed/error counts show admission control working (sheds
    # are typed replies — a shed burst with flat errors is the SLO
    # doing its job; climbing errors mean timeouts or unroutable
    # pins), and the latency percentiles ride the right axis in ms.
    # All render through series(), so pre-serving metrics files plot
    srv_cnt_keys = [k for k in ("serve_requests", "serve_ok",
                                "serve_shed", "serve_errors",
                                "serve_qps", "serve_respawns")
                    if any(k in e for e in epochs)]
    srv_ms_keys = [k for k in ("serve_p50_ms", "serve_p99_ms")
                   if any(k in e for e in epochs)]
    if srv_cnt_keys or srv_ms_keys:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in srv_cnt_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("requests / outcomes / QPS")
        ax2 = ax.twinx()
        for k in srv_ms_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax2.plot(*zip(*pts), label=k, linestyle="--")
        ax2.set_ylabel("latency, ms")
        lines, labels = ax.get_legend_handles_labels()
        lines2, labels2 = ax2.get_legend_handles_labels()
        ax.legend(lines + lines2, labels + labels2, fontsize=8)
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_serving.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_serving.png")

    # pool router (PR 18): pool membership on the right axis against
    # the routed-request counters — an eviction shows as a pool_size
    # drop with a reroute burst, a whole-pool breach as pool_sheds.
    # Same series() skip-absent discipline: pre-router files plot
    rtr_cnt_keys = [k for k in ("router_requests", "router_ok",
                                "router_shed", "router_errors",
                                "reroutes", "pool_sheds",
                                "router_respawns")
                    if any(k in e for e in epochs)]
    rtr_pool_key = ("router_pool_size"
                    if any("router_pool_size" in e for e in epochs)
                    else None)
    if rtr_cnt_keys or rtr_pool_key:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in rtr_cnt_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("requests / outcomes")
        ax2 = ax.twinx()
        if rtr_pool_key:
            pts = series(xs, epochs, rtr_pool_key)
            if pts:
                ax2.plot(*zip(*pts), label=rtr_pool_key,
                         linestyle="--")
        ax2.set_ylabel("routable replicas")
        lines, labels = ax.get_legend_handles_labels()
        lines2, labels2 = ax2.get_legend_handles_labels()
        ax.legend(lines + lines2, labels + labels2, fontsize=8)
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_router.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_router.png")

    # perf attribution (telemetry.costmodel/.attribution via the
    # metrics jsonl): mfu and achieved_tflops are the roofline
    # accounting — flat-and-low with a memory-bound verdict means the
    # batch/fusion shape caps throughput, not scheduling; the right
    # axis shows each epoch's wall decomposed into the batch-wait and
    # untracked-residual SHARES (fractions of epoch_wall_sec), so a
    # perf regression shows as one of the shares growing.  mfu is None
    # on hosts with no peak table row and no perf.* override — the
    # series() skip keeps those files plotting
    perf_abs_keys = [k for k in ("mfu", "achieved_tflops")
                     if any(e.get(k) is not None for e in epochs)]
    perf_share_pairs = [
        ("batch_wait_sec", "batch_wait share"),
        ("untracked_residual_sec", "residual share"),
    ]
    have_shares = any(
        e.get(k) is not None and (e.get("epoch_wall_sec") or 0) > 0
        for e in epochs for k, _ in perf_share_pairs)
    if perf_abs_keys or have_shares:
        fig, ax = plt.subplots(figsize=(8, 5))
        for k in perf_abs_keys:
            pts = series(xs, epochs, k)
            if pts:
                ax.plot(*zip(*pts), label=k, marker=".")
        ax.set_xlabel("epoch")
        ax.set_ylabel("MFU (fraction) / achieved TFLOP/s")
        ax2 = ax.twinx()
        for k, label in perf_share_pairs:
            pts = [(x, e[k] / e["epoch_wall_sec"])
                   for x, e in zip(xs, epochs)
                   if e.get(k) is not None
                   and (e.get("epoch_wall_sec") or 0) > 0]
            if pts:
                ax2.plot(*zip(*pts), label=label, linestyle="--")
        ax2.set_ylabel("share of epoch wall time")
        ax2.set_ylim(bottom=0)
        lines, labels = ax.get_legend_handles_labels()
        lines2, labels2 = ax2.get_legend_handles_labels()
        ax.legend(lines + lines2, labels + labels2, fontsize=8)
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_perf.png", dpi=120,
                    bbox_inches="tight")
        print(f"wrote {out_prefix}_perf.png")

    # generation stats (mean +- std band)
    pts = [(x, e["generation_mean"], e.get("generation_std", 0.0))
           for x, e in zip(xs, epochs) if "generation_mean" in e]
    if pts:
        fig, ax = plt.subplots(figsize=(8, 5))
        gx, gm, gs = zip(*pts)
        ax.plot(gx, gm, label="generation outcome mean")
        ax.fill_between(
            gx,
            [m - s for m, s in zip(gm, gs)],
            [m + s for m, s in zip(gm, gs)],
            alpha=0.2)
        ax.set_xlabel("epoch")
        ax.set_ylabel("self-play outcome")
        ax.legend()
        ax.grid(alpha=0.3)
        fig.savefig(out_prefix + "_stats.png", dpi=120, bbox_inches="tight")
        print(f"wrote {out_prefix}_stats.png")


def main():
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(1)
    path = sys.argv[1]
    out_prefix = sys.argv[2] if len(sys.argv) > 2 else (
        os.path.splitext(path)[0])

    if path.endswith(".jsonl"):
        epochs = parse_jsonl(path)
    else:
        epochs = parse_stdout_log(path)
    if not epochs:
        print("no epochs found in log")
        sys.exit(1)
    print(f"parsed {len(epochs)} epochs")
    plot(epochs, out_prefix)


if __name__ == "__main__":
    main()
