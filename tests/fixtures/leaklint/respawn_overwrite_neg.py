"""Negative: every re-store of a resource attribute is disciplined —
an ``is None`` guard, a prior release / ``= None`` / teardown
self-call in the same function, or the entry-guard idiom where every
in-package caller checks first (the WAL append -> _open_segment
shape)."""

import socket


class Frontend:
    def __init__(self):
        self._listener = None

    def ensure(self):
        if self._listener is not None:
            return
        self._listener = socket.create_server(("", 9999))

    def respawn(self):
        self.teardown()
        self._listener = socket.create_server(("", 9999))

    def teardown(self):
        listener, self._listener = self._listener, None
        if listener is not None:
            listener.close()


class Wal:
    def __init__(self, path):
        self._path = path
        self._f = None

    def _open_segment(self):
        self._f = open(self._path, "ab")

    def append(self, rec):
        # the entry guard: the only caller checks liveness first
        if self._f is None:
            self._open_segment()
        self._f.write(rec)
