"""jaxlint rule suite: every rule fires on its positive fixture, stays
quiet on its negative, and obeys suppression comments — plus the CI
gate itself (the whole package must lint clean).

Fixture convention (tests/fixtures/jaxlint/): ``<rule>_pos.py`` must
produce findings of exactly that rule, ``<rule>_neg.py`` and
``<rule>_supp.py`` must produce none (driver shared with the shard/
comm suites: tests/lintfix.py).  The fixtures are parsed, never
imported."""

import json
import os

import pytest
from lintfix import check_fixture, fixture_path

from handyrl_tpu.analysis.jaxlint import lint_paths, lint_source, main
from handyrl_tpu.analysis.rules import RULES

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "jaxlint")
REPO_PACKAGE = os.path.join(
    os.path.dirname(__file__), "..", "handyrl_tpu")

RULE_IDS = sorted(RULES)


def fixture(rule_id, kind):
    return fixture_path("jaxlint", rule_id, kind)


@pytest.mark.parametrize("kind", ["pos", "neg", "supp"])
@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_fixture(rule_id, kind):
    check_fixture("jaxlint", rule_id, kind)


def test_every_positive_names_real_rules():
    # the parametrized fixtures above cover exactly the registry
    assert set(RULE_IDS) == {
        "prng-reuse", "tracer-branch", "host-sync", "donated-reuse",
        "retrace-risk", "debug-leftover"}


# -- suppression machinery -------------------------------------------

def test_bare_suppression_is_itself_reported():
    src = (
        "import jax\n"
        "def f(x):\n"
        "    jax.debug.print('{}', x)  # jaxlint: disable=debug-leftover\n"
        "    return x\n")
    findings = lint_source(src)
    assert [f.rule for f in findings] == ["bare-suppression"]


def test_suppression_on_previous_comment_line():
    src = (
        "import jax\n"
        "def f(x):\n"
        "    # jaxlint: disable=debug-leftover -- demo hook\n"
        "    jax.debug.print('{}', x)\n"
        "    return x\n")
    assert lint_source(src) == []


def test_trailing_code_does_not_extend_suppression_down():
    # a same-line suppression must not silence the NEXT line
    src = (
        "import jax\n"
        "def f(x):\n"
        "    y = 1  # jaxlint: disable=debug-leftover -- only this line\n"
        "    jax.debug.print('{}', x)\n"
        "    return x + y\n")
    findings = lint_source(src)
    assert [f.rule for f in findings] == ["debug-leftover"]


def test_skip_file():
    src = (
        "# jaxlint: skip-file -- generated\n"
        "import jax\n"
        "def f(x):\n"
        "    jax.debug.print('{}', x)\n"
        "    return x\n")
    assert lint_source(src) == []


def test_docstrings_mentioning_syntax_are_not_suppressions():
    # only real comment tokens count: documentation of the suppression
    # syntax inside a string/docstring must neither suppress nor be
    # reported as a bare suppression
    src = (
        '"""Suppress with ``# jaxlint: disable=debug-leftover`` inline,\n'
        'or skip a file with ``# jaxlint: skip-file`` up top."""\n'
        "import jax\n"
        "def f(x):\n"
        "    jax.debug.print('{}', x)\n"
        "    return x\n")
    findings = lint_source(src)
    assert [f.rule for f in findings] == ["debug-leftover"]


def test_bare_skip_file_is_not_a_silent_bypass():
    # a reason-less skip-file still skips the rules, but the bare
    # suppression itself surfaces (and fails the CI gate)
    src = (
        "# jaxlint: skip-file\n"
        "import jax\n"
        "def f(x):\n"
        "    jax.debug.print('{}', x)\n"
        "    return x\n")
    findings = lint_source(src)
    assert [f.rule for f in findings] == ["bare-suppression"]


def test_learner_metric_fix_regression():
    """The exact pattern fixed in learner.train(): per-step float() on
    device metrics flags; the single jax.device_get fetch does not."""
    broken = (
        "import jax\n"
        "class Trainer:\n"
        "    def __init__(self):\n"
        "        self.update_step = jax.jit(lambda p, b: (p, {'d': b}))\n"
        "    def train(self, params, batches):\n"
        "        acc = []\n"
        "        for b in batches:\n"
        "            params, m = self.update_step(params, b)\n"
        "            acc.append(m)\n"
        "        return sum(float(m['d']) for m in acc)\n")
    fixed = broken.replace(
        "        return sum(float(m['d']) for m in acc)\n",
        "        acc = jax.device_get(acc)\n"
        "        return sum(float(m['d']) for m in acc)\n")
    assert any(f.rule == "host-sync" for f in lint_source(broken))
    assert lint_source(fixed) == []


# -- CLI + CI gate ----------------------------------------------------

def test_cli_json_output(capsys):
    rc = main(["--json", fixture("debug-leftover", "pos")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["total"] == len(out["findings"]) > 0
    assert all(f["rule"] == "debug-leftover" for f in out["findings"])


def test_cli_clean_exit(capsys):
    rc = main([fixture("debug-leftover", "neg")])
    assert rc == 0
    assert "clean" in capsys.readouterr().out


def test_cli_list_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in RULE_IDS:
        assert rule_id in out


def test_cli_unknown_rule(capsys):
    assert main(["--select", "no-such-rule", FIXTURES]) == 2


def test_repo_lints_clean():
    """The CI gate, enforced locally too: the shipped package must have
    zero unsuppressed findings."""
    findings = lint_paths([REPO_PACKAGE])
    assert findings == [], "\n".join(
        f"{f.location}: [{f.rule}] {f.message}" for f in findings)
