"""Fixture: suppressed implicit-reshard (a one-time re-layout at
startup, not on the hot path)."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), ("dp", "tp"))


def restore_step(mesh, params, batch):
    rep = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("dp"))
    step = jax.jit(lambda p, b: (p, b.sum()), in_shardings=(rep, dp),
                   donate_argnums=(0,))
    params = jax.device_put(params, dp)
    # jaxlint: disable=implicit-reshard -- one-time checkpoint restore; the copy is off the hot path
    return step(params, batch)
