"""Positive: a verb is handled but nothing in the package sends it."""


def client(conn):
    conn.send(("ping", 1))


def server(hub):
    while True:
        conn, (verb, payload) = hub.recv(timeout=0.3)
        if verb == "ping":
            hub.send(conn, payload)
        elif verb == "stats":   # nothing sends "stats" -> dead-handler
            hub.send(conn, {})
