"""PR 19 smoke drive: two-epoch TicTacToe train with the resource
ledger armed, recorded under runs/pr19_leaklint_smoke/.

Asserts the acceptance line directly: fd_count/thread_count in EVERY
metrics record, growth within budget, and the fd/thread population
PLATEAUED between the first and last epoch.  Then the status snapshot
(with its `resources` section) lands in status.json; render the plots
with scripts/plot_metrics.py (the resource series ride *_guards.png).
"""

import json
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
HERE = os.path.dirname(os.path.abspath(__file__))

args = {
    "env_args": {"env": "TicTacToe"},
    "train_args": {
        "turn_based_training": True,
        "observation": False,
        "gamma": 0.8,
        "forward_steps": 4,
        "burn_in_steps": 0,
        "compress_steps": 4,
        "entropy_regularization": 0.1,
        "entropy_regularization_decay": 0.1,
        "update_episodes": 15,
        "batch_size": 4,
        "minimum_episodes": 10,
        "maximum_episodes": 200,
        "epochs": 2,
        "num_batchers": 1,
        "eval_rate": 0.1,
        "worker": {"num_parallel": 2},
        "lambda": 0.7,
        "policy_target": "VTRACE",
        "value_target": "VTRACE",
        "seed": 1,
        "resource_ledger": True,
        "max_fd_growth": 64,   # armed for real: raises past budget
        "metrics_path": "metrics.jsonl",
    },
    "worker_args": {"num_parallel": 2, "server_address": ""},
}


def main():
    os.chdir(HERE)

    from handyrl_tpu.learner import Learner

    learner = Learner(args)
    learner.run()
    assert learner.model_epoch == 2

    with open("status.json", "w") as f:
        json.dump(learner._status_snapshot(), f, indent=2,
                  sort_keys=True)

    with open("metrics.jsonl") as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert len(records) == 2, records
    for r in records:
        assert r["fd_count"] > 0, r
        assert r["thread_count"] >= 1, r
        assert r["shm_segments"] >= 0, r
        assert 0 <= r["resource_growth"] <= 64, r
    first, last = records[0], records[-1]
    assert last["fd_count"] - first["fd_count"] <= 4, (first, last)
    assert last["thread_count"] - first["thread_count"] <= 2, (
        first, last)

    print("smoke OK:",
          {k: [r[k] for r in records]
           for k in ("fd_count", "thread_count", "shm_segments",
                     "resource_growth")})


if __name__ == "__main__":
    main()
