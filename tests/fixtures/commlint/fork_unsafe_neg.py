"""Negative: spawn contexts are always safe (fresh interpreter), and a
default-context Process BEFORE any threads exist is fine too."""

import multiprocessing as mp
import threading

_mp = mp.get_context("spawn")


def spawn_after_threads(target):
    t = threading.Thread(target=target, daemon=True)
    t.start()
    proc = _mp.Process(target=target)    # spawn context: safe
    proc.start()
    return proc


def process_before_threads(target):
    proc = mp.Process(target=target)     # no threads exist yet
    proc.start()
    t = threading.Thread(target=target, daemon=True)
    t.start()
    return proc


def inline_spawn(target):
    proc = mp.get_context("spawn").Process(target=target)
    proc.start()
    return proc
