"""Fixture: a reduction over an axis the enclosing shard_map never
shards, and a collective with no axis-binding transform at all."""

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXES = ("dp", "tp")


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), AXES)


def grad_sum(g):
    # the shard_map below only shards dp: psum over tp multiplies
    # replicated values by the tp axis size
    return jax.lax.psum(g, "tp")


def make_step(mesh):
    return shard_map(grad_sum, mesh=mesh, in_specs=P("dp"),
                     out_specs=P("dp"))


def stray_mean(x):
    # nothing binds dp here: unbound axis name at trace time
    return jax.lax.pmean(x, "dp")
