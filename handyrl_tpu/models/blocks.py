"""Shared Flax building blocks for policy-value nets.

Conventions (TPU-first):
  * all convs are NHWC (channel-last) — the natural Flax/XLA layout;
  * normalization is GroupNorm, not BatchNorm: it is state-free, so the
    jitted update step needs no mutable batch-stats collection and the
    burn-in steps of RNN replay behave identically to training steps.
    (The reference nets use BatchNorm with train/eval mode switching,
    e.g. /root/reference/handyrl/envs/tictactoe.py:26 — numerics differ
    slightly, semantics do not.)
"""

from typing import Any, Callable, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn


def pick_num_groups(channels: int, target: int = 8) -> int:
    """Largest divisor of ``channels`` that is <= ``target``."""
    for g in range(min(target, channels), 0, -1):
        if channels % g == 0:
            return g
    return 1


class ConvBlock(nn.Module):
    """3x3 conv -> GroupNorm -> ReLU."""

    filters: int
    kernel: int = 3
    use_norm: bool = True

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.filters, (self.kernel, self.kernel),
                    padding="SAME", use_bias=not self.use_norm)(x)
        if self.use_norm:
            x = nn.GroupNorm(num_groups=pick_num_groups(self.filters))(x)
        return nn.relu(x)


class PolicyHead(nn.Module):
    """1x1 conv bottleneck -> flatten -> dense logits.

    Same shape contract as the reference's ``Head``
    (/root/reference/handyrl/envs/tictactoe.py:35-46).
    """

    bottleneck: int
    num_actions: int

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.bottleneck, (1, 1))(x)
        h = nn.leaky_relu(h, negative_slope=0.1)
        h = h.reshape((h.shape[0], -1))
        return nn.Dense(self.num_actions, use_bias=False)(h)


class ValueHead(nn.Module):
    """1x1 conv bottleneck -> flatten -> dense scalar (optionally tanh)."""

    bottleneck: int
    outputs: int = 1
    squash: bool = True

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.bottleneck, (1, 1))(x)
        h = nn.leaky_relu(h, negative_slope=0.1)
        h = h.reshape((h.shape[0], -1))
        h = nn.Dense(self.outputs, use_bias=False)(h)
        return jnp.tanh(h) if self.squash else h
