"""Suppressed: a creator that intentionally leaves the segment for a
successor process, explained."""

from multiprocessing import shared_memory


class Board:
    def __init__(self, size):
        self._seg = shared_memory.SharedMemory(create=True, size=size)  # jaxlint: disable=unlinked-shm -- segment is handed off across respawns; the supervisor unlinks it at fleet teardown

    def close(self):
        self._seg.close()
