"""Negative: the iteration happens on a snapshot taken under the same
lock the mutator holds."""

import threading


class Board:
    def __init__(self):
        self._lock = threading.Lock()
        self.scores = {}

    def start(self):
        threading.Thread(target=self._ingest, daemon=True).start()

    def _ingest(self):
        while True:
            with self._lock:
                self.scores["game"] = 1

    def totals(self):
        with self._lock:
            snapshot = list(self.scores.values())
        return sum(snapshot)
