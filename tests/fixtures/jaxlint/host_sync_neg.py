"""Fixture: device metrics accumulated on device, synced ONCE per
epoch — the fixed learner pattern."""

import jax
import numpy as np


def make_step():
    return jax.jit(lambda p, b: (p, {"loss": b.sum()}))


def epoch(params, batches):
    step = make_step()
    metrics = []
    for batch in batches:
        params, m = step(params, batch)
        metrics.append(m)  # device values stay on device
    metrics = jax.device_get(metrics)  # ONE transfer for the epoch
    total = sum(float(m["loss"]) for m in metrics)
    return params, total


def host_loop(rows):
    # float()/np.asarray on plain host data in loops is fine
    return [float(np.asarray(r).mean()) for r in rows]
