"""leaklint's rule registry: six resource-lifecycle rules.

Same shape as :mod:`.rules` / :mod:`.shardrules` / :mod:`.commrules` /
:mod:`.racerules` / :mod:`.numrules` — each rule is ``(Package,
ModuleInfo) -> Iterable[Finding]`` under a stable kebab-case id (what
suppression comments name), registered in ``LEAK_RULES`` and consuming
the acquisition facts, ownership lattice, and attribute-lifecycle
tables of :mod:`.leaklint`.  None of them import jax (or open a file).

The rules, and the slow death each one prevents:

  ``unreleased-resource``  a function-local socket/file/shm/process
                           reaches some exit (a return, or the end of
                           the function) still live -> one fd or shm
                           segment per call, forever; the accept-loop
                           server socket nobody closes.
  ``leak-on-error``        a release exists on the happy path, but a
                           risky call between acquire and release can
                           raise and skip it (no ``finally``, no
                           ``with``) -> the leak only fires under
                           error load, exactly when you can least
                           afford it.
  ``respawn-overwrite``    ``self.X = <fresh resource>`` while the
                           previous incarnation may still be live —
                           no ``is None`` guard, no prior release or
                           ``= None``, and no caller-side entry guard
                           -> the old socket/ring lives unreferenced
                           until process exit; the PR 13
                           ``frontend.respawn()`` bug class.
  ``unjoined-thread``      a non-daemon thread is spawned and no
                           shutdown path ever joins it -> interpreter
                           exit blocks forever on a worker the owner
                           forgot about.
  ``unlinked-shm``         a shared-memory CREATOR closes its mapping
                           but never unlinks the segment -> the ~66 MB
                           /dev/shm file outlives the process; the PR
                           9 dead-worker bug class.
  ``double-release``       two unconditional releases of one
                           obligation -> the second ``close()``
                           hits a recycled fd or raises mid-teardown
                           and masks the real shutdown error.

Ownership transfer keeps the rules quiet where the fleet is correct:
a resource that is returned, yielded, stored on ``self`` or in a
container, or passed to another call has a NEW owner who inherits the
close obligation — ``ShmRing.create()`` handing its raw segment to the
ring, ``_spawn_gather()`` returning the child process into a
Supervisor slot.  ``daemon=True`` threads/processes carry no join
obligation (the ``_stop``-flag shutdown idiom racelint audits), and
``with``/``contextlib.closing`` discharge everything in scope.
Intentional process-lifetime resources suppress per line with
``# jaxlint: disable=<rule> -- reason``.
"""

from typing import Dict

from .astutil import ModuleInfo, Package
from .leaklint import (
    LeakAnalysis,
    _human_kind,
    _in_ctor,
    analyze_leaks,
)
from .rules import Finding, Rule

LEAK_RULES: Dict[str, Rule] = {}


def leak_rule(rule_id: str, summary: str):
    def deco(fn):
        LEAK_RULES[rule_id] = Rule(rule_id, summary, fn.__doc__ or "",
                                   fn)
        return fn
    return deco


def _loc(node):
    return node.lineno, getattr(node, "col_offset", 0)


def _local_obligated(an: LeakAnalysis, mod: ModuleInfo):
    """Named function-local acquisitions in this module that still own
    their close obligation (not escaped, not with-managed, not
    fire-and-forget daemons) — threads excluded, they belong to
    ``unjoined-thread``."""
    for acq in an.acqs:
        if acq.fn.module is not mod:
            continue
        if acq.kind == "thread" or acq.daemon:
            continue
        if acq.name is None or acq.via_with or acq.escaped:
            continue
        yield acq


@leak_rule("unreleased-resource",
           "function-local resource reaches an exit without a release")
def check_unreleased_resource(package: Package, mod: ModuleInfo):
    """A local socket / file / process / shm handle is acquired and
    some path out of the function — a ``return``, or falling off the
    end — leaves it live: either no release call exists at all, or an
    early return sidesteps the one that does.  Whoever calls this
    function cannot close what was never handed to them, so the fd is
    simply gone.  Escapes (returned, stored, passed on) transfer the
    obligation and stay quiet; ``with`` discharges it in-scope."""
    an = analyze_leaks(package)
    for acq in _local_obligated(an, mod):
        if not acq.leak_exits:
            continue
        line, col = _loc(acq.node)
        exits = ", ".join(str(l) for l in sorted(set(acq.leak_exits)))
        what = _human_kind(acq.kind)
        if acq.releases:
            detail = (f"the release at line "
                      f"{min(r.line for r in acq.releases)} is "
                      f"bypassed by the exit at line {exits}")
        else:
            detail = (f"no release call exists on any path "
                      f"(exits at line {exits})")
        yield Finding(
            "unreleased-resource", mod.path, line, col,
            f"local {what} `{acq.name}` is still live when the "
            f"function exits — {detail}; close it on every path "
            f"(`with`/`finally`) or transfer it to an owner")


@leak_rule("leak-on-error",
           "release exists but an exception between acquire and "
           "release skips it")
def check_leak_on_error(package: Package, mod: ModuleInfo):
    """Every normal exit releases the resource, but between the
    acquisition and the first release some other call runs — and if it
    raises, the exception propagates past the release and the handle
    leaks.  ``find_free_port()``-style helpers fail exactly under fd
    pressure, when ``bind()`` starts raising — the moment the leak
    compounds fastest.  A release inside ``finally`` (or an except
    handler), or a ``with`` block, is exception-safe and quiet."""
    an = analyze_leaks(package)
    for acq in _local_obligated(an, mod):
        if not acq.releases or acq.leak_exits:
            continue
        if any(r.in_finally or r.in_handler for r in acq.releases):
            continue
        if not acq.risky:
            continue
        first = min(r.line for r in acq.releases)
        line, col = _loc(acq.node)
        yield Finding(
            "leak-on-error", mod.path, line, col,
            f"local {_human_kind(acq.kind)} `{acq.name}` is released "
            f"at line {first}, but a call before that release can "
            f"raise and skip it — move the release into `finally` or "
            f"use `with`")


@leak_rule("respawn-overwrite",
           "attribute holding a live resource reassigned without "
           "closing the old one")
def check_respawn_overwrite(package: Package, mod: ModuleInfo):
    """``self.X = <fresh resource>`` outside ``__init__`` where the
    previous incarnation may still be live: no ``self.X is None``
    guard, no release / ``= None`` / teardown self-call earlier in the
    function, and no entry-guard discipline (every in-package caller
    checking first — the WAL ``append() -> _open_segment()`` shape).
    The old socket or ring keeps its fd until process exit with no
    reference left to close it — the exact bug the PR 13
    ``frontend.respawn()`` fix patched by hand.  Daemon threads are
    exempt (dropping the handle is their shutdown idiom)."""
    an = analyze_leaks(package)
    for (cls, attr), stores in sorted(an.attr_stores.items()):
        for st in stores:
            if st.fn.module is not mod:
                continue
            if st.guarded or st.daemon:
                continue
            line, col = _loc(st.node)
            yield Finding(
                "respawn-overwrite", mod.path, line, col,
                f"`self.{attr}` is reassigned a fresh "
                f"{_human_kind(st.kind)} in `{cls}` while the previous "
                f"incarnation may still be live — release or `None` "
                f"it first, or guard with `if self.{attr} is None`")


@leak_rule("unjoined-thread",
           "non-daemon thread spawned and never joined on any "
           "shutdown path")
def check_unjoined_thread(package: Package, mod: ModuleInfo):
    """A ``threading.Thread`` without ``daemon=True`` is started and
    no path ever joins it: a local handle that is dropped un-joined
    and un-escaped, or a ``self.X`` store whose class has no
    ``self.X.join()`` on any shutdown path.  Interpreter exit then
    blocks in threading's shutdown handler waiting on a worker nobody
    owns.  Either join it on the teardown path or make the
    fire-and-forget choice explicit with ``daemon=True``."""
    an = analyze_leaks(package)
    for acq in an.acqs:
        if acq.fn.module is not mod or acq.kind != "thread":
            continue
        if acq.daemon or acq.name is None or acq.via_with \
                or acq.escaped:
            continue
        if any(r.verb == "join" for r in acq.releases):
            continue
        line, col = _loc(acq.node)
        yield Finding(
            "unjoined-thread", mod.path, line, col,
            f"non-daemon thread `{acq.name}` is never joined — join "
            f"it before dropping the handle, or pass `daemon=True` if "
            f"fire-and-forget is intended")
    for (cls, attr), stores in sorted(an.attr_stores.items()):
        events = an.attr_events.get((cls, attr), ())
        if any(e.verb == "join" for e in events):
            continue
        for st in stores:
            if st.fn.module is not mod or st.kind != "thread" \
                    or st.daemon:
                continue
            line, col = _loc(st.node)
            yield Finding(
                "unjoined-thread", mod.path, line, col,
                f"non-daemon thread stored on `{cls}.{attr}` is never "
                f"joined by any method of the class — add a join to "
                f"the shutdown path or pass `daemon=True`")


@leak_rule("unlinked-shm",
           "shared-memory creator closes its mapping but never "
           "unlinks the segment")
def check_unlinked_shm(package: Package, mod: ModuleInfo):
    """``SharedMemory(create=True, ...)`` makes this code the
    segment's OWNER: ``close()`` only unmaps this process's view, the
    backing /dev/shm file needs ``unlink()`` or it survives every
    process that ever attached — the ~66 MB-per-dead-worker leak PR
    9's review caught by hand.  Fires on creators (local or stored on
    ``self``) that release without ever unlinking; attachers
    (``create=True`` absent) owe only ``close()`` and are exempt."""
    an = analyze_leaks(package)
    for acq in an.acqs:
        if acq.fn.module is not mod or not acq.shm_create:
            continue
        if acq.via_with or acq.escaped or not acq.releases:
            continue
        if any(r.verb == "unlink" for r in acq.releases):
            continue
        line, col = _loc(acq.node)
        yield Finding(
            "unlinked-shm", mod.path, line, col,
            f"shared-memory segment `{acq.name}` is created here and "
            f"closed, but never unlinked — the /dev/shm file outlives "
            f"the process; call `.unlink()` on the owner's teardown "
            f"path")
    for (cls, attr), stores in sorted(an.attr_stores.items()):
        events = an.attr_events.get((cls, attr), ())
        if any(e.verb == "unlink" for e in events):
            continue
        for st in stores:
            if st.fn.module is not mod or not st.shm_create:
                continue
            line, col = _loc(st.node)
            yield Finding(
                "unlinked-shm", mod.path, line, col,
                f"shared-memory segment stored on `{cls}.{attr}` is "
                f"created here but no method of the class ever "
                f"unlinks it — the /dev/shm file outlives the "
                f"process; add `.unlink()` to the teardown path")


@leak_rule("double-release",
           "two unconditional releases of one obligation")
def check_double_release(package: Package, mod: ModuleInfo):
    """The same obligation is discharged twice unconditionally — two
    depth-0 ``close()`` calls on one local, or two same-verb releases
    of one ``self.X`` in a single function with no ``= None`` / guard
    / re-store between them.  The second call hits an fd the OS may
    have recycled, or raises mid-teardown and masks the error that
    actually mattered.  Releases under a conditional, inside
    ``finally``/``except``, or separated by a ``self.X = None`` are
    legitimate idempotent-teardown idioms and stay quiet."""
    an = analyze_leaks(package)
    for acq in an.acqs:
        if acq.fn.module is not mod or acq.name is None:
            continue
        plain = [r for r in acq.releases
                 if r.depth == 0 and not r.in_finally
                 and not r.in_handler]
        seen = {}
        for r in sorted(plain, key=lambda r: r.line):
            if r.verb in seen and seen[r.verb] != r.line:
                yield Finding(
                    "double-release", mod.path, r.line, 0,
                    f"`{acq.name}.{r.verb}()` already ran "
                    f"unconditionally at line {seen[r.verb]} — the "
                    f"second release double-frees the "
                    f"{_human_kind(acq.kind)}")
                break
            seen.setdefault(r.verb, r.line)
    for fn, events in an.fn_attr_events.items():
        if fn.module is not mod:
            continue
        by_attr = {}
        for e in sorted(events, key=lambda e: e.line):
            by_attr.setdefault(e.attr, []).append(e)
        for attr, evs in sorted(by_attr.items()):
            # only attributes known to hold a resource participate
            if not any(key[1] == attr for key in an.attr_stores):
                continue
            seen = {}
            for e in evs:
                if e.verb in ("guard", "clear", "swap"):
                    seen.clear()
                    continue
                if e.depth != 0 or e.in_finally:
                    continue
                if e.verb in seen and seen[e.verb] != e.line:
                    yield Finding(
                        "double-release", mod.path, e.line, 0,
                        f"`self.{attr}.{e.verb}()` already ran "
                        f"unconditionally at line {seen[e.verb]} in "
                        f"this function — the second release "
                        f"double-frees the resource")
                    break
                seen.setdefault(e.verb, e.line)
