"""Policy-value-return DRC network for Geister.

Capability parity with the reference ``GeisterNet``
(/root/reference/handyrl/envs/geister.py:130-166): scalar features
broadcast onto the board planes, conv stem, 3-layer DRC body repeated
3x, a move policy head (4 directions x 36 cells), a 70-way piece-layout
set head driven by the turn-color scalar, a tanh value head and an
unsquashed return head — here NHWC Flax with GroupNorm.
"""

import jax.numpy as jnp
from flax import linen as nn

from .blocks import PolicyHead, ValueHead, pick_num_groups
from .recurrent import DRC

BOARD = (6, 6)
NUM_MOVE_ACTIONS = 4 * 36
NUM_SET_ACTIONS = 70


class GeisterNet(nn.Module):
    filters: int = 32
    drc_layers: int = 3
    drc_repeats: int = 3

    def init_hidden(self, batch_shape=()):
        return DRC.initial_state(
            self.drc_layers, BOARD, self.filters, batch_shape)

    @nn.compact
    def __call__(self, obs, hidden):
        board, scalar = obs["board"], obs["scalar"]  # (B,6,6,7), (B,18)
        if hidden is None:
            hidden = self.init_hidden((board.shape[0],))

        s_planes = jnp.broadcast_to(
            scalar[:, None, None, :],
            (scalar.shape[0],) + BOARD + (scalar.shape[-1],),
        )
        h = jnp.concatenate([s_planes, board], axis=-1)

        h = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(h)
        h = nn.GroupNorm(num_groups=pick_num_groups(self.filters))(h)
        h = nn.relu(h)

        h, new_hidden = DRC(
            self.drc_layers, self.filters, num_repeats=self.drc_repeats
        )(h, hidden)

        # move policy: conv head emitting 4 direction planes -> 144 logits
        pm = nn.Conv(8, (3, 3), padding="SAME", use_bias=False)(h)
        pm = nn.GroupNorm(num_groups=pick_num_groups(8))(pm)
        pm = nn.relu(pm)
        pm = nn.Conv(4, (1, 1), use_bias=False)(pm)
        # (B, 6, 6, 4) -> direction-major flat order d*36 + x*6 + y
        pm = jnp.transpose(pm, (0, 3, 1, 2)).reshape(pm.shape[0], -1)

        # set policy: layout prior from the turn-color scalar alone
        turn_color = scalar[:, :1]
        ps = nn.Dense(NUM_SET_ACTIONS)(turn_color)

        policy = jnp.concatenate([pm, ps], axis=-1)
        value = ValueHead(bottleneck=2)(h)
        ret = ValueHead(bottleneck=2, squash=False)(h)
        return {"policy": policy, "value": value, "return": ret,
                "hidden": new_hidden}
