"""Value-target / advantage estimators as reverse ``lax.scan``s.

Semantic parity with /root/reference/handyrl/losses.py:16-81 (Monte
Carlo, TD(lambda), UPGO, V-Trace per IMPALA, arXiv:1802.01561), with the
reference's deque-append reverse Python loops re-expressed as a single
reverse ``lax.scan`` over the time axis — one fused XLA loop instead of
T dispatches.

Array layout: ``(B, T, P, 1)`` (batch, time, player, channel), time on
axis 1 — identical to the reference's batch layout.  All functions are
jit-safe and differentiable (inputs are expected pre-``stop_gradient``
where the algorithm calls for it, as in the reference, which computes
targets on detached values).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _time_leading(x):
    return jnp.moveaxis(x, 1, 0)


def _time_second(x):
    return jnp.moveaxis(x, 0, 1)


def _reverse_scan(step_fn, init, xs_time_second):
    """Run ``step_fn`` backward over axis-1 slices and re-stack outputs
    in forward time order, appending ``init`` as the final step."""
    xs = jax.tree.map(_time_leading, xs_time_second)
    _, ys = lax.scan(step_fn, init, xs, reverse=True)
    ys = _time_second(ys)
    return jnp.concatenate([ys, init[:, None]], axis=1)


def monte_carlo(values, returns):
    """Targets are the observed returns themselves."""
    return returns, returns - values


def temporal_difference(values, returns, rewards, lambda_, gamma):
    """TD(lambda) targets via backward recursion:

      G_t = r_t + gamma * ((1 - lambda_{t+1}) * V_{t+1} + lambda_{t+1} * G_{t+1})

    with ``G_{T-1} = returns_{T-1}``.
    """
    rewards = jnp.zeros_like(values) if rewards is None else rewards

    def step(g_next, x):
        v_next, r, lam = x
        g = r + gamma * ((1.0 - lam) * v_next + lam * g_next)
        return g, g

    targets = _reverse_scan(
        step,
        returns[:, -1],
        (values[:, 1:], rewards[:, :-1], lambda_[:, 1:]),
    )
    return targets, targets - values


def upgo(values, returns, rewards, lambda_, gamma):
    """UPGO targets: bootstrap through the better of the next value and
    the lambda-blended continuation (only propagates advantages along
    trajectories that outperformed the baseline)."""
    rewards = jnp.zeros_like(values) if rewards is None else rewards

    def step(g_next, x):
        v_next, r, lam = x
        g = r + gamma * jnp.maximum(v_next, (1.0 - lam) * v_next + lam * g_next)
        return g, g

    targets = _reverse_scan(
        step,
        returns[:, -1],
        (values[:, 1:], rewards[:, :-1], lambda_[:, 1:]),
    )
    return targets, targets - values


def vtrace(values, returns, rewards, lambda_, gamma, rhos, cs):
    """V-Trace targets and advantages (IMPALA, arXiv:1802.01561).

    ``rhos``/``cs`` are the clipped importance ratios; the correction
    term ``vs - V`` accumulates backward scaled by ``gamma * lambda * c``.
    """
    rewards = jnp.zeros_like(values) if rewards is None else rewards
    values_next = jnp.concatenate([values[:, 1:], returns[:, -1:]], axis=1)
    deltas = rhos * (rewards + gamma * values_next - values)

    def step(acc, x):
        delta, lam, c = x
        acc = delta + gamma * lam * c * acc
        return acc, acc

    vs_minus_v = _reverse_scan(
        step,
        deltas[:, -1],
        (deltas[:, :-1], lambda_[:, 1:], cs[:, :-1]),
    )
    vs = vs_minus_v + values
    vs_next = jnp.concatenate([vs[:, 1:], returns[:, -1:]], axis=1)
    advantages = rewards + gamma * vs_next - values
    return vs, advantages


def impact(values, returns, rewards, lambda_, gamma, rhos, cs):
    """IMPACT targets (arXiv:1912.00167): the V-Trace recursion driven
    by TARGET-NETWORK importance ratios.

    The estimator is numerically the V-Trace recursion — what the
    IMPACT scheme changes is which policy produced ``rhos``/``cs``
    (the maintained target policy instead of the live learner policy;
    see ops.losses) and how the policy loss consumes the advantages (a
    two-sided surrogate clip).  Kept as its own dispatch entry so a
    ``value_target: IMPACT`` config reads explicitly and the golden
    tests can pin the identity."""
    return vtrace(values, returns, rewards, lambda_, gamma, rhos, cs)


def compute_target(algorithm: str, values, returns, rewards, lmb, gamma,
                   rhos, cs, masks):
    """Dispatch to a target estimator, blending lambda with the
    observation mask (unobserved steps pass through with lambda = 1),
    exactly as /root/reference/handyrl/losses.py:63-81."""
    if values is None:
        # no baseline head: fall back to Monte-Carlo returns
        return returns, returns

    if algorithm == "MC":
        return monte_carlo(values, returns)

    lambda_ = lmb + (1.0 - lmb) * (1.0 - masks)

    if algorithm == "TD":
        return temporal_difference(values, returns, rewards, lambda_, gamma)
    if algorithm == "UPGO":
        return upgo(values, returns, rewards, lambda_, gamma)
    if algorithm == "VTRACE":
        return vtrace(values, returns, rewards, lambda_, gamma, rhos, cs)
    if algorithm == "IMPACT":
        return impact(values, returns, rewards, lambda_, gamma, rhos, cs)
    raise ValueError(f"unknown target algorithm {algorithm!r}")
