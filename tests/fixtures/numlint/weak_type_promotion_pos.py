"""POS: a weak python scalar wrapped by asarray drags bf16 to fp32."""
import jax
import jax.numpy as jnp


@jax.jit
def forward(x):
    h = x.astype(jnp.bfloat16)
    step = jnp.asarray(0.1)
    return h * step
