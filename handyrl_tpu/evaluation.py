"""Evaluation: online eval matches, offline eval driver, network battles.

Role parity with /root/reference/handyrl/evaluation.py:32-436 — the
online Evaluator used by workers during training, the multiprocess
offline driver behind ``--eval`` (with first/second seat equalization
for two-player games), and the network battle mode where a server hosts
the env and remote clients drive agents over TCP via the env's
``diff_info``/``update`` delta-sync protocol.
"""

import multiprocessing as mp
import random
import time

from .agent import Agent, RandomAgent, RuleBasedAgent
from .connection import (
    accept_socket_connections,
    open_socket_connection,
)
from .environment import make_env, prepare_env
from .models import TPUModel

NETWORK_PORT = 9876


class NetworkAgentClient:
    """Client side of a network battle: owns the agent and a mirror env,
    executing RPC verbs sent by the server."""

    def __init__(self, agent, env, conn):
        self.conn = conn
        self.agent = agent
        self.env = env

    def run(self):
        while True:
            try:
                command, args = self.conn.recv()
            except (ConnectionResetError, EOFError):
                break
            if command == "quit":
                break
            elif command == "outcome":
                print(f"outcome = {args[0]}")
            elif hasattr(self.agent, command):
                ret = getattr(self.agent, command)(self.env, *args, show=True)
                if command == "action":
                    player = args[0]
                    ret = self.env.action2str(ret, player)
            else:
                ret = getattr(self.env, command)(*args)
                if command == "update":
                    print(self.env)
            self.conn.send(ret)


class NetworkAgent:
    """Server-side proxy: forwards verbs to a remote client agent."""

    def __init__(self, conn):
        self.conn = conn

    def update(self, data, reset):
        return self._send("update", [data, reset])

    def outcome(self, outcome):
        return self._send("outcome", [outcome])

    def action(self, player):
        return self._send("action", [player])

    def observe(self, player):
        return self._send("observe", [player])

    def _send(self, command, args):
        self.conn.send((command, args))
        return self.conn.recv()


def exec_match(env, agents, critic=None, show=False, game_args={}):
    """One match on a shared env instance; returns per-player outcome."""
    if env.reset(game_args):
        return None
    for agent in agents.values():
        agent.reset(env, show=show)
    while not env.terminal():
        if show:
            print(env)
        turn_players = env.turns()
        observers = env.observers()
        actions = {}
        for p, agent in agents.items():
            if p in turn_players:
                actions[p] = agent.action(env, p, show=show)
            elif p in observers:
                agent.observe(env, p, show=show)
        if env.step(actions):
            return None
        if show and critic is not None:
            print(f"cv = {critic.observe(env, None, show=False)}")
    if show:
        print(env)
        print(f"final outcome = {env.outcome()}")
    return env.outcome()


def exec_network_match(env, network_agents, critic=None, game_args={}):
    """One match where agents live on remote clients, kept in sync by
    the env's diff protocol."""
    if env.reset(game_args):
        return None
    for p, agent in network_agents.items():
        info = env.diff_info(p)
        agent.update(info, True)
    while not env.terminal():
        turn_players = env.turns()
        observers = env.observers()
        actions = {}
        for p, agent in network_agents.items():
            if p in turn_players:
                action_str = agent.action(p)
                actions[p] = env.str2action(action_str, p)
            elif p in observers:
                agent.observe(p)
        if env.step(actions):
            return None
        for p, agent in network_agents.items():
            info = env.diff_info(p)
            agent.update(info, False)
    outcome = env.outcome()
    for p, agent in network_agents.items():
        agent.outcome(outcome[p])
    return outcome


def build_agent(raw, env=None):
    """Instantiate a named opponent: 'random', 'rulebase[-key]'."""
    if raw == "random":
        return RandomAgent()
    if raw.startswith("rulebase"):
        key = raw.split("-")[1] if "-" in raw else None
        return RuleBasedAgent(key)
    return None


class Evaluator:
    """Online evaluation during training: trained model vs configured
    opponent pool (default 'random')."""

    def __init__(self, env, args):
        self.env = env
        self.args = args
        self.opponent = args.get("eval", {}).get("opponent", ["random"])
        if not isinstance(self.opponent, list):
            self.opponent = [self.opponent]

    def execute(self, models, args):
        opponents = self.opponent
        opponent = random.choice(opponents) if opponents else "random"
        agents = {}
        for p, model in models.items():
            if model is None:
                agents[p] = build_agent(opponent, self.env) or RandomAgent()
            else:
                agents[p] = Agent(model, observation=self.args["observation"])
        outcome = exec_match(self.env, agents)
        if outcome is None:
            print("None episode in evaluation!")
            return None
        return {"args": args, "result": outcome, "opponent": opponent}


def wp_func(results):
    """Win rate over an outcome histogram (draws count half)."""
    games = sum(results.values())
    if games == 0:
        return 0.0
    win = sum(n for r, n in results.items() if r > 0)
    draw = sum(n for r, n in results.items() if r == 0)
    return (win + draw / 2) / games


def eval_process_mp_child(agents, critic, env_args, index, in_queue, out_queue,
                          seed, show=False):
    from .connection import force_cpu_jax

    force_cpu_jax()
    random.seed(seed + index)
    env = make_env({**env_args, "id": index})
    while True:
        args = in_queue.get()
        if args is None:
            break
        g, agent_ids, pat_idx, game_args = args
        print(f"*** Game {g} ***")
        agent_map = {
            env.players()[p]: agents[ai] for p, ai in enumerate(agent_ids)
        }
        if isinstance(list(agent_map.values())[0], NetworkAgent):
            outcome = exec_network_match(env, agent_map, critic,
                                         game_args=game_args)
        else:
            outcome = exec_match(env, agent_map, critic, show=show,
                                 game_args=game_args)
        out_queue.put((pat_idx, agent_ids, outcome))
    out_queue.put(None)


def evaluate_mp(env, agents, critic, env_args, args_patterns, num_process,
                num_games, seed):
    """Offline evaluation farm: ``num_process`` processes play
    ``num_games`` per pattern; two-player seats are equalized."""
    from .connection import _mp

    in_queue, out_queue = _mp.Queue(), _mp.Queue()
    args_cnt = 0
    total_results, result_map = [{} for _ in agents], [{} for _ in agents]
    print("total games = %d" % (len(args_patterns) * num_games))
    time.sleep(0.1)
    for pat_name, game_args in args_patterns.items():
        for i in range(num_games):
            if len(agents) == 2:
                # first/second seat equalization
                first_agent = 0 if i < (num_games + 1) // 2 else 1
                seat = "first" if first_agent == 0 else "second"
                tmp_pat_idx = f"{pat_name}_{seat}"
                agent_ids = [first_agent, 1 - first_agent]
            else:
                tmp_pat_idx = pat_name
                agent_ids = random.sample(
                    list(range(len(agents))), len(agents))
            in_queue.put((args_cnt, agent_ids, tmp_pat_idx, game_args))
            args_cnt += 1

    network_mode = agents[0] is None
    if network_mode:  # network battle mode
        agents = network_match_acception(
            num_process, env_args, len(agents), NETWORK_PORT)
    else:
        agents = [agents] * num_process

    for i in range(num_process):
        in_queue.put(None)
        args = (agents[i], critic, env_args, i, in_queue, out_queue, seed)
        if num_process > 1:
            _mp.Process(target=eval_process_mp_child, args=args,
                        daemon=True).start()
            if network_mode:
                for agent in agents[i]:
                    agent.conn.close()
        else:
            eval_process_mp_child(*args, show=True)

    finished_cnt = 0
    while finished_cnt < num_process:
        ret = out_queue.get()
        if ret is None:
            finished_cnt += 1
            continue
        pat_idx, agent_ids, outcome = ret
        if outcome is not None:
            for idx, p in enumerate(env.players()):
                agent_id = agent_ids[idx]
                oc = outcome[p]
                result_map[agent_id].setdefault(pat_idx, {})
                result_map[agent_id][pat_idx][oc] = (
                    result_map[agent_id][pat_idx].get(oc, 0) + 1)
                total_results[agent_id][oc] = (
                    total_results[agent_id].get(oc, 0) + 1)

    for idx, result in enumerate(result_map):
        print(f"agent {idx}")
        for pat_idx, results in result.items():
            print(f"    pattern {pat_idx}: "
                  f"win rate = {wp_func(results):.3f} "
                  f"({sum(results.values())} games)")
    for idx, results in enumerate(total_results):
        print(f"agent {idx}: win rate = {wp_func(results):.3f}")


def network_match_acception(n, env_args, num_agents, port):
    """Accept ``n * num_agents`` client connections and group them into
    per-match agent lists."""
    waiting_conns = []
    accepted_conns = []

    for conn in accept_socket_connections(port):
        if conn is None:
            continue
        waiting_conns.append(conn)
        if len(waiting_conns) == num_agents:
            conn = waiting_conns[0]
            accepted_conns.append(conn)
            waiting_conns = waiting_conns[1:]
            conn.send(env_args)  # send accepted env args

        if len(accepted_conns) >= n * num_agents:
            break

    agents_list = [
        [NetworkAgent(accepted_conns[i * num_agents + j])
         for j in range(num_agents)]
        for i in range(n)
    ]
    return agents_list


def client_mp_child(env_args, model_path, conn):
    env = make_env(env_args)
    model = load_model(model_path, env)
    NetworkAgentClient(Agent(model), env, conn).run()


def load_model(model_path, env):
    """Load a saved checkpoint (.ckpt pickle or exported .npz) into a
    TPUModel for evaluation."""
    import pickle

    model = TPUModel(env.net())
    if model_path.endswith(".npz"):
        import numpy as np

        from .utils.tree import unflatten_params

        archive = np.load(model_path)
        model.params = unflatten_params({
            key: archive[key] for key in archive.files
            if key != "__header__"
        })
        return model
    with open(model_path, "rb") as f:
        state = pickle.load(f)
    params = state["params"] if isinstance(state, dict) and "params" in state \
        else state
    model.params = params
    return model


def eval_main(args, argv):
    env_args = args["env_args"]
    prepare_env(env_args)
    env = make_env(env_args)

    model_path = argv[0] if len(argv) >= 1 else "models/latest.ckpt"
    num_games = int(argv[1]) if len(argv) >= 2 else 100
    num_process = int(argv[2]) if len(argv) >= 3 else 1

    def resolve_agent(raw):
        agent = build_agent(raw, env)
        if agent is None:
            model = load_model(raw, env)
            agent = Agent(model)
        return agent

    agent1 = resolve_agent(model_path)
    critic = None
    print(f"evaluated files = {model_path}")

    seed = random.randrange(1 << 31)
    print(f"seed = {seed}")
    opponent = args.get("eval_args", {}).get("opponent", "random")
    agents = [agent1] + [
        build_agent(opponent, env) or RandomAgent()
        for _ in range(len(env.players()) - 1)
    ]
    evaluate_mp(env, agents, critic, env_args, {"default": {}},
                num_process, num_games, seed)


def eval_server_main(args, argv):
    print("network match server mode")
    env_args = args["env_args"]
    prepare_env(env_args)
    env = make_env(env_args)

    num_games = int(argv[0]) if len(argv) >= 1 else 100
    num_process = int(argv[1]) if len(argv) >= 2 else 1

    seed = random.randrange(1 << 31)
    print(f"seed = {seed}")
    evaluate_mp(env, [None] * len(env.players()), None, env_args,
                {"default": {}}, num_process, num_games, seed)


def eval_client_main(args, argv):
    print("network match client mode")
    from .connection import _mp

    procs, conns = [], []
    while True:
        try:
            host = argv[1] if len(argv) >= 2 else "localhost"
            conn = open_socket_connection(host, NETWORK_PORT)
            env_args = conn.recv()
        except (EOFError, ConnectionError, OSError):
            break

        model_path = argv[0] if len(argv) >= 1 else "models/latest.ckpt"
        p = _mp.Process(target=client_mp_child,
                        args=(env_args, model_path, conn), daemon=True)
        p.start()
        procs.append(p)
        # keep our copy open: spawned children receive the socket via
        # the resource sharer, which needs the parent fd alive
        conns.append(conn)
    for p in procs:
        p.join()
