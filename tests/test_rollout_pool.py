"""RolloutPool: the lockstep batched actor engine.

The pool must produce byte-compatible wire episodes while batching
inference across seats and episodes.  The strongest checks here replay
recorded episodes through the sequential single-seat path (``Seat``)
and require the numbers the pool recorded — behavior probabilities,
value estimates — to match, which catches row-indexing, masking, and
hidden-state-continuity bugs.
"""

import random

import numpy as np
import pytest

from handyrl_tpu.batch import decompress_moments, make_batch
from handyrl_tpu.environment import make_env
from handyrl_tpu.generation import (
    MOMENT_KEYS,
    Generator,
    RolloutPool,
    Seat,
)
from handyrl_tpu.models import TPUModel
from handyrl_tpu.utils.tree import softmax_np

TTT_CFG = {
    "turn_based_training": True, "observation": False, "gamma": 0.8,
    "forward_steps": 8, "burn_in_steps": 0, "compress_steps": 4,
    "lambda": 0.7, "policy_target": "TD", "value_target": "TD",
    "eval": {"opponent": ["random"]},
}


def _make_pool(env_name, cfg, k, seed=0):
    envs = [make_env({"env": env_name}) for _ in range(k)]
    model = TPUModel(envs[0].net())
    envs[0].reset()
    model.init_params(
        envs[0].observation(envs[0].players()[0]), seed=seed)
    pool = RolloutPool(envs, cfg)
    players = envs[0].players()
    job = {"role": "g", "player": players,
           "model_id": {p: 1 for p in players}}
    models = {p: model for p in players}
    return pool, model, job, models


def _collect(pool, job, models, n, refill=True):
    episodes = []
    while pool.has_free_slot():
        pool.assign(job, models)
    while len(episodes) < n:
        for verb, payload in pool.step():
            assert verb == "episode"
            if payload is not None:
                episodes.append(payload)
            if refill and pool.has_free_slot():
                pool.assign(job, models)
    return episodes


def test_pool_wire_format_and_batch():
    random.seed(11)
    pool, model, job, models = _make_pool("TicTacToe", TTT_CFG, k=4)
    episodes = _collect(pool, job, models, 6)
    for ep in episodes:
        assert set(ep) == {"args", "steps", "outcome", "moment",
                           "final_model_epoch", "gen_model_epoch"}
        moments = [m for blob in ep["moment"]
                   for m in decompress_moments(
                       {"moment": [blob], "start": 0, "base": 0,
                        "end": 10**9})]
        assert len(moments) == ep["steps"]
        for m in moments:
            assert set(MOMENT_KEYS) <= set(m)
            assert m["turn"]  # someone acted every step

    sel = [{
        "args": ep["args"], "outcome": ep["outcome"],
        "moment": ep["moment"][:2], "base": 0, "start": 0,
        "end": min(8, ep["steps"]), "train_start": 0,
        "total": ep["steps"],
    } for ep in episodes]
    batch = make_batch(sel, TTT_CFG)
    assert batch["observation"].shape[:3] == (6, 8, 1)
    assert np.all(batch["selected_prob"] > 0)


def test_pool_selected_prob_matches_replay():
    """Feed-forward: every recorded behavior probability must equal a
    fresh single-state inference on the recorded observation."""
    random.seed(12)
    pool, model, job, models = _make_pool("TicTacToe", TTT_CFG, k=3)
    episodes = _collect(pool, job, models, 4)
    checked = 0
    for ep in episodes:
        moments = decompress_moments(
            {"moment": ep["moment"], "start": 0, "base": 0,
             "end": ep["steps"]})
        for m in moments:
            (player,) = m["turn"]
            out = model.inference(m["observation"][player])
            masked = np.where(
                m["action_mask"][player] > 0, -1e32, out["policy"])
            probs = softmax_np(masked)
            assert m["selected_prob"][player] == pytest.approx(
                float(probs[m["action"][player]]), abs=1e-4)
            assert m["value"][player] == pytest.approx(
                np.ravel(out["value"]), abs=1e-4)
            checked += 1
    assert checked > 10


def test_pool_recurrent_hidden_continuity():
    """Recurrent: replaying each seat's observation stream through the
    sequential Seat path must reproduce the pool's recorded values —
    proves per-row hidden state advances exactly like a private seat."""
    random.seed(13)
    cfg = dict(TTT_CFG, observation=True, burn_in_steps=2,
               turn_based_training=True)
    pool, model, job, models = _make_pool("Geister", cfg, k=2, seed=3)
    episodes = _collect(pool, job, models, 2)
    assert pool.hidden is not None  # DRC net: stacked hidden in play
    for ep in episodes:
        moments = decompress_moments(
            {"moment": ep["moment"], "start": 0, "base": 0,
             "end": ep["steps"]})
        for player in (0, 1):
            seat = Seat(player, model)
            for m in moments:
                obs = m["observation"][player]
                if obs is None:
                    continue
                out = seat.think(obs)
                if m["value"][player] is not None:
                    np.testing.assert_allclose(
                        m["value"][player],
                        np.ravel(np.asarray(out["value"], np.float32)),
                        atol=2e-3)


def test_pool_eval_slots():
    random.seed(14)
    pool, model, job, models = _make_pool("TicTacToe", TTT_CFG, k=2)
    ejob = {"role": "e", "player": [0], "model_id": {0: 1, 1: -1}}
    emodels = {0: model, 1: None}
    assert pool.accepts(ejob)
    results = []
    while len(results) < 3:
        if pool.has_free_slot():
            pool.assign(ejob, emodels)
        for verb, payload in pool.step():
            assert verb == "result"
            assert payload is not None
            results.append(payload)
    for res in results:
        assert res["opponent"] == "random"
        assert set(res["result"]) == {0, 1}
        assert res["args"]["role"] == "e"


def test_pool_rejects_mixed_snapshots():
    job = {"role": "g", "player": [0, 1], "model_id": {0: 3, 1: 5}}
    assert not RolloutPool.accepts(job)
    ejob = {"role": "e", "player": [0], "model_id": {0: 2, 1: -1}}
    assert RolloutPool.accepts(ejob)


def test_pool_eval_pinned_across_model_swap():
    """An in-flight eval match keeps using the snapshot it was
    scheduled with after the pool swaps to a newer one (solo-inference
    fallback), so win rates are never credited to a mixed policy."""
    random.seed(16)
    pool, model, job, models = _make_pool("TicTacToe", TTT_CFG, k=2)
    ejob = {"role": "e", "player": [0], "model_id": {0: 1, 1: -1}}
    pool.assign(ejob, {0: model, 1: None})

    model2 = TPUModel(model.module)
    env = make_env({"env": "TicTacToe"})
    env.reset()
    model2.init_params(env.observation(0), seed=98)
    pool.assign({"role": "g", "player": [0, 1],
                 "model_id": {0: 2, 1: 2}}, {0: model2, 1: model2})
    assert pool.model is model2

    solo_calls = []
    original = model.inference
    model.inference = lambda *a, **kw: (
        solo_calls.append(1) or original(*a, **kw))
    result = None
    while result is None:
        for verb, payload in pool.step():
            if verb == "result":
                result = payload
    model.inference = original
    assert result is not None
    assert solo_calls, "pinned eval seat must use its own snapshot"


def test_pool_model_swap_keeps_running():
    """A newer snapshot arriving mid-flight switches the pool without
    disturbing in-progress episodes."""
    random.seed(15)
    pool, model, job, models = _make_pool("TicTacToe", TTT_CFG, k=2)
    while pool.has_free_slot():
        pool.assign(job, models)
    pool.step()

    model2 = TPUModel(model.module)
    env = make_env({"env": "TicTacToe"})
    env.reset()
    model2.init_params(env.observation(0), seed=99)
    job2 = {"role": "g", "player": [0, 1], "model_id": {0: 2, 1: 2}}
    models2 = {0: model2, 1: model2}

    episodes = []
    while len(episodes) < 4:
        if pool.has_free_slot():
            pool.assign(job2, models2)
        episodes.extend(
            p for v, p in pool.step() if p is not None)
    assert pool.model is model2
