"""Network-battle RPC round trip: the dynamic twin of commlint's
static protocol graph.

A real NetworkAgentClient (agent + mirror env) runs against the
server-side NetworkAgent stub over an in-process duplex pipe, and the
test drives every verb of the evaluation protocol — ``update`` /
``observe`` / ``action`` / ``outcome`` / ``quit`` — asserting each
request gets its matching reply (and that ``quit``, fire-and-forget by
protocol, terminates the client loop without one).  What commlint
proves from source (every sent verb has a handler, every round-trip
handler replies), this proves by execution."""

import threading
from multiprocessing import Pipe

from handyrl_tpu.agent import RandomAgent
from handyrl_tpu.envs.tictactoe import Environment as TicTacToe
from handyrl_tpu.evaluation import NetworkAgent, NetworkAgentClient


def _start_client(conn):
    client = NetworkAgentClient(RandomAgent(), TicTacToe(), conn)
    thread = threading.Thread(target=client.run, daemon=True)
    thread.start()
    return thread


def test_every_protocol_verb_round_trips():
    server_conn, client_conn = Pipe(duplex=True)
    thread = _start_client(client_conn)
    agent = NetworkAgent(server_conn)
    env = TicTacToe()
    assert not env.reset()

    # update(reset=True): client mirrors the fresh env, resets agent
    assert agent.update(env.diff_info(0), True) is None

    # a few real turns: action returns the client's action STRING,
    # decodable and legal in the server's env
    for _ in range(3):
        player = env.turns()[0]
        action_str = agent.action(player)
        assert isinstance(action_str, str)
        action = env.str2action(action_str, player)
        assert action in env.legal_actions(player)
        # the other seat merely observes this turn
        other = [p for p in env.players() if p != player][0]
        agent.observe(other)
        assert not env.step({player: action})
        # delta-sync the client's mirror (update(reset=False))
        assert agent.update(env.diff_info(0), False) is None
        if env.terminal():
            break

    # outcome: acked with an (empty) reply, not silence
    assert agent.outcome(1) is None

    # quit is fire-and-forget: no reply, and the client loop exits
    agent.quit()
    thread.join(timeout=10)
    assert not thread.is_alive(), "client did not exit on quit"


def test_quit_is_idempotent_on_dead_client():
    """quit() after the client is gone must not raise — series teardown
    races client exits by design."""
    server_conn, client_conn = Pipe(duplex=True)
    thread = _start_client(client_conn)
    agent = NetworkAgent(server_conn)
    agent.quit()
    thread.join(timeout=10)
    assert not thread.is_alive()
    client_conn.close()
    agent.quit()  # second quit into a closed pipe: swallowed
    agent.quit()
