"""Actor-side runtime: workers, gather fan-in, local & remote clusters.

Role parity with /root/reference/handyrl/worker.py:26-271.  Workers are
CPU processes running self-play (generation) or evaluation matches; a
tree of Gather processes batches their requests so the learner serves
O(num_gathers) connections instead of O(num_workers).  Remote machines
join elastically through an entry handshake.

TPU-native specifics: every child process pins its JAX to the CPU
backend (``force_cpu_jax``) — actor inference is a CPU-jitted forward,
the TPU belongs to the learner's update step alone.  Processes are
spawned, not forked, because PJRT clients do not survive fork.

Ports (same as the reference so operational docs carry over):
  9999 — entry server: one-shot handshake assigning worker-id blocks
  9998 — worker server: persistent gather connections
"""

import copy
import functools
import pickle
import queue
import random
import threading
import time
from collections import deque
from socket import gethostname

from .connection import (
    QueueCommunicator,
    _mp,
    accept_socket_connections,
    force_cpu_jax,
    open_multiprocessing_connections,
    open_socket_connection,
    send_recv,
)

ENTRY_PORT = 9999
WORKER_PORT = 9998


class Worker:
    """One actor process: request a job, fetch models, roll out, reply."""

    def __init__(self, args, conn, wid):
        print(f"opened worker {wid}")
        self.worker_id = wid
        self.args = args
        self.conn = conn
        self.latest_model = (-1, None)

        from .environment import make_env
        from .evaluation import Evaluator
        from .generation import Generator

        self.env = make_env({**args["env"], "id": wid})
        self.generator = Generator(self.env, self.args)
        self.evaluator = Evaluator(self.env, self.args)
        random.seed(args["seed"] + wid)

    def __del__(self):
        print(f"closed worker {self.worker_id}")

    def _gather_models(self, model_ids):
        from .models import RandomModel

        model_pool = {}
        for model_id in model_ids:
            if model_id not in model_pool:
                if model_id < 0:
                    model_pool[model_id] = None
                elif model_id == self.latest_model[0]:
                    # the latest model is cached locally
                    model_pool[model_id] = self.latest_model[1]
                else:
                    # request a snapshot from the learner
                    model = pickle.loads(
                        send_recv(self.conn, ("model", model_id)))
                    if model_id == 0:
                        # id 0 = uniform-random stand-in
                        self.env.reset()
                        obs = self.env.observation(self.env.players()[0])
                        model = RandomModel(model, obs)
                    model_pool[model_id] = model
                    if model_id > self.latest_model[0]:
                        self.latest_model = (model_id, model)
        return model_pool

    def run(self):
        try:
            self._loop()
        except (ConnectionResetError, BrokenPipeError, EOFError, OSError):
            pass  # learner/gather is gone: exit quietly

    def _loop(self):
        while True:
            args = send_recv(self.conn, ("args", None))
            if args is None:
                break
            role = args["role"]

            models = {}
            if "model_id" in args:
                model_ids = list(args["model_id"].values())
                model_pool = self._gather_models(model_ids)
                for p, model_id in args["model_id"].items():
                    models[p] = model_pool[model_id]

            if role == "g":
                episode = self.generator.execute(models, args)
                send_recv(self.conn, ("episode", episode))
            elif role == "e":
                result = self.evaluator.execute(models, args)
                send_recv(self.conn, ("result", result))


def make_worker_args(args, n_ga, gaid, base_wid, wid):
    # interleaved worker ids across gathers (reference worker.py:90-91)
    return args, base_wid + wid * n_ga + gaid


def open_worker(conn, args, wid):
    force_cpu_jax()
    worker = Worker(args, conn, wid)
    worker.run()


class Gather(QueueCommunicator):
    """Fan-in proxy: one process per ~16 workers.

    Prefetches job-arg blocks, caches model replies by id, and batches
    episode/result uploads so learner round trips scale with gathers,
    not workers (parity with /root/reference/handyrl/worker.py:99-173).
    """

    def __init__(self, args, conn, gather_id):
        print(f"started gather {gather_id}")
        self.gather_id = gather_id
        self.server_conn = conn
        self.args_queue = deque()
        self.data_map = {"model": {}}
        self.result_send_map = {}
        self.result_send_cnt = 0

        n_pro = args["worker"]["num_parallel"]
        n_ga = args["worker"]["num_gathers"]
        num_workers = n_pro // n_ga + int(gather_id < n_pro % n_ga)
        base_wid = args["worker"].get("base_worker_id", 0)

        worker_conns = open_multiprocessing_connections(
            num_workers,
            open_worker,
            functools.partial(make_worker_args, args, n_ga, gather_id,
                              base_wid),
        )
        super().__init__(worker_conns)
        self.buffer_length = 1 + len(worker_conns) // 4

    def run(self):
        while self.connection_count() > 0:
            try:
                conn, (command, args) = self.recv(timeout=0.3)
            except queue.Empty:
                continue

            if command == "args":
                if not self.args_queue:
                    # prefetch a block of job assignments
                    self.server_conn.send(
                        (command, [None] * self.buffer_length))
                    self.args_queue.extend(self.server_conn.recv())
                self.send(conn, self.args_queue.popleft())

            elif command in self.data_map:
                # cacheable request (model snapshots keyed by id)
                if args not in self.data_map[command]:
                    self.server_conn.send((command, args))
                    self.data_map[command][args] = self.server_conn.recv()
                self.send(conn, self.data_map[command][args])

            else:
                # ack first, batch the upload
                self.send(conn, None)
                self.result_send_map.setdefault(command, []).append(args)
                self.result_send_cnt += 1
                if self.result_send_cnt >= self.buffer_length:
                    self._flush_results()

    def _flush_results(self):
        for command, args_list in self.result_send_map.items():
            self.server_conn.send((command, args_list))
            self.server_conn.recv()
        self.result_send_map = {}
        self.result_send_cnt = 0


def gather_loop(args, conn, gather_id):
    force_cpu_jax()
    gather = Gather(args, conn, gather_id)
    try:
        gather.run()
    except (ConnectionResetError, BrokenPipeError, EOFError, OSError):
        pass  # learner is gone: exit quietly


class WorkerCluster(QueueCommunicator):
    """Local actor pool: gather processes over pipes."""

    def __init__(self, args):
        super().__init__()
        self.args = args

    def run(self):
        if "num_gathers" not in self.args["worker"]:
            self.args["worker"]["num_gathers"] = (
                1 + max(0, self.args["worker"]["num_parallel"] - 1) // 16)
        for i in range(self.args["worker"]["num_gathers"]):
            conn0, conn1 = _mp.Pipe(duplex=True)
            # gathers spawn worker children, so they cannot be daemonic;
            # they exit on their own once every worker disconnects
            _mp.Process(
                target=gather_loop, args=(self.args, conn1, i)
            ).start()
            conn1.close()
            self.add_connection(conn0)


class WorkerServer(QueueCommunicator):
    """Learner-side acceptor for remote worker machines.

    Two listener threads: the entry port hands out worker-id blocks and
    the merged config; the worker port accepts persistent gather
    connections into the communicator (elastic joins, parity with
    /root/reference/handyrl/worker.py:192-224).
    """

    def __init__(self, args):
        super().__init__()
        self.args = args
        self.total_worker_count = 0

    def run(self):
        threading.Thread(target=self._entry_server, daemon=True).start()
        threading.Thread(target=self._worker_server, daemon=True).start()

    def _entry_server(self):
        print(f"started entry server {ENTRY_PORT}")
        for conn in accept_socket_connections(port=ENTRY_PORT):
            if conn is None:
                continue
            worker_args = conn.recv()
            print(f"accepted connection from {worker_args['address']}")
            worker_args["base_worker_id"] = self.total_worker_count
            self.total_worker_count += worker_args["num_parallel"]
            args = copy.deepcopy(self.args)
            args["worker"] = worker_args
            conn.send(args)
            conn.close()

    def _worker_server(self):
        print(f"started worker server {WORKER_PORT}")
        for conn in accept_socket_connections(port=WORKER_PORT):
            if conn is None:
                continue
            self.add_connection(conn)


def entry(worker_args):
    """Remote machine -> learner handshake; returns the merged config."""
    conn = open_socket_connection(worker_args["server_address"], ENTRY_PORT)
    conn.send(worker_args)
    args = conn.recv()
    conn.close()
    return args


class RemoteWorkerCluster:
    """Worker-machine runtime: handshake, then gathers dialing the
    learner's worker port."""

    def __init__(self, args):
        args["address"] = gethostname()
        if "num_gathers" not in args:
            args["num_gathers"] = 1 + max(0, args["num_parallel"] - 1) // 16
        self.args = args

    def run(self):
        args = entry(self.args)
        print(args)
        from .environment import prepare_env

        prepare_env(args["env"])

        process = []
        try:
            for i in range(self.args["num_gathers"]):
                conn = open_socket_connection(
                    self.args["server_address"], WORKER_PORT)
                p = _mp.Process(
                    target=gather_loop, args=(args, conn, i))
                p.start()
                conn.close()
                process.append(p)
            while True:
                time.sleep(100)
        finally:
            for p in process:
                p.terminate()


def worker_main(args, argv):
    worker_args = args["worker_args"]
    if len(argv) >= 1:
        worker_args["num_parallel"] = int(argv[0])
        worker_args.pop("num_gathers", None)

    worker = RemoteWorkerCluster(args=worker_args)
    worker.run()
