"""Device-resident episode staging: the replay buffer lives in HBM.

The reference (and this repo's fallback path) assembles every training
batch on the host: sample episodes, decompress, gather/pad numpy, ship
the result to the device (/root/reference/handyrl/train.py:271-319).
On a learner whose update step takes ~1 ms that host work IS the
training loop — the device idles >95% of wall-clock (measured in
BENCH_r03: 14 steps/s end-to-end vs 225 device-resident).

``DeviceReplay`` inverts the layout, TPU-first:

  * each finished episode is decompressed and columnarized ONCE, then
    uploaded into a ring of fixed-shape device buffers (obs rides the
    compact wire dtype — bf16 or uint8 — so HBM cost is half/quarter
    of f32);
  * every training batch is built ON DEVICE by one jitted gather: the
    host contributes only three small int32 vectors per draw (episode
    slot, window start, seat), and XLA fuses the window fetch into a
    single gather from the flat ring;
  * masks, padding, value bootstrap, progress — all the ``make_batch``
    semantics — are recomputed inside the same jit from episode
    lengths, equal to the host path (tests/test_staging.py pins batch
    equality draw by draw).

Per-step feed cost collapses from "assemble + transfer ~20 MB on the
host" to "transfer ~3 KB of indices", and the per-episode upload is
amortized over every draw of that episode (recency-biased sampling
draws each episode many times per epoch).

Storage layout: per-step channels are TWO-dimensional
``(CAP * T_max, flat_features)`` arrays (slot-major time, trailing
dims flattened), so a window fetch is ONE gather with indices
``slot * T_max + t`` — never materializing a ``(B, T_max, ...)``
intermediate — and, critically, the persistent ring pads to the TPU's
(8, 128) tile with ~1% overhead.  Keeping logical trailing dims (e.g.
``(N, P, 6, 6, 7)``) instead would tile-pad the ring up to ~24x and
OOM the device (observed on Geister: a 2 GB ring became a 47 GB
allocation).  The gather reshapes windows back to logical shapes
in-jit, where they are transient activations XLA lays out freely.
Per-slot channels (outcome, lengths) are ``(CAP + 1, ...)``; the +1
and an extra ``_RUN_ROUND``-row stripe past the ring are SCRATCH that
batched-append padding scatters into and no gather ever reads.

Concurrency contract: appends and samples MUST run on one thread (the
trainer thread calls ``ingest`` between update steps).  Both jits
donate the buffers, so interleaving from two threads would race the
donation.  The learner's server thread only enqueues raw episodes into
``pending`` (thread-safe under the internal lock).
"""

import random
import threading
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from .batch import BF16, ILLEGAL, _build_columnar
from .utils.tree import tree_map


def make_replay_update_step(replay, model, loss_cfg, optimizer,
                            compute_dtype, batch_size, mesh=None,
                            params=None, fsdp=False, seed=0):
    """ONE jitted program per training step: index draw -> ring gather
    -> loss -> grad -> Adam.  Everything happens on device — the host
    contributes three SCALARS per call (ring fill, oldest slot, step
    counter), so a training step uploads nothing at all.  The draw
    folds the step counter into a fixed PRNG key and reproduces the
    triangular recency bias + uniform window/seat choice in-jit.

    With a mesh, params/optimizer keep their usual shardings while the
    ring rides replicated and the gathered batch is constrained onto
    ``dp`` — each device materializes only its own batch rows.

    Under ``update_algorithm: impact`` the signature grows the target
    params (same treatment as ``params``): ``step(params, opt_state,
    buffers, state, target_params)`` returning the refreshed target as
    its last element — still ONE jitted program per training step.
    """
    from .ops.update import make_update_core

    core = make_update_core(model, loss_cfg, optimizer, compute_dtype)
    impact = loss_cfg.update_algorithm == "impact"
    base_key = jax.random.PRNGKey(seed)

    def _draw(buffers, state):
        # state = device int32 [size, oldest, step_idx]: keeping the
        # draw scalars ON DEVICE and threading the step counter through
        # the jit means a steady-state step uploads NOTHING — three
        # per-step host-int uploads measurably cost ~40% throughput on
        # tunneled hosts (BENCH r5 probe)
        size, oldest, step_idx = state[0], state[1], state[2]
        slots, tstarts, seats = replay._draw_on_device(
            buffers, size, oldest, step_idx, base_key, batch_size)
        batch = replay._gather_batch(buffers, slots, tstarts, seats)
        if replay._out is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, replay._out), batch)
        return batch

    if impact:
        def step(params, opt_state, buffers, state, target_params):
            batch = _draw(buffers, state)
            p, o, metrics, t = core(params, opt_state, batch,
                                    target_params)
            return (p, o, metrics,
                    state + jnp.asarray([0, 0, 1], jnp.int32), t)
    else:
        def step(params, opt_state, buffers, state):
            batch = _draw(buffers, state)
            p, o, metrics = core(params, opt_state, batch)
            return p, o, metrics, state + jnp.asarray([0, 0, 1],
                                                      jnp.int32)

    if mesh is None:
        if impact:
            return jax.jit(step, donate_argnums=(0, 1, 3, 4))
        return jax.jit(step, donate_argnums=(0, 1, 3))

    from .parallel.mesh import param_sharding, replicated
    from .parallel.update import opt_state_sharding

    p_shard = param_sharding(mesh, params, fsdp=fsdp)
    rep = replicated(mesh)
    o_shard = opt_state_sharding(optimizer, params, p_shard, rep)
    if impact:
        return jax.jit(
            step,
            in_shardings=(p_shard, o_shard, rep, rep, p_shard),
            out_shardings=(p_shard, o_shard, rep, rep, p_shard),
            donate_argnums=(0, 1, 3, 4),
        )
    return jax.jit(
        step,
        in_shardings=(p_shard, o_shard, rep, rep),
        out_shardings=(p_shard, o_shard, rep, rep),
        donate_argnums=(0, 1, 3),
    )

_GROW_ROUND = 32   # T_max granularity; growth doubles => few recompiles
# episode uploads pad to _GROW_ROUND-row buckets (not full t_max
# stripes: ~6x less wire traffic at real episode-length spreads) and
# each append batch pads its TOTAL rows to _RUN_ROUND so the scatter
# jit sees a handful of shapes; padding rows land in a scratch stripe
# past the ring that no gather ever reads
_RUN_ROUND = 256
_MAX_RUN = 8       # per-slot scatter width (ingest batch cap)
_PER_SLOT = ("outcome", "ep_len", "ep_total")


def _decompress_episode(ep):
    """Full-episode columnar arrays from the wire format (bz2 or raw
    pickle moment blocks, magic-sniffed per block — see
    batch.load_block).  Runs once per episode at ingest."""
    from .batch import load_block

    moments = [m for blob in ep["moment"] for m in load_block(blob)]
    col = _build_columnar(moments)
    col["outcome"] = np.asarray(
        [ep["outcome"][p] for p in col["players"]],
        np.float32).reshape(-1, 1)
    col["steps"] = ep["steps"]
    return col


def _round_up(n, k=_GROW_ROUND):
    return ((n + k - 1) // k) * k


class DeviceReplay:
    """Ring buffer of episodes in device memory + jitted batch gather.

    ``mode`` mirrors ``make_batch``'s player selection
    (batch.py _episode_tensors):
      turn — turn-based training: acting channels gather the turn
             player (P_in=1), value channels keep all players
      seat — simultaneous games: ONE random seat per draw, all channels
      all  — observation mode: all players, all channels
    """

    def __init__(self, cfg, capacity, max_bytes, max_steps_hint=0,
                 mesh=None):
        self.cfg = cfg
        # single-process multi-chip: the ring is REPLICATED over the
        # mesh (appends are cheap; HBM budget applies per device) and
        # the sample jit emits dp-sharded batches — each device gathers
        # only its own batch rows, so sampling scales with the mesh
        self._rep = None
        self._out = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._rep = NamedSharding(mesh, P())
            self._out = NamedSharding(mesh, P("dp"))
        self.capacity = int(capacity)   # may shrink to fit max_bytes
        self.max_bytes = int(max_bytes)
        self.forward_steps = cfg["forward_steps"]
        self.burn_in = cfg.get("burn_in_steps", 0) or 0
        self.t_win = self.burn_in + self.forward_steps
        if cfg["turn_based_training"]:
            self.mode = "all" if cfg.get("observation") else "turn"
        else:
            self.mode = "seat"
        obs_wire = cfg.get("transfer_dtype") or ""
        self.obs_store = {"bfloat16": BF16, "uint8": np.uint8}.get(
            obs_wire, np.float32)
        self.compute_dtype = cfg.get("compute_dtype") or "bfloat16"

        self.t_max = _round_up(max(max_steps_hint, self.t_win))
        self.buffers = None        # device pytree
        self.num_players = None
        self._append_fn = None
        self._sample_fn = None

        # host-side mirrors (sampling math reads these, never devices)
        self._rng = None             # lazily seeded from `random`
        self.ep_len = None
        self.write_ptr = 0         # next slot (FIFO ring)
        self.size = 0              # filled slots
        self.episodes_seen = 0
        self.growths = 0           # T_max growth count: each one is a
        #                            LEGITIMATE recompile of the fused
        #                            step (the trainer widens its
        #                            RetraceGuard budget by this)

        # server thread -> trainer thread handoff
        self.pending = deque()
        self.pending_cap = 512
        self.dropped = 0
        self._lock = threading.Lock()
        self._state_dirty = True   # ring changed since last device_state

    def device_state(self, step_idx):
        """Device int32 ``[size, oldest, step_idx]`` for the fused
        update step (make_replay_update_step).  Uploaded once here and
        then THREADED through the jit (which returns it with the step
        counter advanced), so steady-state steps upload nothing; call
        again only when ``state_dirty`` says an append/growth moved
        the ring."""
        self._state_dirty = False
        arr = jnp.asarray(
            np.asarray([self.size, self.oldest, step_idx], np.int32))
        if self._rep is not None:
            arr = jax.device_put(arr, self._rep)
        return arr

    @property
    def state_dirty(self):
        return self._state_dirty

    # -- ingest -------------------------------------------------------

    def offer(self, episodes):
        """Learner-server-thread side: queue raw episodes for the
        trainer thread.  Bounded: a stalled trainer sheds the OLDEST
        pending episodes rather than growing without limit."""
        with self._lock:
            self.pending.extend(e for e in episodes if e is not None)
            while len(self.pending) > self.pending_cap:
                self.pending.popleft()
                self.dropped += 1

    def ingest(self, max_episodes=64, batch=_MAX_RUN):
        """Trainer-thread only: move pending episodes into the device
        ring.  Bounded per call so one call can't stall an update.

        Up to ``batch`` episodes upload as ONE device scatter —
        per-dispatch latency, not bandwidth, dominates small uploads,
        especially through tunneled hosts — and each episode ships
        only its bucket-rounded length, not a full t_max stripe."""
        batch = min(batch, _MAX_RUN)
        if self.buffers is None:
            # size T_max from everything already waiting (the warmup
            # backlog usually contains a near-maximal episode, saving
            # most growth recompiles later)
            with self._lock:
                if self.pending:
                    self.t_max = max(
                        self.t_max,
                        _round_up(max(e["steps"]
                                      for e in self.pending if e)))
        done = 0
        while done < max_episodes:
            cols = []
            with self._lock:
                while self.pending and len(cols) < batch:
                    cols.append(self.pending.popleft())
            if not cols:
                return
            cols = [_decompress_episode(ep) for ep in cols]
            done += len(cols)
            # batched is the ONLY path: size/allocate/grow decisions
            # are taken once over the whole run, then the run lands as
            # one device scatter (the legacy per-episode `_append`
            # dispatch measured ~12x slower and is gone)
            need = max(len(c["turn_idx"]) for c in cols)
            if self.buffers is None:
                if need > self.t_max:
                    self.t_max = _round_up(need)
                self._init_buffers(cols[0])
            elif need > self.t_max:
                self._grow(_round_up(max(need, self.t_max * 2)))
            while cols:
                # never more episodes than ring slots in one scatter:
                # repeated slot indices would mix trajectories
                # (undefined duplicate-index winner)
                run = cols[:self.capacity]
                self._append_run(run)
                del cols[:len(run)]

    def warm_start(self, episodes):
        """Restore a replayed backlog (durability WAL) straight into
        the ring on the CALLER's thread, bypassing the bounded
        ``pending`` handoff (whose shed-oldest cap exists for a live
        stalled trainer, not for a finite resume replay).  MUST run
        before the trainer thread starts — same single-thread contract
        as ``ingest``.  Returns the number of episodes staged."""
        count = 0
        chunk = []
        for episode in episodes:
            if episode is None:
                continue
            chunk.append(episode)
            if len(chunk) >= 64:
                self.offer(chunk)
                self.ingest(max_episodes=len(chunk))
                count += len(chunk)
                chunk = []
        if chunk:
            self.offer(chunk)
            self.ingest(max_episodes=len(chunk))
            count += len(chunk)
        return count

    # -- buffer management -------------------------------------------

    def _per_slot_bytes(self, col):
        """HBM bytes one ring slot will occupy (capacity sizing).

        Counts what the TPU actually allocates, not logical bytes: a
        persistent ``(rows, width)`` buffer tile-pads its trailing dim
        to 128 lanes, so every narrow per-step channel (prob, act,
        value, reward, return, tmask, omask, turn_idx — widths 1..P)
        costs a full 128-wide stripe.  Sizing from logical bytes here
        would let the ring blow through ``device_replay_mb`` by >10x
        on narrow channels — the same trap the module docstring
        documents for obs."""
        def lanes(width):
            return ((max(int(width), 1) + 127) // 128) * 128

        P = len(col["players"])
        A = col["amask"].shape[-1]
        obs_bytes = 0
        for leaf in jax.tree.leaves(col["obs"]):
            width = int(np.prod(leaf.shape[1:]))  # (T, P, ...) -> P*...
            item = (np.dtype(self.obs_store).itemsize
                    if np.issubdtype(leaf.dtype, np.floating)
                    else leaf.dtype.itemsize)
            obs_bytes += lanes(width) * item
        step = (obs_bytes                    # observation tree
                + lanes(P) * 4 * 3           # prob + value f32, act i32
                + lanes(P * A)               # amask bool
                + lanes(P) * 4 * 2           # reward, return
                + lanes(P) * 2               # tmask, omask bool
                + lanes(1) * 4)              # turn_idx
        return step * self.t_max + self._slot_const_bytes(P)

    @staticmethod
    def _slot_const_bytes(P):
        # per-slot channels: outcome (CAP, P, 1) tiles its last two
        # dims to (8, 128); ep_len/ep_total are 1D (amortized ~0)
        return ((P + 7) // 8) * 8 * 128 * 4 + 8

    def _init_buffers(self, col):
        self.num_players = len(col["players"])
        per_slot = self._per_slot_bytes(col)
        # remembered for re-clamping when T_max grows
        self._per_step_bytes = (
            per_slot - self._slot_const_bytes(self.num_players)
        ) // self.t_max
        # the budget is a hard ceiling — flooring it away would OOM at
        # exactly the episode sizes (GRF-scale) where it matters most
        fit = max(1, self.max_bytes // per_slot)
        if fit < self.capacity:
            print(f"device replay: {self.capacity} episodes at "
                  f"~{per_slot/1e6:.2f} MB each exceed the "
                  f"{self.max_bytes >> 20} MiB budget; ring capped at "
                  f"{fit} (raise device_replay_mb to widen)"
                  + (" — WARNING: a ring this small cripples replay "
                     "diversity" if fit < 64 else ""))
            self.capacity = int(fit)
        P = self.num_players
        A = col["amask"].shape[-1]
        # + one scratch stripe past the ring (and one scratch slot)
        # where batched-append PADDING rows land; gathers never read it
        flat = self.capacity * self.t_max + _RUN_ROUND
        z = jnp.zeros
        # logical per-step shapes; stored flattened to 2D (see module
        # docstring: TPU tile padding on small trailing dims)
        self.obs_shapes = [leaf.shape[1:]
                           for leaf in jax.tree.leaves(col["obs"])]
        self.obs_treedef = jax.tree.structure(col["obs"])
        self.shapes = {
            "prob": (P, 1), "act": (P, 1), "amask": (P, A),
            "value": (P, 1), "reward": (P, 1), "return": (P, 1),
            "tmask": (P, 1), "omask": (P, 1),
        }

        def flat2d(shape, dtype):
            width = int(np.prod(shape)) if shape else 1
            return z((flat, width), dtype)

        self.buffers = {
            "obs": tree_map(
                lambda a: flat2d(a.shape[1:],
                                 self.obs_store
                                 if np.issubdtype(a.dtype, np.floating)
                                 else a.dtype),
                col["obs"]),
            "prob": flat2d((P, 1), jnp.float32),
            "act": flat2d((P, 1), jnp.int32),
            "amask": flat2d((P, A), jnp.bool_),
            "value": flat2d((P, 1), jnp.float32),
            "reward": flat2d((P, 1), jnp.float32),
            "return": flat2d((P, 1), jnp.float32),
            "tmask": flat2d((P, 1), jnp.bool_),
            "omask": flat2d((P, 1), jnp.bool_),
            "turn_idx": flat2d((), jnp.int32),
            "outcome": z((self.capacity + 1, P, 1), jnp.float32),
            "ep_len": z((self.capacity + 1,), jnp.int32),
            "ep_total": z((self.capacity + 1,), jnp.int32),
        }
        if self._rep is not None:
            self.buffers = jax.device_put(self.buffers, self._rep)
        self.ep_len = np.zeros(self.capacity, np.int32)
        self._build_jits()

    def _build_jits(self):
        def append(buffers, ep, flat_idx, slots):
            # scatter write: per-step channels land at explicit flat
            # row indices (bucket-rounded episode rows + scratch-bound
            # padding), per-slot channels at their slot indices.  One
            # dispatch per ingest batch; shapes bucket to _RUN_ROUND
            # totals so the jit compiles a handful of variants.
            out = {}
            for key, buf in buffers.items():
                idx = slots if key in _PER_SLOT else flat_idx
                out[key] = jax.tree.map(
                    lambda b, e, i=idx: b.at[i].set(e),
                    buf, ep[key])
            return out

        if self._rep is not None:
            self._append_fn = jax.jit(
                append, donate_argnums=0, out_shardings=self._rep)
            self._sample_fn = jax.jit(
                self._gather_batch, out_shardings=self._out)
        else:
            self._append_fn = jax.jit(append, donate_argnums=0)
            self._sample_fn = jax.jit(self._gather_batch)

    def _pad_episode(self, col, rows):
        """Columnar episode -> (rows, ...) host arrays in the storage
        dtypes (``rows`` is the episode's bucket-rounded length, NOT
        t_max: short episodes must not ship full stripes)."""
        T = len(col["turn_idx"])
        pad = rows - T

        def padt(a, value=0):
            a = np.ascontiguousarray(a).reshape(T, -1)  # 2D storage
            if pad == 0:
                return a
            return np.pad(a, [(0, pad), (0, 0)],
                          constant_values=value)

        def obs_store(a):
            if not np.issubdtype(a.dtype, np.floating):
                return a
            if self.obs_store == np.uint8:
                q = a.astype(np.uint8)
                if not np.array_equal(q.astype(a.dtype), a):
                    raise ValueError(
                        "transfer_dtype 'uint8' requires integer-"
                        "valued observations; use 'bfloat16'")
                return q
            return a.astype(self.obs_store)

        return {
            "obs": tree_map(lambda a: padt(obs_store(a)), col["obs"]),
            "prob": padt(col["prob"].astype(np.float32)),
            "act": padt(col["act"].astype(np.int32)),
            "amask": padt(col["amask"] != 0, True),
            "value": padt(col["value"].astype(np.float32)),
            "reward": padt(col["reward"].astype(np.float32)),
            "return": padt(col["return"].astype(np.float32)),
            "tmask": padt(col["tmask"] != 0),
            "omask": padt(col["omask"] != 0),
            "turn_idx": padt(col["turn_idx"].astype(np.int32)),
            "outcome": col["outcome"][None],  # (1, P, 1): one ring slot
            "ep_len": np.asarray([T], np.int32),
            "ep_total": np.asarray([col["steps"]], np.int32),
        }

    def _append_run(self, cols):
        """Write ``len(cols) <= _MAX_RUN`` episodes with ONE device
        scatter.  Each episode ships its bucket-rounded rows; the
        batch's total rows pad to _RUN_ROUND (padding rows scatter
        into the scratch stripe past the ring, per-slot padding into
        the scratch slot) so the jit sees few shapes.  Callers
        guarantee buffers exist and no episode exceeds t_max; slot
        wrap-around needs no special casing — indices are explicit."""
        k = len(cols)
        lens = [len(c["turn_idx"]) for c in cols]
        rows = [_round_up(t) for t in lens]
        eps = [self._pad_episode(c, r) for c, r in zip(cols, rows)]
        slots = [(self.write_ptr + i) % self.capacity
                 for i in range(k)]
        total = sum(rows)
        pad = -total % _RUN_ROUND
        scratch = self.capacity * self.t_max
        flat_idx = np.concatenate(
            [s * self.t_max + np.arange(r, dtype=np.int32)
             for s, r in zip(slots, rows)]
            + ([scratch + np.arange(pad, dtype=np.int32)]
               if pad else []))
        slot_idx = np.asarray(
            slots + [self.capacity] * (_MAX_RUN - k), np.int32)

        def cat_steps(*arrs):
            out = np.concatenate(arrs)
            if pad:
                out = np.concatenate(
                    [out, np.zeros((pad,) + out.shape[1:], out.dtype)])
            return out

        def cat_slots(*arrs):
            out = np.concatenate(arrs)
            if k < _MAX_RUN:
                out = np.concatenate([out, np.zeros(
                    (_MAX_RUN - k,) + out.shape[1:], out.dtype)])
            return out

        ep = {key: jax.tree.map(
            cat_slots if key in _PER_SLOT else cat_steps,
            *[e[key] for e in eps]) for key in eps[0]}
        self.buffers = self._append_fn(
            self.buffers, ep, flat_idx, slot_idx)
        for s, t in zip(slots, lens):
            self.ep_len[s] = t
        self.write_ptr = (self.write_ptr + k) % self.capacity
        self.size = min(self.size + k, self.capacity)
        self.episodes_seen += k
        self._state_dirty = True

    def _grow(self, new_t_max):
        """A longer episode than ever seen arrived: re-lay the ring
        with a larger T_max (device-side copy + one recompile).  Growth
        doubles, so this happens O(log T) times per run.  The byte
        budget is re-enforced: if wider slots no longer fit, the ring
        shrinks, keeping the NEWEST episodes (FIFO semantics)."""
        old_t, cap = self.t_max, self.capacity
        per_slot_const = self._slot_const_bytes(self.num_players)
        new_cap = min(cap, max(1, self.max_bytes // (
            self._per_step_bytes * new_t_max + per_slot_const)))
        print(f"device replay: growing T_max {old_t} -> {new_t_max}"
              + (f", ring {cap} -> {new_cap} (byte budget)"
                 if new_cap < cap else ""))

        # slot order oldest -> newest, truncated to the newest new_cap
        n = self.size
        order = [(self.write_ptr - n + i) % cap for i in range(n)]
        keep = np.asarray(order[-new_cap:] if n > new_cap else order,
                          np.int32)
        kept = len(keep)
        # per-step channels gather whole slot stripes via flat indices
        flat_keep = (keep[:, None] * old_t
                     + np.arange(old_t)[None]).reshape(-1)

        def relayout(buf):
            def leaf(a):
                if a.shape[0] == cap * old_t + _RUN_ROUND:
                    rows = a[flat_keep].reshape(
                        (kept, old_t) + a.shape[1:])
                    pad = [(0, new_cap - kept), (0, new_t_max - old_t)
                           ] + [(0, 0)] * (a.ndim - 1)
                    flat = jnp.pad(rows, pad).reshape(
                        (new_cap * new_t_max,) + a.shape[1:])
                    # fresh scratch stripe past the new ring
                    return jnp.pad(
                        flat, [(0, _RUN_ROUND)] + [(0, 0)] * (a.ndim - 1))
                # per-slot channel (+ its scratch slot)
                rows = a[keep]
                pad = [(0, new_cap + 1 - kept)] + [(0, 0)] * (a.ndim - 1)
                return jnp.pad(rows, pad)
            return tree_map(leaf, buf)

        # jaxlint: disable=retrace-risk -- growth doubles T_max, so this compiles O(log T) times per run and the shapes differ every time anyway
        self.buffers = jax.jit(
            relayout, donate_argnums=0, out_shardings=self._rep
        )(self.buffers)
        new_len = np.zeros(new_cap, np.int32)
        new_len[:kept] = self.ep_len[keep]
        self.ep_len = new_len
        self.size = kept
        self.write_ptr = kept % new_cap
        self.capacity = new_cap
        self.t_max = new_t_max
        self.growths += 1
        self._state_dirty = True
        self._build_jits()

    # -- sampling -----------------------------------------------------

    @property
    def oldest(self):
        """Ring slot of the oldest live episode (host mirror)."""
        return (self.write_ptr - self.size) % self.capacity

    def draw_indices(self, batch_size):
        """Host-side draw: recency-biased episode choice + random
        training window, as three int32 vectors.

        Same distribution as Batcher.select_episode's accept loop —
        P(idx) = (idx+1)/S with S = n(n+1)/2 — but drawn in closed
        form (inverse CDF of the discrete triangle) so a 256-row draw
        is a few numpy ops, not 256 Python rejection loops."""
        if self._rng is None:
            self._rng = np.random.default_rng(random.getrandbits(64))
        rng = self._rng
        n = self.size
        oldest = self.oldest
        # (idx+1)(idx+2) <= u*n*(n+1) + 2  =>  triangular idx
        u = rng.random(batch_size)
        idx = np.floor(
            (np.sqrt(1.0 + 4.0 * u * n * (n + 1)) - 3.0) / 2.0
        ).astype(np.int64) + 1
        idx = np.clip(idx, 0, n - 1)
        slots = ((oldest + idx) % self.capacity).astype(np.int32)
        cands = 1 + np.maximum(0, self.ep_len[slots] - self.forward_steps)
        tstarts = rng.integers(0, cands, dtype=np.int32)
        if self.mode == "seat":
            seats = rng.integers(
                0, self.num_players, batch_size, dtype=np.int32)
        else:
            seats = np.zeros(batch_size, np.int32)
        return slots, tstarts, seats

    def sample(self, batch_size):
        """One device-resident training batch (trainer thread only)."""
        slots, tstarts, seats = self.draw_indices(batch_size)
        return self._sample_fn(
            self.buffers, jnp.asarray(slots), jnp.asarray(tstarts),
            jnp.asarray(seats))

    def _draw_on_device(self, buffers, size, oldest, step_idx,
                        base_key, batch_size):
        """The draw_indices math as traced jax ops (used inside the
        fused update step, so a step needs no per-call array uploads).
        Same distributions as the host draw — triangular recency over
        the ring, uniform window start, uniform seat — on a different
        RNG stream (jax PRNG keyed by the config seed + step counter;
        like the host path, which draws from the ``random`` module the
        Learner seeds with ``args['seed']``, the stream is
        config-seed-deterministic)."""
        key = jax.random.fold_in(base_key, step_idx)
        k1, k2, k3 = jax.random.split(key, 3)
        size = jnp.asarray(size)
        n = size.astype(jnp.float32)
        u = jax.random.uniform(k1, (batch_size,))
        idx = jnp.floor(
            (jnp.sqrt(1.0 + 4.0 * u * n * (n + 1)) - 3.0) / 2.0
        ).astype(jnp.int32) + 1
        idx = jnp.clip(idx, 0, size - 1)
        slots = (oldest + idx) % self.capacity
        cands = 1 + jnp.maximum(
            0, buffers["ep_len"][slots] - self.forward_steps)
        tstarts = jnp.floor(
            jax.random.uniform(k2, (batch_size,)) * cands
        ).astype(jnp.int32)
        if self.mode == "seat":
            seats = jax.random.randint(
                k3, (batch_size,), 0, self.num_players, jnp.int32)
        else:
            seats = jnp.zeros(batch_size, jnp.int32)
        return slots, tstarts, seats

    # The gather: all of make_batch's semantics, on device.
    def _gather_batch(self, buffers, slots, tstarts, seats):
        t_max, t_win = self.t_max, self.t_win
        lens = buffers["ep_len"][slots]                  # (B,)
        totals = buffers["ep_total"][slots]

        # window positions g in episode time; validity from lengths
        g = (tstarts - self.burn_in)[:, None] + jnp.arange(t_win)  # (B,T)
        valid = (g >= 0) & (g < lens[:, None])
        after = g >= lens[:, None]       # past the terminal step
        gi = jnp.clip(g, 0, t_max - 1)
        flat_idx = slots[:, None] * t_max + gi                     # (B,T)

        def fetch(buf, shape):
            # 2D ring row -> logical (B, T, *shape) window
            return buf[flat_idx].reshape(flat_idx.shape + tuple(shape))

        def mask_t(x, pad_value, m=valid):
            shape = m.shape + (1,) * (x.ndim - 2)
            return jnp.where(m.reshape(shape), x, pad_value)

        turn = fetch(buffers["turn_idx"], ())            # (B,T)
        obs = jax.tree.unflatten(self.obs_treedef, [
            fetch(buf, shape) for buf, shape in zip(
                jax.tree.leaves(buffers["obs"]), self.obs_shapes)
        ])                                               # (B,T,P,...)
        prob = fetch(buffers["prob"], self.shapes["prob"])
        act = fetch(buffers["act"], self.shapes["act"])
        amask = fetch(buffers["amask"], self.shapes["amask"])
        value = fetch(buffers["value"], self.shapes["value"])
        reward = fetch(buffers["reward"], self.shapes["reward"])
        ret = fetch(buffers["return"], self.shapes["return"])
        tmask = fetch(buffers["tmask"], self.shapes["tmask"])
        omask = fetch(buffers["omask"], self.shapes["omask"])
        outcome = buffers["outcome"][slots]              # (B,P,1)

        def select_players(x, idx):
            # (B,T,P,...) -> (B,T,1,...) by per-(row,step) player index
            shape = idx.shape + (1,) * (x.ndim - 2)
            return jnp.take_along_axis(
                x, idx.reshape(shape).astype(jnp.int32), axis=2)

        if self.mode == "turn":
            def acting(x):
                return select_players(x, turn)
        elif self.mode == "seat":
            seat_bt = jnp.broadcast_to(seats[:, None], turn.shape)

            def acting(x):
                return select_players(x, seat_bt)

            # seat mode selects ONE player for every channel
            value, reward, ret = acting(value), acting(reward), acting(ret)
            tmask, omask = acting(tmask), acting(omask)
            outcome = jnp.take_along_axis(
                outcome, seats[:, None, None], axis=1)
        else:
            def acting(x):
                return x

        cdt = jnp.dtype(self.compute_dtype)

        def obs_out(a):
            sel = acting(a)
            if (jnp.issubdtype(sel.dtype, jnp.floating)
                    or sel.dtype == jnp.uint8):
                sel = sel.astype(cdt)
            return mask_t(sel, 0)

        return {
            "observation": tree_map(obs_out, obs),
            "selected_prob": mask_t(acting(prob), 1.0),
            "action": mask_t(acting(act), 0),
            "action_mask": jnp.where(
                mask_t(acting(amask), True),
                jnp.float32(ILLEGAL), jnp.float32(0)),
            "value": jnp.where(
                after[..., None, None],
                outcome[:, None],
                mask_t(value, 0.0)),
            "reward": mask_t(reward, 0.0),
            "return": mask_t(ret, 0.0),
            "outcome": outcome[:, None],                 # (B,1,P,1)
            "episode_mask": valid[..., None, None].astype(jnp.float32),
            "turn_mask": mask_t(tmask, False).astype(jnp.float32),
            "observation_mask": mask_t(omask, False).astype(jnp.float32),
            "progress": (jnp.where(
                valid,
                g.astype(jnp.float32) / totals[:, None].astype(
                    jnp.float32),
                1.0))[..., None],
        }
