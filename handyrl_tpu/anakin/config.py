"""Typed ``anakin.*`` configuration (validated like pipeline.*/chaos.*:
the dataclass the engine actually runs with IS the validation layer,
and tests/test_docs.py mechanically requires docs/parameters.md to
cover every field)."""

from dataclasses import dataclass


@dataclass
class AnakinConfig:
    # off (default) = the IMPALA worker path generates episodes;
    # on = require the fused on-device rollout (error if the env has no
    # pure-JAX twin); auto = use it when the env has one, fall back
    # loudly otherwise
    mode: str = "off"
    # concurrent self-play games on the device's env axis (the fused
    # step's batch dimension — thousands per chip is the design point)
    num_envs: int = 1024
    # scanned env steps per fused rollout segment; 0 = the env's
    # MAX_STEPS.  Segments are episode-aligned: every game must be able
    # to finish inside one segment, so the engine rejects values below
    # the env's MAX_STEPS
    unroll_length: int = 0
    # frozen past-snapshot opponents on the vectorized opponent-pool
    # axis: num_envs factors as (opponent_pool + 1) groups — group 0
    # plays pure self-play, group k plays the learner seat against
    # frozen snapshot k (refreshed oldest-out at each epoch boundary).
    # 0 = pure self-play only
    opponent_pool: int = 0

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    @classmethod
    def from_config(cls, cfg) -> "AnakinConfig":
        cfg = dict(cfg or {})
        unknown = set(cfg) - {
            "mode", "num_envs", "unroll_length", "opponent_pool"}
        if unknown:
            raise ValueError(
                f"unknown anakin keys: {sorted(unknown)}")
        num_envs = cfg.get("num_envs", 1024)
        self = cls(
            mode=str(cfg.get("mode", "off") or "off"),
            # an explicit 0 must REJECT below, not silently default
            num_envs=int(1024 if num_envs is None else num_envs),
            unroll_length=int(cfg.get("unroll_length", 0) or 0),
            opponent_pool=int(cfg.get("opponent_pool", 0) or 0),
        )
        if self.mode not in ("off", "on", "auto"):
            raise ValueError(f"unknown anakin.mode {self.mode!r}")
        if self.num_envs < 1:
            raise ValueError("anakin.num_envs must be >= 1")
        if self.unroll_length < 0:
            raise ValueError("anakin.unroll_length must be >= 0")
        if self.opponent_pool < 0:
            raise ValueError("anakin.opponent_pool must be >= 0")
        if (self.opponent_pool
                and self.num_envs % (self.opponent_pool + 1) != 0):
            raise ValueError(
                "anakin.num_envs must divide evenly into "
                f"opponent_pool + 1 = {self.opponent_pool + 1} groups "
                "(the opponent axis is a static factor of the env axis)")
        return self
