"""numlint rule suite: every dtype/precision rule fires on its positive
fixture, stays quiet on its negative, and obeys suppression comments —
plus the dtype-lattice machinery (config facts, weak-type promotion
algebra, param seeding from call sites, the compute-set closure through
function-valued jit/grad arguments), the unified-CLI surface (--num),
and the repo gate: the shipped package must num-lint clean WITH the
lattice verifiably populated (the ``compute_dtype``/``obs_store``
config facts, the update step's bf16 cast summary, and the loss path
inside the compute set must all be discovered, or the gate would be
vacuously green).

Fixture convention (tests/fixtures/numlint/): ``<rule>_pos.py`` must
produce findings of exactly that rule under the base+num rule set,
``<rule>_neg.py`` and ``<rule>_supp.py`` must produce none (driver
shared with the base/shard/comm/race suites: tests/lintfix.py).  The
fixtures are parsed, never imported."""

import json
import os

import pytest
from lintfix import check_fixture, fixture_path

from handyrl_tpu.analysis.astutil import ModuleInfo, Package
from handyrl_tpu.analysis.commrules import COMM_RULES
from handyrl_tpu.analysis.jaxlint import (
    active_registry,
    lint_paths,
    load_package,
    main,
)
from handyrl_tpu.analysis.numlint import (
    DtypeFact,
    analyze_num,
    parse_dtype,
    promote,
)
from handyrl_tpu.analysis.numrules import NUM_RULES
from handyrl_tpu.analysis.racerules import RACE_RULES
from handyrl_tpu.analysis.rules import RULES
from handyrl_tpu.analysis.shardrules import SHARD_RULES

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "numlint")
REPO_PACKAGE = os.path.join(
    os.path.dirname(__file__), "..", "handyrl_tpu")

RULE_IDS = sorted(NUM_RULES)


def fixture(rule_id, kind):
    return fixture_path("numlint", rule_id, kind)


def _analyze(src):
    package = Package([ModuleInfo("m", "m", src)])
    return analyze_num(package), package


def _fn(package, qname):
    return next(fn for fn in package.all_functions()
                if fn.qname == qname)


@pytest.mark.parametrize("kind", ["pos", "neg", "supp"])
@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_fixture(rule_id, kind):
    check_fixture("numlint", rule_id, kind, num=True)


def test_num_registry_is_exactly_the_issue_rule_set():
    assert set(RULE_IDS) == {
        "implicit-upcast", "weak-type-promotion", "lowp-accum",
        "unguarded-cast", "dtype-split-brain", "nonfinite-risk"}


def test_registries_do_not_collide():
    # one suppression namespace across all five families
    assert not set(NUM_RULES) & set(RULES)
    assert not set(NUM_RULES) & set(SHARD_RULES)
    assert not set(NUM_RULES) & set(COMM_RULES)
    assert not set(NUM_RULES) & set(RACE_RULES)
    combined = active_registry(shard=True, comm=True, race=True,
                               num=True)
    assert set(combined) == (set(RULES) | set(SHARD_RULES)
                             | set(COMM_RULES) | set(RACE_RULES)
                             | set(NUM_RULES))


def test_other_family_fixtures_stay_quiet_under_num_rules():
    """The base/shard/comm/race fixtures must not trip the num rules:
    the five families stay independently testable."""
    for family in ("jaxlint", "shardlint", "commlint", "racelint"):
        tree = os.path.join(os.path.dirname(__file__), "fixtures",
                            family)
        findings = lint_paths([tree], num=True,
                              select=sorted(NUM_RULES))
        assert findings == [], (
            f"num rules fired on {family} fixtures: "
            f"{[(f.rule, f.path, f.line) for f in findings]}")


def test_num_fixtures_stay_quiet_under_shard_rules():
    findings = lint_paths([FIXTURES], shard=True,
                          select=sorted(SHARD_RULES))
    assert findings == [], (
        f"shard rules fired on num fixtures: "
        f"{[(f.rule, f.path, f.line) for f in findings]}")


# -- dtype lattice machinery -------------------------------------------

def test_promote_weak_scalar_does_not_widen_concrete():
    """JAX weak-type semantics: a Python float times a bf16 array
    stays bf16; two concrete float widths promote to the wider."""
    bf16 = DtypeFact("bfloat16")
    weak = DtypeFact("float32", weak=True)
    assert promote(bf16, weak).dtype == "bfloat16"
    assert promote(weak, bf16).dtype == "bfloat16"
    f32 = DtypeFact("float32")
    assert promote(bf16, f32).dtype == "float32"
    # bf16 x fp16 have equal rank: JAX resolves the tie at float32
    assert promote(bf16, DtypeFact("float16")).dtype == "float32"


def test_parse_dtype_canonicalizes_spellings():
    assert parse_dtype("bf16") == "bfloat16"
    assert parse_dtype("jnp.bfloat16") == "bfloat16"
    assert parse_dtype("half") == "float16"
    assert parse_dtype("np.uint8") == "uint8"
    assert parse_dtype("not-a-dtype") is None


def test_config_facts_are_harvested_package_wide():
    an, _ = _analyze(
        "import numpy as np\n\n"
        "class Cfg:\n"
        "    def __init__(self, cfg):\n"
        "        self.compute_dtype = cfg.get('compute_dtype') "
        "or 'bfloat16'\n"
        "        self.obs_store = {'uint8': np.uint8}.get('uint8', "
        "np.float32)\n")
    assert "bfloat16" in an.config_facts.get("compute_dtype", set())
    assert "uint8" in an.config_facts.get("obs_store", set())


def test_param_dtypes_seed_from_call_sites_and_defaults():
    """The make_apply_fn idiom: a param named after a config fact
    inherits the configured dtype on top of its literal default."""
    an, pkg = _analyze(
        "import jax.numpy as jnp\n\n"
        "compute_dtype = 'bfloat16'\n\n"
        "def make(compute_dtype='float32'):\n"
        "    dtype = jnp.dtype(compute_dtype)\n"
        "    return cast(dtype)\n\n"
        "def cast(dtype):\n"
        "    return jnp.zeros((2,)).astype(dtype)\n")
    cast = _fn(pkg, "m:cast")
    assert an.param_dtypes[cast]["dtype"] >= {"bfloat16", "float32"}
    assert an.fn_casts[cast] >= {"bfloat16", "float32"}


def test_compute_set_closes_over_function_valued_grad_args():
    """`jax.grad(loss_fn)` inside a jitted step pulls loss_fn AND its
    callees into the compute set — the channel that puts the real loss
    path in scope for the compute-only rules."""
    an, pkg = _analyze(
        "import jax\n\n"
        "@jax.jit\n"
        "def step(params, batch):\n"
        "    return jax.grad(loss_fn)(params, batch)\n\n"
        "def loss_fn(params, batch):\n"
        "    return helper(params)\n\n"
        "def helper(params):\n"
        "    return params\n\n"
        "def host_only(x):\n"
        "    return x\n")
    names = {fn.qname for fn in an.compute_fns}
    assert {"m:step", "m:loss_fn", "m:helper"} <= names
    assert "m:host_only" not in names


def test_return_summary_flows_across_calls():
    """A callee that always returns bf16 seeds the caller's local —
    the interprocedural edge behind cross-function upcast findings."""
    an, pkg = _analyze(
        "import jax\nimport jax.numpy as jnp\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    h = embed(x)\n"
        "    return h\n\n"
        "def embed(x):\n"
        "    return x.astype(jnp.bfloat16)\n")
    embed = _fn(pkg, "m:embed")
    assert an.returns[embed] == DtypeFact("bfloat16")


# -- CLI ---------------------------------------------------------------

def test_cli_num_flag_runs_num_rules(capsys):
    rc = main(["--num", "--json", fixture("lowp-accum", "pos")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["findings"]
    assert all(f["rule"] == "lowp-accum" for f in out["findings"])


def test_cli_without_num_flag_skips_num_rules(capsys):
    rc = main([fixture("lowp-accum", "pos")])
    assert rc == 0
    assert "clean" in capsys.readouterr().out


def test_cli_num_composes_with_other_families(capsys):
    rc = main(["--shard", "--comm", "--race", "--num", "--json",
               fixture("nonfinite-risk", "pos")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert all(f["rule"] == "nonfinite-risk"
               for f in out["findings"])


def test_cli_list_rules_shows_num_family_without_flag(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in sorted(NUM_RULES):
        assert rule_id in out


def test_cli_select_accepts_num_rules_only_with_flag(capsys):
    assert main(["--select", "lowp-accum", FIXTURES]) == 2
    capsys.readouterr()
    rc = main(["--num", "--select", "lowp-accum",
               fixture("lowp-accum", "pos")])
    assert rc == 1


def test_cli_sarif_includes_num_rules(capsys):
    rc = main(["--num", "--sarif", fixture("implicit-upcast", "pos")])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    rule_ids = {r["id"]
                for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert set(NUM_RULES) <= rule_ids


# -- repo gate ---------------------------------------------------------

def test_repo_numlints_clean():
    """The CI gate, enforced locally too: the shipped package must have
    zero unsuppressed findings under the base+num rule set."""
    findings = lint_paths([REPO_PACKAGE], num=True)
    assert findings == [], "\n".join(
        f"{f.location}: [{f.rule}] {f.message}" for f in findings)


def test_repo_all_five_families_clean():
    findings = lint_paths([REPO_PACKAGE], shard=True, comm=True,
                          race=True, num=True)
    assert findings == [], "\n".join(
        f"{f.location}: [{f.rule}] {f.message}" for f in findings)


def test_repo_dtype_lattice_is_populated():
    """The gate above is only meaningful if the analyzer actually SEES
    the repo's precision structure: the mixed-precision config facts,
    the update path's bf16/fp32 cast pair, and the loss functions
    inside the compute set must all be discovered, or a refactor that
    hides them would silently disable every dtype rule."""
    package, _, errors = load_package([REPO_PACKAGE])
    assert errors == []
    an = analyze_num(package)
    # the package-wide config facts: the compute dtype defaults to
    # bfloat16 and observations ride the wire as uint8
    assert "bfloat16" in an.config_facts.get("compute_dtype", set())
    assert "uint8" in an.config_facts.get("obs_store", set())
    # the update step's cast summary: make_apply_fn/_cast_floats cast
    # to BOTH the bf16 compute dtype and the fp32 master dtype
    update_casts = set()
    for fn in package.all_functions():
        if fn.module.name == "handyrl_tpu.ops.update":
            update_casts |= an.fn_casts.get(fn, set())
    assert {"bfloat16", "float32"} <= update_casts
    # the compute-set closure reaches the loss path through
    # `jax.grad(loss_fn)` even though `jax.jit(core)` jits a
    # function-valued parameter the base engine cannot resolve
    names = {fn.qname for fn in an.compute_fns}
    assert "handyrl_tpu.ops.losses:compute_loss" in names
    assert "handyrl_tpu.ops.losses:compose_losses" in names
    assert "handyrl_tpu.ops.update:make_update_core.loss_fn" in names


def test_repo_suppressions_all_carry_reasons():
    """Zero unexplained suppressions, re-checked end to end (the
    bare-suppression rule enforces the same convention inline)."""
    import re
    pat = re.compile(r"#\s*jaxlint:\s*(disable=[^\n]*|skip-file[^\n]*)")
    for dirpath, _, files in os.walk(REPO_PACKAGE):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path) as f:
                for i, line in enumerate(f, 1):
                    m = pat.search(line)
                    if m is None:
                        continue
                    assert " -- " in m.group(0), (
                        f"{path}:{i}: suppression without a reason: "
                        f"{line.strip()}")
