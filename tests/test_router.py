"""The replica-pool routing tier (handyrl_tpu.serving.registry +
.router, docs/serving.md "Pool routing"): RouterConfig validation, the
registry's exact-clock lifecycle (expiry/eviction, generation bumps,
drain vs suspect, routing policies), the announcer's register/beat/
re-register loop, the router frontend over real TCP (an unmodified
ServeClient cannot tell the pool from one frontend), healthz from
registry bookkeeping with a no-replica-dialed proof, and the tier-1
multi-replica chaos drill (kill 1 of 2 replicas mid-load)."""

import hashlib
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from handyrl_tpu.pipeline.config import PipelineConfig
from handyrl_tpu.serving import RouterConfig, ServingConfig
from handyrl_tpu.serving.client import ServeClient, ServeError, ShedError
from handyrl_tpu.serving.frontend import ServingFrontend
from handyrl_tpu.serving.registry import ReplicaAnnouncer, ServiceRegistry
from handyrl_tpu.serving.router import RouterFrontend


# ---------------------------------------------------------------------
# config
# ---------------------------------------------------------------------

def test_router_config_defaults_off_and_validates():
    cfg = RouterConfig.from_config(None)
    assert cfg.mode == "off" and not cfg.enabled
    cfg = RouterConfig.from_config({"mode": "on", "port": 0})
    assert cfg.enabled and cfg.port == 0
    with pytest.raises(ValueError):
        RouterConfig.from_config({"mode": "sideways"})
    with pytest.raises(ValueError):
        RouterConfig.from_config({"bogus_key": 1})
    with pytest.raises(ValueError):
        RouterConfig.from_config({"policy": "random"})
    with pytest.raises(ValueError):
        RouterConfig.from_config({"heartbeat_interval": 0})
    with pytest.raises(ValueError):
        # the timeout must exceed the beat cadence or every replica
        # flaps between beats
        RouterConfig.from_config({"heartbeat_interval": 2.0,
                                  "heartbeat_timeout": 1.0})
    with pytest.raises(ValueError):
        RouterConfig.from_config({"max_attempts": 0})
    with pytest.raises(ValueError):
        RouterConfig.from_config({"reply_timeout": 0})
    with pytest.raises(ValueError):
        RouterConfig.from_config({"replica_failures": -1})
    with pytest.raises(ValueError):
        RouterConfig.from_config({"failure_window": 0})


def test_train_config_requires_serving_for_router():
    """The router fronts serving replicas: router on with serving off
    is a config error, not a silently idle pool."""
    from handyrl_tpu.config import Config

    raw = {"env_args": {"env": "TicTacToe"},
           "train_args": {"router": {"mode": "on", "port": 0}}}
    with pytest.raises(ValueError, match="router.mode"):
        Config.from_dict(raw)
    raw["train_args"]["serving"] = {"mode": "on", "port": 0}
    cfg = Config.from_dict(raw)
    assert cfg.train_args["router"]["mode"] == "on"


def test_serving_config_validates_router_address():
    cfg = ServingConfig.from_config(
        {"mode": "on", "port": 0, "router_address": "10.0.0.1:9994"})
    assert cfg.router_address == "10.0.0.1:9994"
    with pytest.raises(ValueError):
        ServingConfig.from_config(
            {"mode": "on", "router_address": "nocolon"})


# ---------------------------------------------------------------------
# registry lifecycle (injectable clock: expiry tests are exact)
# ---------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def _advert(port=1000, **over):
    out = {"port": port, "capacity": 8, "inflight": 0, "p99_ms": 1.0,
           "slo_breached": False, "epochs": [1]}
    out.update(over)
    return out


def test_registry_evicts_silent_replicas_exactly_on_timeout():
    clock = _FakeClock()
    reg = ServiceRegistry(heartbeat_timeout=6.0, clock=clock)
    assert reg.register("a", _advert()) == 0
    assert reg.register("b", _advert(port=2000)) == 0
    clock.now = 4.0
    assert reg.beat("a", _advert())
    # b has been silent 4s < timeout: both still routable
    assert reg.sweep() == [] and reg.pool_size() == 2
    clock.now = 6.0
    # b is now silent EXACTLY the timeout: boundary is inclusive-alive
    assert reg.sweep() == [] and reg.pool_size() == 2
    clock.now = 6.01
    assert reg.sweep() == ["b"]
    assert reg.pool_size() == 1 and reg.evictions == 1
    # a beat from the evicted name is refused — the re-register trigger
    assert not reg.beat("b", _advert(port=2000))
    assert reg.beat("a", _advert())


def test_reregistration_bumps_generation_across_eviction():
    clock = _FakeClock()
    reg = ServiceRegistry(heartbeat_timeout=1.0, clock=clock)
    assert reg.register("r", _advert()) == 0
    assert reg.generation("r") == 0
    clock.now = 5.0
    assert reg.sweep() == ["r"]
    assert reg.generation("r") is None
    # generation memory SURVIVES eviction: the respawned replica's
    # re-register is observably a rejoin, not a first sight
    assert reg.register("r", _advert()) == 1
    assert reg.generation("r") == 1
    assert reg.register("r", _advert()) == 2
    assert reg.registrations == 3


def test_drain_is_sticky_but_suspect_clears_on_beat():
    clock = _FakeClock()
    reg = ServiceRegistry(heartbeat_timeout=10.0, clock=clock)
    reg.register("r", _advert())
    # suspect (the router's FailureWindow verdict) recovers on a beat
    reg.drain("r", suspect=True)
    assert reg.pool_size() == 0
    assert reg.beat("r", _advert())
    assert reg.pool_size() == 1
    # a graceful drain is the replica's explicit goodbye: beats keep
    # the entry fresh but never make it routable again
    reg.drain("r")
    assert reg.beat("r", _advert())
    assert reg.pool_size() == 0
    assert reg.snapshot()["replicas"]["r"]["draining"]
    # only a re-register (a fresh incarnation) undoes the goodbye
    reg.register("r", _advert())
    assert reg.pool_size() == 1


def test_least_loaded_spreads_away_from_the_hot_replica():
    clock = _FakeClock()
    reg = ServiceRegistry(heartbeat_timeout=10.0, clock=clock)
    reg.register("hot", _advert(p99_ms=50.0, inflight=6))
    reg.register("cold", _advert(port=2000, p99_ms=2.0))
    assert reg.pick() == "cold"
    # the router's own in-flight view counts too (adverts lag a beat)
    for _ in range(200):
        reg.note_inflight("cold", +1)
    assert reg.pick() == "hot"
    for _ in range(300):
        reg.note_inflight("cold", -1)  # floors at 0, never negative
    assert reg.snapshot()["replicas"]["cold"]["inflight"] == 0
    assert reg.pick() == "cold"


def test_pin_routes_only_to_advertising_replicas():
    clock = _FakeClock()
    reg = ServiceRegistry(heartbeat_timeout=10.0, clock=clock)
    reg.register("old", _advert(epochs=[1, 7]))
    reg.register("new", _advert(port=2000, epochs=[1], p99_ms=0.1))
    # unpinned goes least-loaded (new is cheaper)...
    assert reg.pick() == "new"
    # ...but the epoch-7 pin must land on its advertiser
    assert reg.pick(pin=7) == "old"
    assert reg.pick(pin=7, exclude={"old"}) is None
    assert reg.pick(pin=99) is None
    # eviction re-routes the pin to any surviving advertiser
    reg.register("new", _advert(port=2000, epochs=[1, 7]))
    reg.drain("old")
    assert reg.pick(pin=7) == "new"


def test_rendezvous_hash_keeps_seats_put_across_pool_changes():
    clock = _FakeClock()
    reg = ServiceRegistry(heartbeat_timeout=10.0, clock=clock)
    names = ["r0", "r1", "r2"]
    for i, n in enumerate(names):
        reg.register(n, _advert(port=1000 + i))

    def hrw(cands, seat):
        return max(cands, key=lambda n: (int(hashlib.md5(
            f"{n}|{seat}".encode()).hexdigest(), 16), n))

    picks = {s: reg.pick(seat=s, policy="hash") for s in range(32)}
    assert picks == {s: hrw(names, s) for s in range(32)}
    # an UNRELATED addition moves only seats that hash onto it —
    # highest-random-weight, not modulo
    reg.register("r3", _advert(port=1003))
    for s in range(32):
        if hrw(names + ["r3"], s) != "r3":
            assert reg.pick(seat=s, policy="hash") == picks[s]
    # removing a replica remaps ONLY its seats
    reg.drain("r1")
    for s in range(32):
        if picks[s] != "r1":
            assert reg.pick(seat=s, policy="hash") in (picks[s], "r3")
        else:
            assert reg.pick(seat=s, policy="hash") != "r1"


def test_all_breached_is_the_whole_pool_signal():
    clock = _FakeClock()
    reg = ServiceRegistry(heartbeat_timeout=10.0, clock=clock)
    assert not reg.all_breached()  # empty pool is pool_down, not SLO
    reg.register("a", _advert(slo_breached=True))
    reg.register("b", _advert(port=2000, slo_breached=False))
    assert not reg.all_breached()
    reg.beat("b", _advert(port=2000, slo_breached=True))
    assert reg.all_breached()


# ---------------------------------------------------------------------
# announcer <-> router registry verbs (real TCP, no serving replicas)
# ---------------------------------------------------------------------

def _router(**over):
    cfg = RouterConfig.from_config({
        "mode": "on", "port": 0, "heartbeat_interval": 0.05,
        "heartbeat_timeout": 1.0, "reply_timeout": 3.0,
        "replica_failures": 0, "failure_window": 5.0, **over})
    router = RouterFrontend(cfg)
    router.start()
    return router


def _wait(cond, deadline=10.0, msg="condition never held"):
    limit = time.monotonic() + deadline
    while not cond():
        assert time.monotonic() < limit, msg
        time.sleep(0.01)


def test_announcer_registers_beats_and_reregisters_after_eviction():
    router = _router()
    ann = ReplicaAnnouncer(
        "127.0.0.1", router.port, "r0",
        lambda: {"port": 1234, "epochs": [1]},
        interval=2.0, retry_interval=0.05)
    try:
        ann.start()
        _wait(lambda: ann.generation == 0, msg="register never landed")
        # the router owns the cadence: the ack's interval replaced ours
        assert ann.interval == router.cfg.heartbeat_interval
        _wait(lambda: router.registry.snapshot()
              ["replicas"].get("r0", {}).get("beats", 0) >= 2,
              msg="beats never flowed")
        assert router.registry.generation("r0") == 0
        # forced eviction (a future-now sweep): the next beat answers
        # the typed unknown-replica error, the announcer re-registers,
        # and the registry's generation bump records the rejoin
        router.registry.sweep(now=router.clock() + 100.0)
        _wait(lambda: router.registry.generation("r0") == 1,
              msg="re-register never landed")
        assert ann.registrations >= 2
        # graceful close sends the drain goodbye: the entry survives
        # (in-flight completes) but is never picked again
        ann.close()
        _wait(lambda: router.registry.snapshot()
              ["replicas"].get("r0", {}).get("draining", False),
              msg="drain never landed")
        assert router.registry.pool_size() == 0
    finally:
        ann.close()
        router.close()


def test_router_sheds_pool_down_on_an_empty_pool():
    router = _router()
    client = None
    try:
        client = ServeClient("127.0.0.1", router.port, timeout=5.0)
        with pytest.raises(ShedError) as err:
            client.infer_batch(np.zeros((1, 2), np.float32))
        assert err.value.reason == "pool_down"
        stats = client.stats()
        assert stats["pool_sheds"] == 1
        assert stats["shed_by"] == {"pool_down": 1}
        assert stats["submitted"] == (stats["ok"] + stats["shed"]
                                      + stats["errors"])
    finally:
        if client is not None:
            client.close()
        router.close()


# ---------------------------------------------------------------------
# the pool over real TCP: 2 replica stacks behind one router
# ---------------------------------------------------------------------

class _StubEnv:
    def players(self):
        return [0]

    def reset(self):
        pass

    def observation(self, player):
        return np.zeros(2, np.float32)


class _StubModel:
    """Policy = tag + row index: replies prove WHICH replica answered."""

    module = "stub"

    def __init__(self, tag=0.0):
        self.tag = float(tag)
        self.calls = []

    def inference_batch(self, obs, hidden=None):
        rows = obs.shape[0]
        self.calls.append(rows)
        return {"policy": self.tag + np.tile(
            np.arange(rows, dtype=np.float32)[:, None], (1, 3))}


class _Pool:
    """N real serving stacks (stub model + InferenceService +
    ServingFrontend + ReplicaAnnouncer) registered into one router."""

    def __init__(self, n=2, router_over=None, epochs=None):
        from handyrl_tpu.pipeline.service import InferenceService

        self.router = _router(**(router_over or {}))
        self.models, self.services = [], []
        self.frontends, self.announcers = [], []
        env = _StubEnv()
        for i in range(n):
            model = _StubModel(tag=1000.0 * i)
            pcfg = PipelineConfig.from_config(
                {"mode": "on", "batch_window": 0.001, "max_batch": 16})
            svc = InferenceService(model, pcfg, epoch=1)
            svc.start()
            scfg = ServingConfig.from_config(
                {"mode": "on", "port": 0, "slo_ms": 0.0,
                 "reply_timeout": 3.0})
            fe = ServingFrontend(svc, env, scfg)
            fe.start()
            eps = (epochs or [(1,)] * n)[i]
            ann = ReplicaAnnouncer(
                "127.0.0.1", self.router.port, f"replica-{i}",
                (lambda fe=fe, eps=eps: fe.advert(epochs=eps)),
                interval=self.router.cfg.heartbeat_interval,
                retry_interval=0.05)
            ann.start()
            self.models.append(model)
            self.services.append(svc)
            self.frontends.append(fe)
            self.announcers.append(ann)
        _wait(lambda: self.router.registry.pool_size() >= n,
              msg="pool never formed")

    def close(self):
        for ann in self.announcers:
            ann.close(drain=False)
        self.router.close()
        for fe in self.frontends:
            fe.close()
        for svc in self.services:
            svc.close()


def test_pool_serves_unmodified_clients_and_reconciles():
    pool = _Pool(n=2)
    client = None
    try:
        client = ServeClient("127.0.0.1", pool.router.port, timeout=5.0)
        batch = np.zeros((3, 2), np.float32)
        tags = set()
        for _ in range(8):
            reply = client.infer_batch(batch)
            assert reply["epoch"] == 1
            assert reply["outputs"]["policy"].shape == (3, 3)
            # the tag digit identifies the serving replica
            tags.add(float(reply["outputs"]["policy"][0, 0]))
        assert tags <= {0.0, 1000.0}
        # live-epoch pin serves through the pool like a direct client
        reply = client.infer_batch(batch, epoch=1)
        assert reply["epoch"] == 1
        # the stats verb answers the ROUTER's counters, reconciled
        stats = client.stats()
        assert stats["submitted"] >= 9
        assert stats["submitted"] == (stats["ok"] + stats["shed"]
                                      + stats["errors"])
        assert stats["registry"]["pool_size"] == 2
        # a replica error is forwarded verbatim (bad schema stays typed)
        with pytest.raises(ServeError, match="bad request"):
            client.infer_batch(np.zeros((2, 9), np.float32))
        assert client.infer_batch(batch)["epoch"] == 1  # conn survives
    finally:
        if client is not None:
            client.close()
        pool.close()


def test_hash_policy_pins_a_seat_to_one_replica():
    pool = _Pool(n=2, router_over={"policy": "hash"})
    client = None
    try:
        client = ServeClient("127.0.0.1", pool.router.port, timeout=5.0)
        batch = np.zeros((1, 2), np.float32)
        expect = max(
            ("replica-0", "replica-1"),
            key=lambda n: (int(hashlib.md5(
                f"{n}|league-seat-3".encode()).hexdigest(), 16), n))
        tag = 1000.0 * int(expect[-1])
        for _ in range(6):
            reply = client.infer_batch(batch, seat="league-seat-3")
            assert float(reply["outputs"]["policy"][0, 0]) == tag
    finally:
        if client is not None:
            client.close()
        pool.close()


def test_unroutable_pin_answers_typed_error_not_a_shed():
    pool = _Pool(n=2)
    client = None
    try:
        client = ServeClient("127.0.0.1", pool.router.port, timeout=5.0)
        with pytest.raises(ServeError, match="snapshot 42 unavailable"):
            client.infer_batch(np.zeros((1, 2), np.float32), epoch=42)
        stats = client.stats()
        assert stats["errors"] == 1 and stats["shed"] == 0
        assert stats["pool_sheds"] == 0  # a live pool: not pool_down
    finally:
        if client is not None:
            client.close()
        pool.close()


def test_per_replica_sheds_reroute_but_pool_wide_sheds_escalate():
    pool = _Pool(n=2)
    client = None
    try:
        client = ServeClient("127.0.0.1", pool.router.port, timeout=5.0)
        batch = np.zeros((1, 2), np.float32)
        # jam ONE replica's admission (inflight at cap => "overload"):
        # the router re-routes to the other; the client never sees it
        fe0 = pool.frontends[0]
        fe0.inflight = fe0.cfg.max_inflight
        for _ in range(4):
            assert client.infer_batch(batch)["epoch"] == 1
        assert pool.router.stats()["pool_sheds"] == 0
        # jam BOTH: every attempted replica sheds — the POOL breached,
        # and the escalation is typed pool_overload (counted)
        fe1 = pool.frontends[1]
        fe1.inflight = fe1.cfg.max_inflight
        with pytest.raises(ShedError) as err:
            client.infer_batch(batch)
        assert err.value.reason == "pool_overload"
        stats = pool.router.stats()
        assert stats["pool_sheds"] == 1
        assert stats["shed_by"].get("pool_overload") == 1
        assert stats["reroutes"] >= 1
        # release both gates: the pool serves again
        fe0.inflight = 0
        fe1.inflight = 0
        assert client.infer_batch(batch)["epoch"] == 1
        stats = client.stats()
        assert stats["submitted"] == (stats["ok"] + stats["shed"]
                                      + stats["errors"])
    finally:
        if client is not None:
            client.close()
        pool.close()


def test_epoch_stats_report_the_metrics_contract_keys():
    pool = _Pool(n=2)
    client = None
    try:
        client = ServeClient("127.0.0.1", pool.router.port, timeout=5.0)
        assert client.infer_batch(
            np.zeros((1, 2), np.float32))["epoch"] == 1
        out = pool.router.epoch_stats()
        assert out["router_requests"] == 1 and out["router_ok"] == 1
        assert out["router_shed"] == 0 and out["router_errors"] == 0
        assert out["router_pool_size"] == 2
        assert out["reroutes"] == 0 and out["pool_sheds"] == 0
        # reset: the next epoch starts from zero (pool size is a gauge)
        again = pool.router.epoch_stats()
        assert again["router_requests"] == 0
        assert again["router_pool_size"] == 2
    finally:
        if client is not None:
            client.close()
        pool.close()


# ---------------------------------------------------------------------
# healthz: registry bookkeeping only — no replica is dialed
# ---------------------------------------------------------------------

def test_healthz_answers_from_the_registry_without_dialing_replicas():
    import socket as socket_mod

    from handyrl_tpu.telemetry.status import StatusServer

    router = _router()
    status = StatusServer(0, router.stats, healthz_fn=router.healthz)
    probe = socket_mod.socket()
    try:
        url = f"http://127.0.0.1:{status.port}/healthz"
        # empty pool: the probe answers (200, bookkeeping) but not-ok
        with urllib.request.urlopen(url, timeout=10) as r:
            body = json.loads(r.read())
        assert body == {"ok": False, "pool_size": 0, "generation": 0}
        # register a replica whose advertised endpoint is a listener
        # WE own: if healthz dialed replicas, it would have to connect
        # here — the accept queue staying empty is the proof
        probe.bind(("127.0.0.1", 0))
        probe.listen(1)
        probe.setblocking(False)
        router.registry.register(
            "fake", _advert(port=probe.getsockname()[1]))
        with urllib.request.urlopen(url, timeout=10) as r:
            body = json.loads(r.read())
        assert body == {"ok": True, "pool_size": 1, "generation": 0}
        with pytest.raises(BlockingIOError):
            probe.accept()  # nobody ever dialed the replica
        # the full snapshot view rides the same no-dial contract
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/", timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["registry"]["pool_size"] == 1
        assert "fake" in snap["registry"]["replicas"]
        with pytest.raises(BlockingIOError):
            probe.accept()
    finally:
        probe.close()
        status.close()
        router.close()


# ---------------------------------------------------------------------
# tier-1 chaos drill: kill 1 of 2 replicas mid-load
# ---------------------------------------------------------------------

def test_chaos_kill_one_replica_evicts_reroutes_and_respawns():
    """DELIBERATELY IN TIER-1 (deterministic, seconds): the acceptance
    drill for the pool's failure model.  Kill 1 of 2 replicas SILENTLY
    (frontend + announcer, no goodbye) under epoch-pinned load:

      * zero lost in-flight — every request answers typed (ok with the
        pinned epoch, shed with a reason, or error), none time out;
      * the corpse is evicted within router.heartbeat_timeout (+ one
        accept poll + one beat of advert lag);
      * the reconciliation invariant holds exactly at the router;
      * respawn re-registers under the same name with a GENERATION
        BUMP, and the pool serves from both replicas again."""
    rt_over = {"heartbeat_interval": 0.1, "heartbeat_timeout": 1.0}
    pool = _Pool(n=2, router_over=rt_over)
    outcomes = {"ok": 0, "shed": 0, "error": 0, "lost": 0}
    bad_epochs = []
    stop = threading.Event()
    lock = threading.Lock()

    def load():
        client = ServeClient("127.0.0.1", pool.router.port,
                             timeout=10.0)
        batch = np.zeros((2, 2), np.float32)
        try:
            while not stop.is_set():
                try:
                    reply = client.infer_batch(batch, epoch=1)
                    with lock:
                        outcomes["ok"] += 1
                        if reply["epoch"] != 1:
                            bad_epochs.append(reply["epoch"])
                except ShedError:
                    with lock:
                        outcomes["shed"] += 1
                except ServeError:
                    with lock:
                        outcomes["error"] += 1
                except Exception:
                    # a transport failure or timeout at the CLIENT is
                    # a lost request — the drill's zero-loss clause
                    with lock:
                        outcomes["lost"] += 1
        finally:
            client.close()

    threads = [threading.Thread(target=load, daemon=True)
               for _ in range(3)]
    try:
        for t in threads:
            t.start()
        _wait(lambda: outcomes["ok"] >= 20, msg="load never warmed")

        # -- the silent kill: announcer first (no drain goodbye), then
        # the frontend dies like a crashed process
        victim_fe = pool.frontends[0]
        victim_ann = pool.announcers[0]
        victim_ann.kill()
        victim_fe.inject_kill()
        t_kill = time.monotonic()

        # eviction within the configured timeout: the sweep rides the
        # accept poll, and the last beat lags by up to one cadence
        _wait(lambda: pool.router.registry.generation("replica-0")
              is None, deadline=10.0, msg="corpse never evicted")
        elapsed = time.monotonic() - t_kill
        budget = (pool.router.cfg.heartbeat_timeout
                  + pool.router.cfg.heartbeat_interval
                  + 2 * RouterFrontend.ACCEPT_TIMEOUT)
        assert elapsed <= budget, (
            f"eviction took {elapsed:.2f}s > {budget:.2f}s")

        # pinned load keeps serving through the survivor
        ok_at_evict = outcomes["ok"]
        _wait(lambda: outcomes["ok"] >= ok_at_evict + 20,
              msg="survivor never served")

        # -- respawn: fresh port, same name — the announcer's
        # re-register must show up as a generation bump
        victim_fe.respawn()
        victim_ann.respawn()
        _wait(lambda: pool.router.registry.generation("replica-0") == 1,
              msg="generation bump never observed")
        _wait(lambda: pool.router.registry.pool_size() == 2,
              msg="pool never recovered")
        ok_at_respawn = outcomes["ok"]
        _wait(lambda: outcomes["ok"] >= ok_at_respawn + 20,
              msg="recovered pool never served")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        stats = pool.router.stats()
        pool.close()

    # zero lost epoch-pinned in-flight: every request answered typed,
    # and every ok carried the pinned snapshot
    assert outcomes["lost"] == 0, f"lost in-flight requests: {outcomes}"
    assert bad_epochs == []
    assert outcomes["error"] == 0, f"typed errors under pin: {outcomes}"
    # reconciliation holds EXACTLY at the router, and any sheds that
    # happened in the eviction gap are typed pool-level escalations
    assert stats["submitted"] == (stats["ok"] + stats["shed"]
                                  + stats["errors"])
    assert stats["submitted"] >= outcomes["ok"]
    for reason, count in stats["shed_by"].items():
        assert reason.startswith("pool_") and count > 0
    # the kill was detected through the failure path, not a goodbye:
    # eviction counted, and the dying host was suspect-drained (or the
    # sweep beat the first forward to it)
    assert stats["registry"]["evictions"] >= 1
    assert stats["registry"]["registrations"] >= 3  # 2 joins + rejoin
