"""Actor-side runtime: workers, gather fan-in, local & remote clusters.

Capability parity with the reference actor plane
(/root/reference/handyrl/worker.py): CPU worker processes run
self-play or evaluation jobs; a small tree of Gather processes batches
their traffic so the learner serves O(gathers) connections instead of
O(workers); remote machines join elastically through a one-shot entry
handshake.

The wire protocol is shared with the learner and is therefore fixed:
request tuples ``(verb, payload)`` with verbs ``args`` / ``model`` /
``episode`` / ``result`` (payload may be a list for batched requests),
job-args dicts ``{role, player, model_id}``, and the two well-known
ports below.  Everything else — model caching, job prefetch, upload
batching — is organized framework-side here.

TPU-native specifics: every child process pins JAX to the CPU backend
(``force_cpu_jax``) — actor inference is a CPU-jitted forward; the TPU
belongs to the learner's update step alone.  Processes are spawned,
not forked, because PJRT clients do not survive fork.

Ports (same numbers as the reference so operational docs carry over):
  9999 — entry: one-shot handshake assigning worker-id blocks
  9998 — worker: persistent gather connections
"""

import copy
import functools
import pickle
import queue
import random
import threading
import time
from collections import OrderedDict, deque
from socket import gethostname

from .connection import (
    QueueCommunicator,
    TracedConnection,
    _mp,
    accept_socket_connections,
    force_cpu_jax,
    open_multiprocessing_connections,
    open_socket_connection,
    send_recv,
)
from . import telemetry
from .telemetry import payload_trace

ENTRY_PORT = 9999
WORKER_PORT = 9998

_PEER_GONE = (ConnectionResetError, BrokenPipeError, EOFError, OSError)


class ModelCache:
    """Resolves model ids to actor-side models, fetching snapshots from
    the learner on miss.

    Id conventions (protocol): ``id < 0`` is an empty opponent slot,
    ``id == 0`` is the uniform-random stand-in, positive ids are
    learner epochs.  A small LRU keeps the newest epoch plus recent
    old-epoch opponents (league/past-self play) warm, and when a new
    epoch arrives with the same net structure the previous instance is
    re-pointed at the new params — preserving its compiled inference
    function across epochs instead of re-jitting every 200 episodes.
    """

    CAPACITY = 3  # newest epoch + a couple of league opponents

    def __init__(self, conn, env):
        self._conn = conn
        self._env = env
        self._cache = OrderedDict()  # model_id -> model (LRU order)
        self._newest_id = -1

    def _adopt(self, model):
        """Warm the new epoch's model with the previous newest
        instance's compiled inference function.  Params are passed as
        jit *arguments*, so the trace is weight-independent; the cached
        instance itself is left untouched (it may still serve its own
        epoch in the same resolve call)."""
        prev = self._cache.get(self._newest_id)
        if prev is None or not hasattr(prev, "module"):
            return model
        try:
            if prev.module == model.module:
                model._jitted = prev._jitted
        except Exception:
            pass
        return model

    def _fetch(self, model_id):
        from .models import RandomModel

        blob = send_recv(self._conn, ("model", model_id))
        model = pickle.loads(blob)
        if model_id == 0:
            self._env.reset()
            obs = self._env.observation(self._env.players()[0])
            model = RandomModel(model, obs)
        elif model_id > self._newest_id:
            model = self._adopt(model)
        return model

    def resolve(self, model_ids):
        """Return {model_id: model} covering every id in the list."""
        resolved = {}
        for model_id in set(model_ids):
            if model_id < 0:
                resolved[model_id] = None
                continue
            if model_id in self._cache:
                self._cache.move_to_end(model_id)
                resolved[model_id] = self._cache[model_id]
                continue
            model = self._fetch(model_id)
            self._cache[model_id] = model
            self._newest_id = max(self._newest_id, model_id)
            while len(self._cache) > self.CAPACITY:
                self._cache.popitem(last=False)
            resolved[model_id] = model
        return resolved


class Worker:
    """One actor process: pull jobs, resolve their models, roll out
    episodes and evaluation matches, push the results back.

    With ``lockstep_episodes > 1`` (the default) jobs run through a
    RolloutPool: K episodes advance together and each step issues one
    batched CPU forward across every seat, instead of one batch-1
    dispatch per seat per step.  Jobs the pool cannot take (mixed
    model snapshots) fall back to the sequential path."""

    def __init__(self, args, conn, wid):
        print(f"opened worker {wid}")
        self.worker_id = wid
        self.args = args
        self.conn = conn
        random.seed(args["seed"] + wid)

        from .environment import make_env
        from .evaluation import Evaluator
        from .generation import Generator, RolloutPool

        self.env = make_env({**args["env"], "id": wid})
        # pipelined dataflow (handyrl_tpu.pipeline): the shm handshake
        # rides the control plane through the gather; None = legacy
        # local inference (pipeline off, remote learner, or refusal)
        from .pipeline import attach_pipeline

        self.pipeline = attach_pipeline(conn, self.env, args)
        if self.pipeline is not None:
            print(f"worker {wid}: pipelined inference attached "
                  f"(client {self.pipeline.client_id})")
            if not self.pipeline.cfg.compress:
                # episodes ride shared memory: skip the bz2 CPU cost
                # (spilled episodes still interop — blocks are
                # magic-sniffed at every consumer)
                self.args = {**args, "episode_compress": False}
        self.models = ModelCache(conn, self.env)
        generator = Generator(self.env, self.args)
        evaluator = Evaluator(self.env, self.args)
        # role -> (runner, reply verb): the job protocol's two roles
        self.roles = {
            "g": (generator.execute, "episode"),
            "e": (evaluator.execute, "result"),
        }
        lockstep = int(self.args.get("lockstep_episodes", 1) or 1)
        self.pool = None
        if lockstep > 1:
            # the pool gets its own envs: self.env backs the sequential
            # fallback and the ModelCache (which resets it)
            envs = [make_env({**args["env"], "id": wid})
                    for _ in range(lockstep)]
            self.pool = RolloutPool(envs, self.args)

    def __del__(self):
        print(f"closed worker {self.worker_id}")

    def _resolve(self, job):
        id_by_player = job.get("model_id", {})
        resolved = self.models.resolve(list(id_by_player.values()))
        if self.pipeline is not None:
            # epoch-pinned served wrappers: each snapshot's forward is
            # answered by the inference service while it holds exactly
            # that epoch, locally otherwise — so league/pinned-eval
            # seats stay on their own policy by construction.  Only
            # feed-forward nets wrap (recurrent hidden state lives on
            # the worker; shipping it per step would drown the rings)
            for mid, model in resolved.items():
                if (mid > 0 and model is not None
                        and hasattr(model, "module")
                        and not getattr(model, "is_recurrent", False)):
                    resolved[mid] = self.pipeline.wrap(model, mid)
        return {p: resolved[mid] for p, mid in id_by_player.items()}

    def _next_job(self):
        """One job from the learner — also the pipeline's surge
        trigger: the shm brownout (``chaos.surge_hold_uploads``) arms
        off the model ids in the job stream, exactly like the
        gather's control-plane hold."""
        job = send_recv(self.conn, ("args", None))
        if self.pipeline is not None:
            self.pipeline.note_jobs([job])
        return job

    def _ship(self, verb, payload):
        """One finished payload upstream: episodes ride the shm
        trajectory ring when the pipeline is attached (zero-copy, no
        ack round trip); everything else — results, episodes the ring
        refuses (full/oversize), and surge-hold overflow — takes the
        control plane (spills are stamped ``shm_spilled``, counted,
        never dropped)."""
        if (verb == "episode" and payload is not None
                and self.pipeline is not None):
            for episode in self.pipeline.ship_episode(payload):
                with payload_trace(episode):
                    send_recv(self.conn, ("episode", episode))
            return
        with payload_trace(payload):
            send_recv(self.conn, (verb, payload))

    def _run_job(self, job):
        models = self._resolve(job)
        runner, reply_verb = self.roles[job["role"]]
        payload = self._traced_run(runner, job, models)
        self._ship(reply_verb, payload)

    @staticmethod
    def _traced_run(runner, job, models):
        """One sequential job under a fresh (sampled) trace context:
        the rollout span is recorded here, and the finished payload is
        stamped with its context plus the snapshot epoch that generated
        it — the learner reduces those stamps into the per-epoch
        `policy_lag_*` metrics and follows the context across
        processes in the exported trace."""
        ctx = telemetry.maybe_trace()
        telemetry.set_trace(ctx)
        t0 = telemetry.span_begin()
        try:
            payload = runner(models, job)
            telemetry.span_end("episode.rollout", t0,
                               mode=job["role"])
        finally:
            telemetry.clear_trace()
        if isinstance(payload, dict):
            if ctx is not None:
                payload.setdefault("trace", ctx)
            labels = [job["model_id"][p] for p in job["player"]]
            gen = max([l for l in labels if l >= 0], default=-1)
            if gen >= 0:
                payload.setdefault("gen_model_epoch", gen)
        return payload

    def _run_lockstep(self):
        pool = self.pool
        while True:
            while pool.has_free_slot():
                job = self._next_job()
                if job is None:
                    # learner is done assigning; finish what's in
                    # flight (the sequential path always ships its
                    # current episode — so does the pool)
                    self._drain_pool()
                    return
                if not pool.accepts(job):
                    self._run_job(job)
                    continue
                for verb, payload in pool.assign(job, self._resolve(job)):
                    self._ship(verb, payload)
            for verb, payload in pool.step():
                self._ship(verb, payload)

    def _drain_pool(self):
        """Step the pool without assigning new jobs until every
        in-flight episode finishes, shipping each one upstream."""
        pool = self.pool
        while any(slot is not None for slot in pool.slots):
            for verb, payload in pool.step():
                self._ship(verb, payload)

    def run(self):
        try:
            if self.pool is not None:
                self._run_lockstep()
                return
            while True:
                job = self._next_job()
                if job is None:
                    return
                self._run_job(job)
        except _PEER_GONE:
            pass  # learner/gather went away: exit quietly
        finally:
            if self.pipeline is not None:
                # episodes a surge hold staged must not die with the
                # worker: drain the backlog into the ring, spill the
                # rest to the control plane (best effort — a gone
                # peer can no longer accept anything)
                try:
                    for episode in self.pipeline.flush_backlog():
                        send_recv(self.conn, ("episode", episode))
                except _PEER_GONE:
                    pass
                self.pipeline.close()  # unmap; the learner owns unlink
            telemetry.flush()  # ship the span-log tail before exit


def _spawn_worker(conn, args, wid):
    force_cpu_jax()
    telemetry.configure_from_args(args, role=f"worker-{wid}",
                                  primary=False)
    # the codec wraps post-spawn, in the owning process: sends carry
    # this worker's episode contexts, recvs adopt the gather's
    Worker(args, TracedConnection(conn), wid).run()


class Gather(QueueCommunicator):
    """Fan-in proxy between ~16 workers and the learner.

    Three behaviors, one per verb class: job requests are served from a
    prefetched block, model requests from an id-keyed cache, and
    episode/result uploads are acked immediately and shipped upstream
    in batches.  This keeps learner round-trips proportional to the
    number of gathers (capability parity with the reference gather).
    """

    CACHED_VERBS = ("model",)
    # per-worker round trips forwarded to the learner verbatim,
    # uncached and unbatched: the shm handshake's reply (ring names,
    # client slot) is unique to the asking worker
    FORWARD_VERBS = ("shm",)
    CACHE_CAPACITY = 4  # per verb; epochs advance, so old keys go cold
    FLUSH_AGE = 0.5  # seconds an upload may wait for batch-mates
    # surge-hold defaults (overridden by _init_surge; class-level so
    # partially-constructed gathers in tests keep working)
    _surge_epoch = 0
    _surge_hold = 0.0
    _surge_pending = False
    _hold_until = 0.0

    def __init__(self, args, conn, gather_id):
        print(f"started gather {gather_id}")
        self.gather_id = gather_id
        self.learner_conn = conn
        self.job_queue = deque()
        self.reply_cache = {
            verb: OrderedDict() for verb in self.CACHED_VERBS}
        self.pending_uploads = {}
        self.pending_count = 0
        self.first_pending_t = 0.0
        # heartbeats piggyback on the control plane: every learner
        # round trip proves liveness, so an explicit ("beat", stats)
        # goes out only after heartbeat_interval seconds of silence
        self.heartbeat_interval = float(
            args.get("heartbeat_interval", 2.0) or 0.0)
        self._last_learner_io = time.monotonic()
        self._init_surge(args)

        worker_conns = self._spawn_workers(args, gather_id)
        super().__init__(worker_conns)
        self.block_size = 1 + len(worker_conns) // 4

    @staticmethod
    def _spawn_workers(args, gather_id):
        wcfg = args["worker"]
        n_total, n_gathers = wcfg["num_parallel"], wcfg["num_gathers"]
        count = n_total // n_gathers + int(gather_id < n_total % n_gathers)
        base = wcfg.get("base_worker_id", 0)

        def worker_args(index):
            # interleave ids across gathers so id blocks stay balanced
            return args, base + index * n_gathers + gather_id

        return open_multiprocessing_connections(
            count, _spawn_worker, worker_args)

    def _init_surge(self, args):
        """Chaos surge hold (``chaos.surge_hold_uploads``): when the
        job stream first carries a model id at or past
        ``chaos.surge_epoch``, this gather sits on its upload backlog
        for the hold window — episodes are still acked to workers and
        staged, but nothing ships upstream until the window passes.
        The transport-level face of a preemption wave: generation
        continues while delivery browns out, and the learner then
        drains a flood of episodes stamped with the pre-surge snapshot
        (exactly the staleness the IMPACT/`max_policy_lag` machinery
        exists to absorb).  Job/model round trips keep flowing, so
        heartbeat liveness is unaffected."""
        from .resilience import ChaosConfig

        chaos = ChaosConfig.from_config(args.get("chaos") or {})
        self._surge_epoch = chaos.surge_epoch
        self._surge_hold = chaos.surge_hold_uploads
        self._hold_until = 0.0
        # disabled (or already fired): stop inspecting the job stream
        self._surge_pending = (chaos.surges_enabled
                               and self._surge_hold > 0)

    def _note_surge(self, jobs):
        if not self._surge_pending:
            return
        for job in jobs:
            ids = (job or {}).get("model_id") or {}
            if any(v >= self._surge_epoch for v in ids.values()):
                self._surge_pending = False
                self._hold_until = time.monotonic() + self._surge_hold
                print(f"gather {self.gather_id}: surge — holding "
                      f"uploads for {self._surge_hold:.1f}s")
                return

    def _holding_uploads(self):
        return time.monotonic() < self._hold_until

    def _ask_learner(self, request):
        self.learner_conn.send(request)
        reply = self.learner_conn.recv()
        self._last_learner_io = time.monotonic()
        return reply

    def _beat_if_due(self):
        """Explicit heartbeat after heartbeat_interval of silence so
        the learner's FleetRegistry can tell idle from wedged/dead."""
        if (self.heartbeat_interval > 0
                and time.monotonic() - self._last_learner_io
                >= self.heartbeat_interval):
            self._ask_learner(("beat", {
                "gather_id": self.gather_id,
                "workers": self.connection_count(),
                **self.drop_stats(),
            }))

    def _serve_job(self, conn):
        if not self.job_queue:
            jobs = self._ask_learner(("args", [None] * self.block_size))
            self.job_queue.extend(jobs)
            self._note_surge(jobs)
        self.send(conn, self.job_queue.popleft())

    def _serve_cached(self, conn, verb, key):
        cache = self.reply_cache[verb]
        if key in cache:
            cache.move_to_end(key)
        else:
            cache[key] = self._ask_learner((verb, key))
            while len(cache) > self.CACHE_CAPACITY:
                cache.popitem(last=False)
        self.send(conn, cache[key])

    def _stage_upload(self, conn, verb, payload):
        self.send(conn, None)  # ack now, ship later
        if self.pending_count == 0:
            self.first_pending_t = time.perf_counter()
        self.pending_uploads.setdefault(verb, []).append(payload)
        self.pending_count += 1
        if (self.pending_count >= self.block_size
                and not self._holding_uploads()):
            self.flush_uploads()

    def flush_uploads(self, drain=False):
        """Ship pending uploads upstream — at most two blocks per call.

        Steady state never accumulates past one block, so the cap is
        invisible there; it exists for the post-brownout backlog (a
        surge hold, a slow learner): one giant frame would stall every
        job/model round trip queued behind it AND land on the learner
        as a single atomic intake (one epoch swallows the whole
        backlog), where block-sized chunks drain interleaved with the
        learner's epoch boundaries.  ``drain=True`` (shutdown) loops
        until empty — episodes are never dropped at exit."""
        while self.pending_count:
            budget = self.pending_count if drain else min(
                self.pending_count, 2 * self.block_size)
            for verb in list(self.pending_uploads):
                if budget <= 0:
                    break
                payloads = self.pending_uploads[verb]
                take, rest = payloads[:budget], payloads[budget:]
                budget -= len(take)
                self.pending_count -= len(take)
                if rest:
                    self.pending_uploads[verb] = rest
                else:
                    del self.pending_uploads[verb]
                self._ask_learner((verb, take))
            if not drain:
                break

    def _flush_if_stale(self):
        """Age-based flush: at low episode rates (big envs, few
        workers per gather) a finished episode must not sit behind the
        count trigger indefinitely — ship whatever is pending once the
        oldest upload has waited FLUSH_AGE."""
        if (self.pending_count
                and not self._holding_uploads()
                and time.perf_counter() - self.first_pending_t
                >= self.FLUSH_AGE):
            self.flush_uploads()

    def run(self):
        while self.connection_count() > 0:
            try:
                conn, (verb, payload) = self.recv(timeout=0.3)
            except queue.Empty:
                self._flush_if_stale()
                self._beat_if_due()
                continue
            if verb == "args":
                self._serve_job(conn)
            elif verb in self.reply_cache:
                self._serve_cached(conn, verb, payload)
            elif verb in self.FORWARD_VERBS:
                self.send(conn, self._ask_learner((verb, payload)))
            else:
                self._stage_upload(conn, verb, payload)
            self._flush_if_stale()
        if self.pending_count:
            self.flush_uploads(drain=True)  # never drop episodes at exit


def _maybe_chaos_wrap(conn, args, gather_id):
    """Frame-fault injection (``chaos.frame_*``) on this gather's
    learner connection, with a per-slot deterministic RNG.  A dropped
    request wedges the gather mid-round-trip — by design: the
    learner's heartbeat eviction is what recovers it.  Returns the
    connection unwrapped when no frame faults are configured."""
    from .resilience import ChaosConfig, ChaosConnection

    chaos = ChaosConfig.from_config(args.get("chaos") or {})
    if not chaos.frames_enabled:
        return conn
    rng = random.Random((chaos.seed << 16) ^ gather_id)
    return ChaosConnection(conn, chaos, rng=rng)


def gather_loop(args, conn, gather_id):
    force_cpu_jax()
    telemetry.configure_from_args(args, role=f"gather-{gather_id}",
                                  primary=False)
    # a chaos kill (or any preemption) is a SIGTERM: leave the flight
    # record behind on the way out
    telemetry.install_signal_dump()
    # trace codec OUTSIDE the chaos wrapper, so injected frame faults
    # hit enveloped frames exactly like real traffic
    gather = Gather(args,
                    TracedConnection(
                        _maybe_chaos_wrap(conn, args, gather_id)),
                    gather_id)
    try:
        gather.run()
    except _PEER_GONE:
        # learner went away MID-session: exit nonzero (quietly) so a
        # supervising RemoteWorkerCluster counts a failure — only the
        # drain path (workers done, run() returns) exits 0
        raise SystemExit(1)
    finally:
        telemetry.flush()  # ship the span-log tail before exit


def _default_num_gathers(num_parallel):
    return 1 + max(0, num_parallel - 1) // 16


class WorkerCluster(QueueCommunicator):
    """Local actor pool: gather processes connected over pipes, kept
    alive by a Supervisor.

    A gather that crashes (or is evicted for missed heartbeats — see
    ``report_stale``) is respawned with jittered exponential backoff;
    a slot that keeps dying trips its circuit breaker and the fleet
    shrinks instead of restart-storming (resilience.supervisor).  The
    optional ``chaos:`` config section arms a ChaosMonkey against the
    same supervisor so failure handling is testable end to end."""

    POLL_INTERVAL = 0.2  # supervision tick, seconds

    def __init__(self, args):
        super().__init__()
        self.args = args
        self.supervisor = None
        self._monkey = None
        self._slot_conns = {}

    def _spawn_gather(self, slot):
        """Supervisor spawn hook: fresh pipe + gather process for a
        slot; the slot's previous (dead) connection is dropped."""
        ours, theirs = _mp.Pipe(duplex=True)
        # gathers spawn worker children, so they cannot be daemonic;
        # they exit on their own once every worker disconnects
        proc = _mp.Process(
            target=gather_loop, args=(self.args, theirs, slot))
        proc.start()
        theirs.close()
        old = self._slot_conns.get(slot)
        if old is not None:
            self.disconnect(old)
        self._slot_conns[slot] = ours
        self.add_connection(ours)
        return proc

    def run(self):
        from .resilience import (
            BackoffPolicy,
            ChaosConfig,
            ChaosMonkey,
            Supervisor,
        )

        wcfg = self.args["worker"]
        wcfg.setdefault(
            "num_gathers", _default_num_gathers(wcfg["num_parallel"]))
        rng = random.Random(self.args.get("seed", 0))
        self.supervisor = Supervisor(
            self._spawn_gather, wcfg["num_gathers"],
            policy=BackoffPolicy(
                base=float(self.args.get("respawn_backoff", 0.5) or 0.5),
                rng=rng),
            max_respawns=int(self.args.get("max_respawns", 5)),
        )
        self.supervisor.start_all()
        chaos = ChaosConfig.from_config(self.args.get("chaos") or {})
        if chaos.kills_enabled or chaos.surges_enabled:
            self._monkey = ChaosMonkey(chaos)
        threading.Thread(target=self._supervise, daemon=True).start()

    def note_epoch(self, epoch):
        """Learner epoch tick: the chaos surge trigger's clock (the
        scheduled burst preemption fires when the noted epoch reaches
        ``chaos.surge_epoch``)."""
        if self._monkey is not None:
            self._monkey.note_epoch(epoch)

    def _supervise(self):
        while not self.shutdown_flag:
            if self._monkey is not None:
                self._monkey.maybe_kill(self.supervisor)
                self._monkey.maybe_surge(self.supervisor)
            self.supervisor.poll()
            time.sleep(self.POLL_INTERVAL)

    def begin_drain(self):
        # workers are about to receive their None jobs and exit; from
        # here a gather exit is completion, not a crash
        if self.supervisor is not None:
            self.supervisor.stop()

    def report_stale(self, conn):
        """Learner-side heartbeat expiry: evict the wedged gather so
        the supervisor respawns it."""
        if self.supervisor is None:
            return
        for slot, slot_conn in self._slot_conns.items():
            if slot_conn is conn:
                self.supervisor.kill_slot(slot, reason="missed heartbeats")
                return

    def fleet_stats(self):
        stats = super().fleet_stats()
        if self.supervisor is not None:
            stats.update(self.supervisor.stats())
        return stats

    def terminate_fleet(self):
        """Preemption teardown (SIGTERM grace window): kill every
        gather child NOW instead of draining.  A dying learner must
        not leave an orphan fleet behind to compete with its own
        supervised relaunch for host cores — the relaunch spawns a
        fresh fleet and the WAL already holds the backlog the orphans
        would have delivered."""
        if self.supervisor is not None:
            self.supervisor.terminate_all()

    def shutdown(self):
        self.begin_drain()
        super().shutdown()


class WorkerServer(QueueCommunicator):
    """Learner-side acceptor for remote worker machines.

    Two listener threads: the entry port hands out worker-id blocks
    plus the merged config, and the worker port accepts persistent
    gather connections into the communicator — so machines may join at
    any time during training (elastic scale-out)."""

    # entry-handshake deadline, seconds (class-level so tests can
    # shrink it: a slow-loris peer should cost ITS deadline, not 10s
    # of test wall time)
    ENTRY_TIMEOUT = 10.0
    # class-level defaults so partially-constructed servers (tests
    # drive _safe_admit via WorkerServer.__new__) keep working
    entry_port = ENTRY_PORT
    _admit_lock = threading.Lock()

    def __init__(self, args):
        super().__init__()
        self.args = args
        self.total_worker_count = 0
        self.entry_port = ENTRY_PORT
        # id-block reservation guard: entry handshakes run CONCURRENTLY
        # (one thread each), and two machines joining at once must not
        # be handed overlapping worker-id blocks
        self._admit_lock = threading.Lock()

    def note_epoch(self, epoch):
        """No supervised fleet here (remote gathers run under their own
        machine-side supervisors), so there is no monkey to tick; the
        gather-side surge hold still works remotely — it triggers off
        the model ids in the job stream, not this call."""

    def terminate_fleet(self):
        """Remote gathers belong to their machines' supervisors: a
        preempted learner just leaves, the severed sockets fail their
        round trips, and the machine-side session resume (PR 3) brings
        them back against the relaunched learner."""

    def _admit(self, conn):
        """Entry handshake: reserve an id block, reply merged config."""
        # jaxlint: disable=unbounded-recv -- bounded: _safe_admit arms a socket deadline before calling, so a silent peer raises timeout instead of wedging the entry loop
        remote_cfg = conn.recv()
        print(f"accepted connection from {remote_cfg['address']}")
        count = int(remote_cfg["num_parallel"])
        with self._admit_lock:
            # handshakes run concurrently: the reservation must be
            # atomic or two joining machines get overlapping id blocks
            remote_cfg["base_worker_id"] = self.total_worker_count
            self.total_worker_count += count
        merged = copy.deepcopy(self.args)
        merged["worker"] = remote_cfg
        conn.send(merged)
        conn.close()

    def _safe_admit(self, conn):
        """One guarded entry handshake: a peer preempted mid-handshake,
        a corrupt frame, or a stray client talking garbage to the entry
        port is normal churn — it must cost that one connection, never
        the accept loop (which could otherwise never admit a machine
        again).  Broad catch is deliberate: garbage bytes can surface
        as UnpicklingError/KeyError/etc., and the loop must survive
        all of them."""
        try:
            # a peer that connects and then says NOTHING must not park
            # the entry thread forever (commlint unbounded-recv): give
            # the whole handshake a deadline, after which the recv in
            # _admit raises socket.timeout (an OSError) and the peer
            # is dropped like any other garbage handshake
            conn.sock.settimeout(self.ENTRY_TIMEOUT)
            self._admit(conn)
        except Exception as exc:  # noqa: BLE001 — see docstring
            print(f"entry handshake failed ({exc!r}); dropping peer")
            try:
                conn.close()
            except OSError:
                pass

    def _entry_server(self):
        print(f"started entry server {self.entry_port}")
        for conn in accept_socket_connections(
                port=self.entry_port,
                max_frame_bytes=self._max_frame_bytes()):
            if conn is not None:
                # one thread per handshake: admits run CONCURRENTLY,
                # so a slow-loris (or merely slow) peer costs its own
                # deadline, never the machines queued behind it — the
                # accept loop goes straight back to accept()
                threading.Thread(
                    target=self._safe_admit, args=(conn,),
                    daemon=True, name="entry-admit").start()

    def _worker_server(self):
        print(f"started worker server {WORKER_PORT}")
        for conn in accept_socket_connections(
                port=WORKER_PORT, max_frame_bytes=self._max_frame_bytes()):
            if conn is not None:
                self.add_connection(conn)

    def _max_frame_bytes(self):
        from .connection import DEFAULT_MAX_FRAME_BYTES

        return int(self.args.get("max_frame_bytes", 0)
                   or DEFAULT_MAX_FRAME_BYTES)

    def report_stale(self, conn):
        """A remote gather missed its heartbeats: sever the socket so
        its blocked round-trip fails, the gather exits nonzero, and
        the worker machine's own supervisor respawns it.  (Local
        fleets instead kill the child directly — WorkerCluster.)"""
        print("dropping stale worker connection (missed heartbeats)")
        self.disconnect(conn)

    def run(self):
        threading.Thread(target=self._entry_server, daemon=True).start()
        threading.Thread(target=self._worker_server, daemon=True).start()


def entry(worker_args):
    """Remote machine -> learner handshake; returns the merged config."""
    conn = open_socket_connection(worker_args["server_address"], ENTRY_PORT)
    try:
        conn.send(worker_args)
        # jaxlint: disable=unbounded-recv -- one-shot startup handshake, operator-visible: the learner replies immediately on accept, and a dead learner raises into _join's retry loop
        merged = conn.recv()
    finally:
        # a learner dying mid-handshake raises into _join's retry
        # loop; without this the retry loop leaks one fd per attempt
        conn.close()
    return merged


class RemoteWorkerCluster:
    """Worker-machine runtime: handshake on the entry port, then local
    gathers each dialing the learner's worker port.

    Resilient by session: the entry handshake retries with backoff
    until the learner answers; each gather slot is supervised
    (crash/eviction -> reconnect-with-backoff respawn, a dial the
    learner refuses counts as a failure of the same slot); and when
    every slot has circuit-broken dead — the learner was gone long
    enough to exhaust every slot's respawn budget — the cluster
    RESUMES the session: it re-runs the entry handshake (re-fetching
    the merged args, which may have changed across a learner restart)
    and respawns the fleet, whose fresh workers re-fetch the current
    model snapshot through their ModelCache on their first jobs."""

    SESSION_POLL = 0.5  # supervision tick, seconds

    def __init__(self, args):
        args["address"] = gethostname()
        args.setdefault(
            "num_gathers", _default_num_gathers(args["num_parallel"]))
        self.args = args
        self._rng = random.Random()

    def _join(self, policy):
        """Entry handshake, retried with backoff until the learner is
        reachable; returns the merged config."""
        attempt = 0
        while True:
            try:
                return entry(self.args)
            except OSError as exc:
                delay = policy.delay(attempt)
                attempt += 1
                print(f"learner unreachable ({exc!r}); "
                      f"retrying entry in {delay:.1f}s")
                time.sleep(delay)

    def _spawn_gather(self, merged, slot):
        from .connection import DEFAULT_MAX_FRAME_BYTES

        conn = open_socket_connection(
            self.args["server_address"], WORKER_PORT,
            max_frame_bytes=int(merged.get("max_frame_bytes", 0)
                                or DEFAULT_MAX_FRAME_BYTES))
        try:
            proc = _mp.Process(
                target=gather_loop, args=(merged, conn, slot))
            proc.start()
        finally:
            # the spawn context pickles conn at start(); the parent's
            # copy must close whether or not the start succeeded, or
            # every failed respawn strands a learner-facing fd
            conn.close()
        return proc

    def _run_session(self, merged):
        """One supervised fleet against one learner session; returns
        once no slot is live — True for a clean drain (training
        ended), False when the fleet was lost (learner gone
        mid-session)."""
        from .resilience import BackoffPolicy, Supervisor

        supervisor = Supervisor(
            functools.partial(self._spawn_gather, merged),
            self.args["num_gathers"],
            policy=BackoffPolicy(
                base=float(merged.get("respawn_backoff", 0.5) or 0.5),
                rng=self._rng),
            max_respawns=int(merged.get("max_respawns", 5)),
            # a gather that exits 0 drained its workers after the
            # learner's None jobs — training ended; don't respawn it
            # against a learner that is finishing (gather_loop exits
            # nonzero when the learner vanishes mid-session)
            treat_clean_exit_as_drain=True,
        )
        supervisor.start_all()
        try:
            while True:
                # poll BEFORE the exit check: a child that died during
                # the sleep must be recorded (-> backoff respawn)
                # before the check can mistake it for session end
                supervisor.poll()
                if (supervisor.alive_count() == 0
                        and supervisor.pending_count() == 0):
                    # poll just ran, so every slot is DEAD or STOPPED
                    # here — decide the verdict before terminate_all's
                    # stop() relabels anything
                    return (supervisor.dead_count() == 0
                            and supervisor.stopped_count() > 0)
                time.sleep(self.SESSION_POLL)
        finally:
            # also reached on a partial launch failure or Ctrl-C:
            # gathers are non-daemonic and must not be orphaned
            supervisor.terminate_all()

    def run(self):
        from .environment import prepare_env
        from .resilience import BackoffPolicy

        entry_policy = BackoffPolicy(rng=self._rng)
        while True:
            merged = self._join(entry_policy)
            print(merged)
            prepare_env(merged["env"])
            drained = self._run_session(merged)
            print("training session complete; waiting for the next "
                  "learner" if drained
                  else "gather fleet lost; re-entering the session")


def worker_main(args, argv):
    worker_args = args["worker_args"]
    if len(argv) >= 1:
        worker_args["num_parallel"] = int(argv[0])
        worker_args.pop("num_gathers", None)
    RemoteWorkerCluster(args=worker_args).run()
