"""Hungry Geese: TorusConv net, simultaneous-mode batch + update step."""

import random

import numpy as np
import pytest

from handyrl_tpu.batch import make_batch
from handyrl_tpu.envs.kaggle.hungry_geese import Environment as HungryGeese
from handyrl_tpu.generation import Generator
from handyrl_tpu.models import TPUModel
from handyrl_tpu.ops.losses import LossConfig
from handyrl_tpu.ops.update import make_optimizer, make_update_step

CFG = {
    "turn_based_training": False,   # simultaneous game: solo training
    "observation": False,
    "gamma": 0.8,
    "forward_steps": 8,
    "burn_in_steps": 0,
    "compress_steps": 4,
    "entropy_regularization": 0.1,
    "entropy_regularization_decay": 0.1,
    "lambda": 0.7,
    "policy_target": "UPGO",
    "value_target": "TD",
}


def test_torus_conv_wraps():
    """A feature at the left edge bleeds to the right edge via wrap."""
    import jax
    import jax.numpy as jnp

    from handyrl_tpu.models.geese_net import TorusConv

    m = TorusConv(filters=1, use_norm=False)
    x = np.zeros((1, 7, 11, 1), np.float32)
    x[0, 3, 0, 0] = 1.0
    params = m.init(jax.random.PRNGKey(0), jnp.asarray(x))
    out = m.apply(params, jnp.asarray(x))
    # the kernel sees the impulse from the opposite edge
    assert float(np.abs(np.asarray(out)[0, 3, 10, 0])) > 0


def test_net_inference_shapes():
    env = HungryGeese()
    model = TPUModel(env.net())
    model.init_params(env.observation(0))
    out = model.inference(env.observation(0), None)
    assert out["policy"].shape == (4,)
    assert out["value"].shape == (1,)
    assert -1.0 <= float(out["value"][0]) <= 1.0


@pytest.mark.slow
def test_simultaneous_batch_and_update():
    random.seed(3)
    env = HungryGeese()
    model = TPUModel(env.net())
    model.init_params(env.observation(0), seed=3)
    gen = Generator(env, CFG)
    args = {"player": env.players(),
            "model_id": {p: 1 for p in env.players()}}
    episodes = []
    while len(episodes) < 2:
        ep = gen.generate({p: model for p in env.players()}, args)
        if ep is not None:
            episodes.append(ep)

    def select(ep):
        end = min(CFG["forward_steps"], ep["steps"])
        return {
            "args": ep["args"], "outcome": ep["outcome"],
            "moment": ep["moment"][:(end - 1) // CFG["compress_steps"] + 1],
            "base": 0, "start": 0, "end": end, "train_start": 0,
            "total": ep["steps"],
        }

    batch = make_batch([select(ep) for ep in episodes], CFG)
    T = CFG["forward_steps"]
    # solo training: one random player selected per episode
    assert batch["observation"].shape == (2, T, 1, 7, 11, 17)
    assert batch["action_mask"].shape == (2, T, 1, 4)
    assert batch["value"].shape == (2, T, 1, 1)

    loss_cfg = LossConfig.from_config(CFG)
    optimizer = make_optimizer(1e-3)
    params = model.params
    opt_state = optimizer.init(params)
    update = make_update_step(model, loss_cfg, optimizer)
    params, opt_state, metrics = update(params, opt_state, batch)
    for k in ("p", "v", "ent", "total", "grad_norm"):
        assert np.isfinite(float(metrics[k])), (k, float(metrics[k]))


def test_rule_based_agent_avoids_reverse():
    random.seed(5)
    env = HungryGeese()
    for _ in range(20):
        if env.terminal():
            break
        acts = {}
        for p in env.turns():
            a = env.rule_based_action(p)
            if p in env.last_actions:
                assert a != {0: 1, 1: 0, 2: 3, 3: 2}[env.last_actions[p]]
            acts[p] = a
        env.step(acts)
