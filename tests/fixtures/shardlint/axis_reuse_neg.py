"""Fixture: each axis shards at most one dim."""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), ("dp", "tp"))


def batch_spec():
    return P("dp", "tp")


def grouped_spec():
    return P(("dp", "tp"), None)
