"""handyrl_tpu.anakin — fused on-device rollout + update for JAX envs.

Podracer's Anakin architecture (arXiv:2104.06272): for envs with a
pure-JAX twin in ``environment.JAX_ENV_REGISTRY``, env stepping,
inference, batch assembly, and the optimizer update run as ONE jitted,
``vmap``'d program on the device — thousands of lockstep self-play
games per chip, zero control-plane traffic in the hot path.  Non-JAX
envs keep the IMPALA worker path; the worker fleet still runs
evaluation either way.

Public surface: :class:`AnakinConfig` (the validated ``anakin.*``
config keys), :class:`AnakinEngine` (the fused-step builder the
Trainer drives).

``AnakinEngine`` resolves lazily (PEP 562): config validation
(``TrainConfig.__post_init__``) imports this package, and — like
``pipeline.config`` — it must stay importable without pulling jax
into processes that have not pinned a backend yet.  Only the learner,
which already runs jax, ever touches the engine.
"""

from .config import AnakinConfig  # noqa: F401


def __getattr__(name):
    if name == "AnakinEngine":
        from .rollout import AnakinEngine

        return AnakinEngine
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
