"""Suppressed: the blocking call is bounded and says why."""

import threading
import time


class Gate:
    def __init__(self, conn):
        self._lock = threading.Lock()
        self.conn = conn
        self.frames = 0

    def nap(self):
        with self._lock:
            # jaxlint: disable=blocking-under-lock -- 10ms settle delay bounded by the hardware spec; no other thread exists during calibration
            time.sleep(0.01)

    def pull(self):
        with self._lock:
            # jaxlint: disable=blocking-under-lock -- socket has a 50ms timeout; the lock is per-connection and uncontended
            data = self.conn.recv()
            self.frames = self.frames + len(data)
