from .wrapper import TPUModel, RandomModel, snapshot_params, load_params
