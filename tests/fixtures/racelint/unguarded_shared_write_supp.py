"""Suppressed: the bare write is intentional and says why."""

import threading


class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = {}

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self._lock:
                self.jobs["tick"] = len(self.jobs)

    def reset(self):
        # jaxlint: disable=unguarded-shared-write -- rebind is atomic under the GIL and the loop tolerates either dict
        self.jobs = {}
