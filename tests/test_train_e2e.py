"""End-to-end local training: learner server + spawned workers/batchers.

The TPU-native analog of running ``python main.py --train`` for a couple
of epochs on TicTacToe with tiny settings — exercises the whole async
runtime: job assignment, model serving, gather fan-in, episode intake,
recency sampling, batcher farm, jitted updates, checkpointing, and
shutdown.  The update step trains under a RetraceGuard with a budget of
ONE compile (``max_update_compiles``): any shape churn introduced by a
future batching change fails this test at the offending step instead of
surfacing as a silent TPU slowdown."""

import json
import os
import pickle

import pytest


@pytest.mark.slow
def test_local_training_two_epochs(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)

    args = {
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "turn_based_training": True,
            "observation": False,
            "gamma": 0.8,
            "forward_steps": 4,
            "burn_in_steps": 0,
            "compress_steps": 4,
            "entropy_regularization": 0.1,
            "entropy_regularization_decay": 0.1,
            "update_episodes": 15,
            "batch_size": 4,
            "minimum_episodes": 10,
            "maximum_episodes": 200,
            "epochs": 2,
            "num_batchers": 1,
            "eval_rate": 0.1,
            "worker": {"num_parallel": 2},
            "lambda": 0.7,
            "policy_target": "VTRACE",
            "value_target": "VTRACE",
            "seed": 1,
            # retrace/host-sync/sharding guards armed for real: the
            # update step may compile exactly once, must never incur a
            # resharding copy, and every epoch must report the guard
            # counters into the metrics jsonl
            "max_update_compiles": 1,
            "host_transfer_guard": True,
            "sharding_contract_guard": True,
            "max_resharding_copies": 1,
            # control-plane stall watchdog armed for real: the server
            # loop and communicator threads must beat throughout, so a
            # wedge introduced by a future protocol change shows up as
            # stall_events > 0 here
            "stall_watchdog": True,
            "max_stall_seconds": 30.0,
            # numerics guard armed for real: the update step's dtype
            # contract must hold for the whole run and the in-graph
            # loss/grad-norm finiteness flag must stay 0 every step
            "numerics_guard": True,
            "max_nonfinite_steps": 1,
            # resource ledger armed for real: every epoch record must
            # carry the fd/thread/shm population, and the fleet must
            # PLATEAU after bring-up (the soak assert below)
            "resource_ledger": True,
            # perf attribution armed with explicit peaks: CPU has no
            # DEVICE_PEAKS row, so the override is what turns the
            # roofline keys from None into real floats here (the same
            # mechanism an unlisted accelerator would use)
            "perf": {"peak_tflops": 1.0, "peak_hbm_gbs": 100.0},
            "metrics_path": "metrics.jsonl",
            # telemetry armed at the DEFAULT sample rate: the pipeline
            # metrics must land in every epoch record, and the span
            # logs must export to a trace whose ids cross processes
            "telemetry": True,
            "trace_sample_rate": 1.0,
        },
        "worker_args": {"num_parallel": 2, "server_address": ""},
    }

    from handyrl_tpu.learner import Learner

    learner = Learner(args)
    learner.run()  # returns when epochs reached and workers drained

    assert learner.model_epoch == 2

    # exactly ONE compile of the (device-replay) update step across
    # both epochs — with max_update_compiles=1, a second compile would
    # already have raised RetraceError inside the trainer thread, and
    # the trainer records failures instead of crashing the learner, so
    # assert both ends
    assert learner.trainer.failure is None
    assert learner.trainer.retrace_guard.compiles == 1
    assert learner.trainer.retrace_guard.calls > 0

    # sharding contract held for the whole run: every update-step
    # argument kept the layout of its first committed call, so XLA
    # inserted zero silent resharding copies.  (max_resharding_copies=1
    # only raises at the SECOND copy — the == 0 assert here is what
    # enforces zero; the armed budget proves the guard runs live.)
    assert learner.trainer.shard_guard is not None
    assert learner.trainer.shard_guard.copies == 0

    # guard counters flow into the metrics jsonl, one record per epoch
    with open("metrics.jsonl") as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert len(records) == 2
    for record in records:
        assert record["retrace_count"] == 1
        assert record["host_transfers"] >= 1  # the epoch snapshot sync
        assert record["resharding_copies"] == 0
        # every control-plane wait stayed bounded (no wedged loop) and
        # no peer spoke a verb the server does not handle
        assert record["stall_events"] == 0
        assert record["unknown_verbs"] == 0
        # the lock-order guard is armed by default: every epoch reports
        # its contention window and the run never observed two locks
        # taken in conflicting orders
        assert "lock_contention_sec" in record
        assert record["lock_order_inversions"] == 0
        # the numerics guard is armed (max_nonfinite_steps=1 would
        # raise at the SECOND NaN step — the == 0 asserts here are
        # what enforce zero): no update step went NaN/Inf and every
        # argument leaf kept its first-call dtype/weak-type
        assert record["nonfinite_steps"] == 0
        assert record["numerics_contract_breaks"] == 0
        assert "weak_upcasts" in record
        # pipeline telemetry, present EVERY epoch: off-policy staleness
        # is finite and the epoch's wall time splits into feed wait vs
        # device work (batch_wait_sec is 0.0 on the device-replay path
        # but must be present either way)
        import math

        assert math.isfinite(record["policy_lag_max"])
        # NOTE p95 >= mean is NOT an invariant of nearest-rank p95
        # (96 zeros + 4 ones -> p95 0.0, mean 0.04): only chain the
        # true invariants
        assert record["policy_lag_max"] >= record["policy_lag_p95"] >= 0.0
        assert record["policy_lag_max"] >= record["policy_lag_mean"] >= 0.0
        assert "batch_wait_sec" in record
        assert "device_step_sec" in record
        assert record["queue_depth"] >= 0
        assert record["epoch_wall_sec"] > 0.0
        assert record["time_sec"] >= record["epoch_wall_sec"]
        # perf attribution, present EVERY epoch: the cost model
        # harvested the step program's flops at its one compile, and
        # the peak override above makes mfu/achieved real floats on
        # this CPU host; the roofline verdict must commit either way
        assert isinstance(record["mfu"], float) and record["mfu"] > 0.0
        assert isinstance(record["achieved_tflops"], float)
        assert record["achieved_tflops"] > 0.0
        assert record["arithmetic_intensity"] > 0.0
        assert record["roofline_verdict"] in (
            "compute-bound", "memory-bound")
        # wall-time reconciliation, EXACT by construction: the epoch
        # wall equals the tracked sections plus the explicit residual
        # over the record's own rounded values (the attribution
        # layer's no-hidden-time contract)
        tracked = sum(v for k, v in record.items()
                      if k.startswith("profile_") and k.endswith("_sec")
                      and isinstance(v, (int, float)))
        assert record["untracked_residual_sec"] == pytest.approx(
            record["epoch_wall_sec"] - tracked, abs=1e-6)
        assert tracked + record["untracked_residual_sec"] == \
            pytest.approx(record["epoch_wall_sec"], abs=1e-6)
        # the inference dispatch carries the SAME guard contract as
        # the update step (GSPMD inference plane): zero resharding
        # copies every epoch, and the compile count never exceeds the
        # batch-bucket geometries — snapshots hot-swap through one
        # compiled forward, they never add a compile
        assert record["infer_resharding_copies"] == 0
        # exactly one compile per batch-bucket geometry — snapshots
        # hot-swap every epoch through ONE compiled forward, so the
        # cumulative count is bounded by the handful of pow2 buckets
        # this tiny fleet can produce, never by the epoch count.
        # (The multichip dry-run script pins the per-geometry count
        # exactly on a deterministic synchronous dispatch.)
        assert 0 <= record["infer_compiles"] <= 4
        # the resource ledger samples every epoch: the population
        # keys are present in EVERY record (schema stability for the
        # plots and the soak assert below)
        assert record["fd_count"] > 0
        assert record["thread_count"] >= 1
        assert record["shm_segments"] >= 0
        assert record["resource_growth"] >= 0

    # soak: the fleet's resource population PLATEAUS — the last
    # epoch's fd/thread counts stay within a small churn margin of
    # epoch 1 (workers connect during bring-up, so growth is measured
    # epoch-to-epoch, not from zero).  A leak on any per-epoch path
    # (snapshot serving, batcher restarts, eval spawns) compounds and
    # fails here
    first, last = records[0], records[-1]
    assert last["fd_count"] - first["fd_count"] <= 4, (
        f"fd count grew {first['fd_count']} -> {last['fd_count']} "
        f"across epochs: a per-epoch leak")
    assert last["thread_count"] - first["thread_count"] <= 2, (
        f"thread count grew {first['thread_count']} -> "
        f"{last['thread_count']} across epochs")

    # the run's span logs export to a Perfetto trace whose propagated
    # ids cross at least two processes (worker rollouts -> learner
    # rpc/intake): the cross-process causality the envelope exists for
    from handyrl_tpu.telemetry.export import collect_run, export_run

    roles, spans = collect_run(".")
    assert len(roles) >= 2, f"span logs from one process only: {roles}"
    by_trace = {}
    for span in spans:
        if "trace" in span:
            by_trace.setdefault(span["trace"], set()).add(span["pid"])
    assert any(len(pids) >= 2 for pids in by_trace.values()), (
        "no trace id crossed a process boundary")
    path, count = export_run(".")
    assert os.path.exists("trace.json") and count > 0

    assert os.path.exists("models/1.ckpt")
    assert os.path.exists("models/2.ckpt")
    assert os.path.exists("models/latest.ckpt")

    with open("models/latest.ckpt", "rb") as f:
        state = pickle.load(f)  # checksum footer trails the pickle
    assert state["epoch"] == 2
    assert state["steps"] > 0

    # durability ran live under the default config: every checkpoint
    # is checksummed and indexed by the manifest (the auto-resume
    # source of truth), and the episode WAL logged the whole intake
    from handyrl_tpu.durability import CheckpointManifest, verify_file

    manifest = CheckpointManifest("models")
    entries = manifest.load()["entries"]
    assert sorted(entries) == ["1", "2"]
    for epoch, entry in entries.items():
        assert verify_file(f"models/{epoch}.ckpt", entry["digest"])
    assert manifest.load()["latest"]["epoch"] == 2
    assert verify_file("models/train_state.ckpt")
    for record in records:
        # a fresh run replays nothing; the WAL grows with intake
        assert record["episodes_replayed"] == 0
        assert record["wal_appended"] > 0
    assert os.path.isdir("models/wal")

    # the saved snapshot round-trips into a working model
    from handyrl_tpu.envs.tictactoe import Environment as TicTacToe
    from handyrl_tpu.models import TPUModel

    env = TicTacToe()
    env.reset()
    model = TPUModel(env.net(), state["params"])
    out = model.inference(env.observation(0), None)
    assert out["policy"].shape == (9,)
