"""Fixture: correct key discipline — split/fold_in before reuse."""

import jax


def split_consume(seed):
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.uniform(k1, (3,))
    b = jax.random.normal(k2, (3,))
    return a + b


def loop_fold(seed, steps):
    base = jax.random.PRNGKey(seed)
    out = []
    for i in range(steps):
        key = jax.random.fold_in(base, i)
        out.append(jax.random.uniform(key, (3,)))
    return out


def split_carry(seed, steps):
    key = jax.random.PRNGKey(seed)
    out = []
    for _ in range(steps):
        key, sub = jax.random.split(key)  # the carry idiom
        out.append(jax.random.uniform(sub, (3,)))
    return out


def rebind(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.uniform(key, (3,))
    key = jax.random.PRNGKey(seed + 1)
    b = jax.random.normal(key, (3,))
    return a + b
