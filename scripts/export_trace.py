"""Render a run's telemetry span logs into a Perfetto trace.json.

The learner and every worker/gather/batcher child write per-process
span logs (``spans-<pid>.jsonl``) next to the run's ``metrics.jsonl``
(see docs/observability.md); this tool merges them into the Trace
Event Format that https://ui.perfetto.dev and ``chrome://tracing``
load directly.  Spans carrying a propagated trace context keep it in
``args.trace``, so one episode's worker -> gather -> learner journey
can be followed across process tracks.

Usage:
  python scripts/export_trace.py <run_dir> [out.json]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from handyrl_tpu.telemetry.export import export_run  # noqa: E402


def main():
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(1)
    run_dir = sys.argv[1]
    out = sys.argv[2] if len(sys.argv) > 2 else None
    path, count = export_run(run_dir, out)
    if count == 0:
        print(f"no spans found under {run_dir} (is telemetry on and "
              f"metrics_path set?)")
        sys.exit(1)
    print(f"wrote {count} events to {path}")


if __name__ == "__main__":
    main()
