"""Model wrapper: one uniform interface over Flax policy-value nets.

Role parity with the reference ``ModelWrapper``/``RandomModel``
(/root/reference/handyrl/model.py:33-74): train-side batched forward,
actor-side numpy->numpy single-state ``inference`` with batch-dim
handling, ``init_hidden`` plumbing for recurrent nets, and a
``RandomModel`` whose all-zero outputs yield a uniform policy over
legal actions.

TPU-native differences: parameters are an explicit pytree (not module
state), ``inference`` is a cached ``jax.jit`` of ``module.apply``
(compiled per obs-structure, re-used across weight updates), and
pickling a ``TPUModel`` ships ``(module, numpy params)`` so CPU actor
processes can rebuild and jit locally.
"""

import pickle
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _to_numpy(tree):
    return jax.tree.map(lambda a: np.asarray(a), tree)


def snapshot_params(params) -> bytes:
    """Serialize a params pytree (device -> host, pickled numpy)."""
    return pickle.dumps(_to_numpy(params))


def load_params(blob: bytes):
    return pickle.loads(blob)


class TPUModel:
    """A Flax module bound to a params pytree.

    ``inference`` is the actor-side hot path: numpy obs in, numpy
    outputs out, batch dim added/stripped automatically.
    """

    def __init__(self, module, params=None):
        self.module = module
        self.params = params
        self._jitted = None

    # -- initialization ---------------------------------------------
    def init_params(self, example_obs, seed: int = 0):
        obs_b = jax.tree.map(lambda a: jnp.asarray(a)[None], example_obs)
        hidden_b = self.init_hidden([1])
        variables = self.module.init(jax.random.PRNGKey(seed), obs_b, hidden_b)
        self.params = variables["params"]
        return self.params

    def init_hidden(self, batch_shape=None):
        """Zero hidden state with leading ``batch_shape`` dims, or None
        for feed-forward nets.  ``None``/``[]`` means "no batch dim"
        (single-state actor inference)."""
        if hasattr(self.module, "init_hidden"):
            return self.module.init_hidden(tuple(batch_shape or ()))
        return None

    @property
    def is_recurrent(self) -> bool:
        return hasattr(self.module, "init_hidden")

    # -- forward ----------------------------------------------------
    def apply(self, params, obs, hidden=None):
        return self.module.apply({"params": params}, obs, hidden)

    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        """Single-state forward: numpy in, numpy out (no batch dim)."""
        if self._jitted is None:
            self._jitted = jax.jit(self.apply)
        obs_b = jax.tree.map(lambda a: np.asarray(a)[None], obs)
        hidden_b = (
            jax.tree.map(lambda a: np.asarray(a)[None], hidden)
            if hidden is not None
            else None
        )
        out = self._jitted(self.params, obs_b, hidden_b)
        return jax.tree.map(lambda a: np.asarray(a)[0], out)

    def inference_batch(self, obs, hidden=None) -> Dict[str, Any]:
        """Batched actor forward: numpy ``(N, ...)`` leaves in and out.

        The RolloutPool's hot path — one dispatch covers every seat of
        every lockstep episode.  Shares the jit cache with
        ``inference`` (a second trace for the batched shape)."""
        if self._jitted is None:
            self._jitted = jax.jit(self.apply)
        out = self._jitted(self.params, obs, hidden)
        return jax.tree.map(np.asarray, out)

    # -- serialization (learner -> actor shipping) -------------------
    def __getstate__(self):
        return {"module": self.module, "params": _to_numpy(self.params)}

    def __setstate__(self, state):
        self.module = state["module"]
        self.params = state["params"]
        self._jitted = None


class RandomModel:
    """Uniform-policy stand-in: zero logits over every head.

    Built from a real model's output structure on a sample observation,
    mirroring /root/reference/handyrl/model.py:65-74.
    """

    def __init__(self, model: TPUModel, example_obs):
        outputs = model.inference(example_obs, model.init_hidden())
        self._outputs = {
            k: np.zeros_like(v)
            for k, v in outputs.items()
            if k != "hidden"
        }

    def init_hidden(self, batch_shape=None):
        return None

    def inference(self, obs=None, hidden=None):
        return dict(self._outputs)

    def inference_batch(self, obs, hidden=None):
        """Zero logits for every row of the batch (uniform policy)."""
        n = jax.tree.leaves(obs)[0].shape[0]
        return {
            k: np.broadcast_to(v, (n,) + v.shape)
            for k, v in self._outputs.items()
        }
