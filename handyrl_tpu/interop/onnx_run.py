"""Run ``.onnx`` policy networks with numpy — no onnxruntime needed.

Capability parity with the reference's OnnxModel
(/root/reference/handyrl/evaluation.py:287-365): ``--eval`` accepts a
``.onnx`` artifact, hidden states are discovered by the ``hidden``
input-name prefix, and inference is numpy -> numpy with the same
output contract ({name: array, "hidden": [arrays] | None}).

The interpreter executes the graph nodes in order (ONNX graphs are
topologically sorted by spec) over a numpy environment.  The op set
covers what policy-value networks use: conv/matmul stacks, elementwise
activations, normalization, pooling, shaping — both our own jaxpr
exports (onnx_export.py) and typical torch-exported nets.  Actor-side
evaluation is latency-bound at batch 1, where numpy is plenty.
"""

import numpy as np

from .onnx_proto import (
    DT_BOOL,
    DT_DOUBLE,
    DT_FLOAT,
    DT_FLOAT16,
    DT_INT32,
    DT_INT64,
    DT_INT8,
    DT_UINT8,
    decode,
)

_DTYPES = {
    DT_FLOAT: np.float32, DT_UINT8: np.uint8, DT_INT8: np.int8,
    DT_INT32: np.int32, DT_INT64: np.int64, DT_BOOL: np.bool_,
    DT_FLOAT16: np.float16, DT_DOUBLE: np.float64,
}


def tensor_to_numpy(t: dict) -> np.ndarray:
    code = t.get("data_type", DT_FLOAT)
    dtype = _DTYPES.get(code)
    if dtype is None:
        try:  # bfloat16 and friends: ml_dtypes ships with jax
            import ml_dtypes

            dtype = {16: np.dtype(ml_dtypes.bfloat16)}[code]
        except Exception:
            raise NotImplementedError(
                f"ONNX tensor data_type {code} is not supported")
    dims = [int(d) for d in t.get("dims", [])]
    raw = t.get("raw_data")
    if raw:
        arr = np.frombuffer(raw, dtype=dtype)
    elif t.get("float_data"):
        arr = np.asarray(t["float_data"], np.float32).astype(dtype)
    elif t.get("int64_data"):
        arr = np.asarray(t["int64_data"], np.int64).astype(dtype)
    elif t.get("int32_data"):
        arr = np.asarray(t["int32_data"], np.int32).astype(dtype)
    else:
        arr = np.zeros(0, dtype)
    return arr.reshape(dims).copy()


def _attrs(node):
    out = {}
    for a in node.get("attribute", []):
        name = a["name"]
        if a.get("t") is not None:
            out[name] = tensor_to_numpy(a["t"])
        elif a.get("ints"):
            out[name] = [int(v) for v in a["ints"]]
        elif a.get("floats"):
            out[name] = [float(v) for v in a["floats"]]
        elif a.get("s") is not None and a.get("s") != b"":
            out[name] = a["s"].decode()
        elif a.get("f") is not None:
            out[name] = float(a["f"])
        elif a.get("i") is not None:
            out[name] = int(a["i"])
        else:
            # presence with all-default payload: treat as 0/empty int
            out[name] = int(a.get("i") or 0)
    return out


def _conv(x, w, b, attrs):
    """Grouped 2D convolution, NCHW, via im2col matmul."""
    group = int(attrs.get("group", 1))
    strides = attrs.get("strides", [1, 1])
    dilations = attrs.get("dilations", [1, 1])
    auto_pad = attrs.get("auto_pad", "NOTSET")
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        pads = [0, 0, 0, 0]
        for i, (size, kern) in enumerate(zip(x.shape[2:],
                                             w.shape[2:])):
            eff = (kern - 1) * dilations[i] + 1
            out_sz = -(-size // strides[i])  # ceil
            total = max((out_sz - 1) * strides[i] + eff - size, 0)
            lo = total // 2 if auto_pad == "SAME_UPPER" \
                else total - total // 2
            pads[i], pads[i + 2] = lo, total - lo
    elif auto_pad not in ("NOTSET", "VALID"):
        raise NotImplementedError(f"Conv auto_pad={auto_pad}")
    else:
        pads = attrs.get("pads", [0, 0, 0, 0])  # t, l, b, r
    N, C, H, W = x.shape
    M, Cg, KH, KW = w.shape
    x = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]),
                   (pads[1], pads[3])))
    H_out = (x.shape[2] - (KH - 1) * dilations[0] - 1) // strides[0] + 1
    W_out = (x.shape[3] - (KW - 1) * dilations[1] - 1) // strides[1] + 1
    # im2col: (N, C, KH, KW, H_out, W_out)
    s = x.strides
    cols = np.lib.stride_tricks.as_strided(
        x,
        (N, C, KH, KW, H_out, W_out),
        (s[0], s[1], s[2] * dilations[0], s[3] * dilations[1],
         s[2] * strides[0], s[3] * strides[1]),
        writeable=False,
    )
    out = np.empty((N, M, H_out, W_out), np.float32)
    per_g_in, per_g_out = C // group, M // group
    for g in range(group):
        cg = cols[:, g * per_g_in:(g + 1) * per_g_in]
        wg = w[g * per_g_out:(g + 1) * per_g_out]
        # (N, HW, C*KH*KW) @ (C*KH*KW, M_g)
        lhs = cg.transpose(0, 4, 5, 1, 2, 3).reshape(
            N * H_out * W_out, -1)
        res = lhs @ wg.reshape(per_g_out, -1).T
        out[:, g * per_g_out:(g + 1) * per_g_out] = res.reshape(
            N, H_out, W_out, per_g_out).transpose(0, 3, 1, 2)
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


def _pool(x, attrs, reducer, is_avg):
    if attrs.get("ceil_mode"):
        raise NotImplementedError("pooling with ceil_mode=1")
    k = attrs["kernel_shape"]
    strides = attrs.get("strides", [1] * len(k))  # ONNX default: 1
    pads = attrs.get("pads", [0] * 4)
    fill = 0.0 if is_avg else -np.inf
    x = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]),
                   (pads[1], pads[3])), constant_values=fill)
    N, C, H, W = x.shape
    H_out = (H - k[0]) // strides[0] + 1
    W_out = (W - k[1]) // strides[1] + 1
    s = x.strides
    win = np.lib.stride_tricks.as_strided(
        x, (N, C, H_out, W_out, k[0], k[1]),
        (s[0], s[1], s[2] * strides[0], s[3] * strides[1], s[2], s[3]),
        writeable=False)
    out = reducer(win, axis=(4, 5))
    if is_avg and any(pads) and not attrs.get("count_include_pad"):
        # ONNX default excludes padding from the mean: rescale by the
        # (kernel area) / (valid elements) per output position
        ones = np.ones((1, 1) + (H - pads[0] - pads[2],
                                 W - pads[1] - pads[3]), x.dtype)
        ones = np.pad(ones, ((0, 0), (0, 0), (pads[0], pads[2]),
                             (pads[1], pads[3])))
        so = ones.strides
        counts = np.lib.stride_tricks.as_strided(
            ones, (1, 1, H_out, W_out, k[0], k[1]),
            (so[0], so[1], so[2] * strides[0], so[3] * strides[1],
             so[2], so[3]), writeable=False).sum(axis=(4, 5))
        out = out * (k[0] * k[1]) / counts
    return out


class _Runner:
    """One graph execution pass."""

    def __init__(self, nodes, env):
        self.env = env
        self.nodes = nodes

    def run(self, outputs):
        for node in self.nodes:
            self._exec(node)
        return [self.env[name] for name in outputs]

    def _in(self, node, i, default=None):
        names = node.get("input", [])
        if i >= len(names) or not names[i]:
            return default
        return self.env[names[i]]

    def _axes(self, attrs, node, idx=1):
        """axes as an attribute (opset <13) or an input (opset >=13)."""
        if "axes" in attrs:
            return tuple(attrs["axes"])
        axes_in = self._in(node, idx)
        if axes_in is not None:
            return tuple(int(v) for v in axes_in)
        return None

    def _exec(self, node):
        op = node["op_type"]
        attrs = _attrs(node)
        env = self.env
        x = self._in(node, 0)
        out_names = node["output"]

        if op == "Conv":
            r = _conv(np.asarray(x, np.float32),
                      np.asarray(self._in(node, 1), np.float32),
                      self._in(node, 2), attrs)
        elif op in ("MatMul",):
            r = np.matmul(x, self._in(node, 1))
        elif op == "Gemm":
            a, b = x, self._in(node, 1)
            if attrs.get("transA"):
                a = a.T
            if attrs.get("transB"):
                b = b.T
            r = attrs.get("alpha", 1.0) * (a @ b)
            c = self._in(node, 2)
            if c is not None:
                r = r + attrs.get("beta", 1.0) * c
        elif op == "Add":
            r = x + self._in(node, 1)
        elif op == "Sub":
            r = x - self._in(node, 1)
        elif op == "Mul":
            r = x * self._in(node, 1)
        elif op == "Div":
            r = x / self._in(node, 1)
        elif op == "Pow":
            r = np.power(x, self._in(node, 1))
        elif op == "Max":
            r = x
            for i in range(1, len(node["input"])):
                r = np.maximum(r, self._in(node, i))
        elif op == "Min":
            r = x
            for i in range(1, len(node["input"])):
                r = np.minimum(r, self._in(node, i))
        elif op == "Neg":
            r = -x
        elif op == "Abs":
            r = np.abs(x)
        elif op == "Exp":
            r = np.exp(x)
        elif op == "Log":
            r = np.log(x)
        elif op == "Sqrt":
            r = np.sqrt(x)
        elif op == "Reciprocal":
            r = 1.0 / x
        elif op == "Relu":
            r = np.maximum(x, 0)
        elif op == "LeakyRelu":
            alpha = attrs.get("alpha", 0.01)
            r = np.where(x >= 0, x, alpha * x)
        elif op == "Tanh":
            r = np.tanh(x)
        elif op == "Sigmoid":
            r = 1.0 / (1.0 + np.exp(-x))
        elif op == "Softmax":
            axis = attrs.get("axis", -1)
            e = np.exp(x - np.max(x, axis=axis, keepdims=True))
            r = e / e.sum(axis=axis, keepdims=True)
        elif op in ("GreaterOrEqual", "Greater", "LessOrEqual",
                    "Less", "Equal", "And", "Or", "Xor"):
            y = self._in(node, 1)
            r = {"GreaterOrEqual": np.greater_equal,
                 "Greater": np.greater,
                 "LessOrEqual": np.less_equal, "Less": np.less,
                 "Equal": np.equal, "And": np.logical_and,
                 "Or": np.logical_or, "Xor": np.logical_xor}[op](x, y)
        elif op == "Not":
            r = np.logical_not(x)
        elif op == "IsNaN":
            r = np.isnan(x)
        elif op == "IsInf":
            r = np.isinf(x)
        elif op == "Floor":
            r = np.floor(x)
        elif op == "Where":
            r = np.where(x, self._in(node, 1), self._in(node, 2))
        elif op in ("Identity", "Dropout"):
            r = x
        elif op == "Cast":
            r = np.asarray(x).astype(_DTYPES[attrs["to"]])
        elif op == "Constant":
            r = attrs["value"]
        elif op == "ConstantOfShape":
            value = attrs.get("value")
            fill = value.reshape(-1)[0] if value is not None else 0.0
            r = np.full([int(v) for v in x], fill,
                        value.dtype if value is not None else np.float32)
        elif op == "Shape":
            r = np.asarray(np.shape(x), np.int64)
        elif op == "Reshape":
            shape = [int(v) for v in self._in(node, 1)]
            shape = [x.shape[i] if v == 0 else v
                     for i, v in enumerate(shape)]
            r = np.reshape(x, shape)
        elif op == "Flatten":
            axis = attrs.get("axis", 1)
            lead = int(np.prod(x.shape[:axis])) if axis else 1
            r = np.reshape(x, (lead, -1))
        elif op == "Transpose":
            r = np.transpose(x, attrs.get("perm"))
        elif op == "Concat":
            parts = [self._in(node, i)
                     for i in range(len(node["input"]))]
            r = np.concatenate(parts, axis=attrs["axis"])
        elif op == "Split":
            axis = attrs.get("axis", 0)
            if "split" in attrs:
                sizes = attrs["split"]
            elif len(node.get("input", [])) > 1:
                sizes = [int(v) for v in self._in(node, 1)]
            else:
                sizes = [x.shape[axis] // len(out_names)] * len(out_names)
            pieces = np.split(x, np.cumsum(sizes)[:-1], axis=axis)
            for name, piece in zip(out_names, pieces):
                env[name] = piece
            return
        elif op == "Slice":
            if "starts" in attrs:  # opset <= 9 attribute form
                starts, ends = attrs["starts"], attrs["ends"]
                axes = attrs.get("axes",
                                 list(range(len(starts))))
                steps = [1] * len(starts)
            else:
                starts = [int(v) for v in self._in(node, 1)]
                ends = [int(v) for v in self._in(node, 2)]
                axes = ([int(v) for v in self._in(node, 3)]
                        if self._in(node, 3) is not None
                        else list(range(len(starts))))
                steps = ([int(v) for v in self._in(node, 4)]
                         if self._in(node, 4) is not None
                         else [1] * len(starts))
            idx = [slice(None)] * x.ndim
            for st, en, ax, sp in zip(starts, ends, axes, steps):
                idx[ax] = slice(st, en, sp)
            r = x[tuple(idx)]
        elif op == "Gather":
            r = np.take(x, np.asarray(self._in(node, 1), np.int64),
                        axis=attrs.get("axis", 0))
        elif op == "Expand":
            r = np.broadcast_to(
                x, np.broadcast_shapes(
                    x.shape, tuple(int(v) for v in self._in(node, 1))))
        elif op in ("Squeeze", "Unsqueeze"):
            axes = self._axes(attrs, node)
            if op == "Squeeze":
                r = np.squeeze(x, axis=axes)
            else:
                r = x
                for ax in sorted(axes):
                    r = np.expand_dims(r, ax)
        elif op in ("ReduceSum", "ReduceMean", "ReduceMax", "ReduceMin"):
            axes = self._axes(attrs, node)
            keep = bool(attrs.get("keepdims", 1))
            fn = {"ReduceSum": np.sum, "ReduceMean": np.mean,
                  "ReduceMax": np.max, "ReduceMin": np.min}[op]
            r = fn(x, axis=axes, keepdims=keep)
        elif op == "GlobalAveragePool":
            r = x.mean(axis=tuple(range(2, x.ndim)), keepdims=True)
        elif op == "MaxPool":
            r = _pool(x, attrs, np.max, is_avg=False)
        elif op == "AveragePool":
            r = _pool(x, attrs, np.mean, is_avg=True)
        elif op == "BatchNormalization":
            scale, b = self._in(node, 1), self._in(node, 2)
            mean, var = self._in(node, 3), self._in(node, 4)
            eps = attrs.get("epsilon", 1e-5)
            shape = (1, -1) + (1,) * (x.ndim - 2)
            r = (x - mean.reshape(shape)) / np.sqrt(
                var.reshape(shape) + eps)
            r = r * scale.reshape(shape) + b.reshape(shape)
        elif op == "Pad":
            mode = attrs.get("mode", "constant")
            if "pads" in attrs:
                pads = attrs["pads"]
                value = attrs.get("value", 0.0)
            else:
                pads = [int(v) for v in self._in(node, 1)]
                cval = self._in(node, 2)
                value = float(np.reshape(cval, -1)[0]) \
                    if cval is not None else 0.0
            n = x.ndim
            width = [(pads[i], pads[i + n]) for i in range(n)]
            np_mode = {"constant": "constant", "reflect": "reflect",
                       "edge": "edge", "wrap": "wrap"}[mode]
            kwargs = {"constant_values": value} \
                if np_mode == "constant" else {}
            r = np.pad(x, width, mode=np_mode, **kwargs)
        else:
            raise NotImplementedError(
                f"ONNX op {op!r} is not supported by the numpy runner")
        env[out_names[0]] = r


class OnnxModel:
    """Drop-in for the evaluation model slot: ``--eval model.onnx``.

    Mirrors the reference OnnxModel contract: hidden states are the
    graph inputs whose names start with ``hidden``; inference maps the
    observation pytree leaves onto the remaining inputs in order.
    """

    def __init__(self, model_path):
        self.model_path = model_path
        self._graph = None

    def _load(self):
        with open(self.model_path, "rb") as f:
            model = decode(f.read(), "Model")
        g = model["graph"]
        self._graph = g
        self._init = {t["name"]: tensor_to_numpy(t)
                      for t in g.get("initializer", [])}
        self._inputs = [vi for vi in g.get("input", [])
                        if vi["name"] not in self._init]
        self._outputs = [vi["name"] for vi in g.get("output", [])]
        self._hidden_inputs = [vi for vi in self._inputs
                               if vi["name"].startswith("hidden")]
        self._data_inputs = [vi for vi in self._inputs
                             if not vi["name"].startswith("hidden")]

    @staticmethod
    def _vi_shape(vi):
        dims = vi["type"]["tensor_type"]["shape"].get("dim", [])
        return [int(d.get("dim_value") or 0) for d in dims]

    def init_hidden(self, batch_size=None):
        if self._graph is None:
            self._load()
        if not self._hidden_inputs:
            return None
        lead = list(batch_size) if batch_size is not None else []
        return [np.zeros(lead + self._vi_shape(vi)[1:], np.float32)
                for vi in self._hidden_inputs]

    def inference(self, x, hidden=None, batch_input=False):
        if self._graph is None:
            self._load()
        import jax

        feeds = dict(self._init)
        leaves = jax.tree.leaves(x)
        if hidden is not None:
            leaves = leaves + list(jax.tree.leaves(hidden))
        vis = self._data_inputs + self._hidden_inputs
        if len(leaves) != len(vis):
            raise ValueError(
                f"model expects {len(vis)} inputs, got {len(leaves)}")
        for vi, leaf in zip(vis, leaves):
            # honor the graph's declared input dtype: third-party
            # graphs legitimately take int/bool feeds
            code = vi["type"]["tensor_type"].get("elem_type", DT_FLOAT)
            arr = np.asarray(leaf, _DTYPES.get(code, np.float32))
            feeds[vi["name"]] = arr if batch_input else arr[None]
        results = _Runner(self._graph.get("node", []), feeds).run(
            self._outputs)
        if not batch_input:
            results = [r[0] for r in results]
        outputs = dict(zip(self._outputs, results))
        hidden_out = [outputs.pop(k) for k in list(outputs)
                      if k.startswith("hidden")]
        outputs["hidden"] = hidden_out or None
        return outputs
