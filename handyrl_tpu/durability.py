"""Durability: checksummed checkpoints, a manifest, and an episode WAL.

Podracer-style fleets run learners on preemptible capacity, where the
LEARNER host — not just actors — is evicted mid-epoch (PAPERS.md).  The
resilience package (PR 3) made the worker fleet survive kills and the
IMPACT path (PR 7) made the math survive staleness; this module closes
the remaining gap, the learner's durable state itself:

  * **Checksummed checkpoints** — ``write_checksummed`` appends a
    sha256 footer to the atomic tmp+rename write, and ``read_verified``
    rejects truncated/bit-flipped/zero-length files with
    :class:`CorruptCheckpointError` instead of unpickling garbage.
    Legacy footer-less files still load (verified by unpickling only),
    so pre-durability runs resume unchanged.
  * **Manifest** — :class:`CheckpointManifest` records every landed
    epoch (path, digest, steps, wall time) in ``manifest.json``,
    updated transactionally with each save.  The manifest is the COMMIT
    POINT: an epoch exists once the manifest says so, and a corrupt
    ``latest``/``train_state.ckpt`` falls back to the newest entry
    whose on-disk bytes still match their recorded digest.
  * **Auto-resume** — ``resolve_restart`` turns ``restart_epoch: auto``
    (or a corrupt explicit epoch) into the newest VALID resume point,
    loudly, so recovering from a preemption needs no config surgery.
  * **Episode WAL** — :class:`EpisodeWAL` appends admitted episodes to
    segmented, crc-checksummed log files (one ``write()`` per record,
    fsync'd on a ``wal_flush_interval`` cadence) so a restarted learner
    replays its staged/assembled backlog instead of re-generating it.
    Segments roll when a checkpoint lands and retire once the newer
    segments alone cover the replay-buffer capacity — an episode that
    rotated out of the buffer was either consumed into a landed
    checkpoint or superseded, so its log is dead weight.

Everything here is plain host-side Python: no jax, no device state.
The learner wires it up (handyrl_tpu.learner); the chaos side lives in
resilience.chaos (``learner_kill_*``) and resilience.guardian (the
relaunch supervisor).
"""

import hashlib
import json
import os
import pickle
import struct
import time
import zlib

# Footer appended after the pickle payload: pickle.load reads exactly
# one pickle stream and ignores trailing bytes, so checksummed files
# stay loadable by legacy readers (and legacy files by this one).
CKPT_MAGIC = b"#hrlck:"
_FOOTER_LEN = len(CKPT_MAGIC) + 64  # magic + sha256 hexdigest

MANIFEST_NAME = "manifest.json"

# WAL record framing: payload length, crc32 of the payload, and a
# monotonically increasing per-WAL sequence number (the dedup key that
# makes double replay of a sealed segment idempotent).
_WAL_REC = struct.Struct("!IIQ")
_WAL_SUFFIX = ".wal"


class CorruptCheckpointError(Exception):
    """A checkpoint file failed digest verification (or unpickling)."""


class _TeeHash:
    """File wrapper that hashes bytes as pickle streams them — the
    digest comes free, without materializing a second full copy of a
    multi-GB train state in memory (``pickle.dumps`` would)."""

    __slots__ = ("f", "h")

    def __init__(self, f):
        self.f = f
        self.h = hashlib.sha256()

    def write(self, data):
        self.h.update(data)
        return self.f.write(data)


def write_checksummed(path, state, checksum=True):
    """Atomic checkpoint write (pickle tmp + fsync + rename), with a
    sha256 footer stamped after the payload when ``checksum`` is on.
    The pickle STREAMS to disk (hashed in flight) — peak memory stays
    one copy of the state.  Returns the payload digest ("" when
    checksumming is off)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        if checksum:
            tee = _TeeHash(f)
            pickle.dump(state, tee, protocol=pickle.HIGHEST_PROTOCOL)
            digest = tee.h.hexdigest()
            f.write(CKPT_MAGIC + digest.encode("ascii"))
        else:
            pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
            digest = ""
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return digest


def _read_footer(f, size):
    """Footer digest of an open checkpoint file, or None (legacy)."""
    if size <= _FOOTER_LEN:
        return None
    f.seek(size - _FOOTER_LEN)
    tail = f.read(_FOOTER_LEN)
    if tail[: len(CKPT_MAGIC)] != CKPT_MAGIC:
        return None
    return tail[len(CKPT_MAGIC):].decode("ascii", "replace")


def _hash_payload(f, payload_len, chunk=1 << 20):
    """sha256 of the first ``payload_len`` bytes, streamed in chunks —
    verification never holds a second full copy of a multi-GB
    checkpoint in memory (the write path's _TeeHash twin)."""
    h = hashlib.sha256()
    f.seek(0)
    left = payload_len
    while left > 0:
        block = f.read(min(chunk, left))
        if not block:
            break
        h.update(block)
        left -= len(block)
    return h.hexdigest()


def _verify_open(f, path, expect_digest):
    """Shared verification core: returns payload size after checking
    the footer/manifest digests, raising CorruptCheckpointError."""
    size = os.fstat(f.fileno()).st_size
    if size == 0:
        raise CorruptCheckpointError(f"{path}: zero-length file")
    footer = _read_footer(f, size)
    payload_len = size - _FOOTER_LEN if footer is not None else size
    if footer is not None or expect_digest:
        actual = _hash_payload(f, payload_len)
        if footer is not None and actual != footer:
            raise CorruptCheckpointError(
                f"{path}: content does not match its checksum footer")
        if expect_digest and actual != expect_digest:
            raise CorruptCheckpointError(
                f"{path}: content does not match the manifest digest")
    return footer, payload_len


def read_verified(path, expect_digest=None):
    """Load a checkpoint, verifying its footer (and, when given, the
    manifest-recorded ``expect_digest``).  Hashing streams in chunks
    and the pickle streams from the file — peak memory is the loaded
    object, not object + raw bytes.  Raises
    :class:`CorruptCheckpointError` on any mismatch, truncation, or
    unpickling failure; OSError passes through for missing files."""
    with open(path, "rb") as f:
        _verify_open(f, path, expect_digest)
        f.seek(0)
        try:
            # pickle.load reads exactly one stream; the footer bytes
            # past it are simply never consumed
            return pickle.load(f)
        except Exception as exc:  # truncated/garbage pickle streams
            # raise a zoo (UnpicklingError, EOFError, ValueError, ...)
            raise CorruptCheckpointError(f"{path}: {exc!r}") from exc


def verify_file(path, expect_digest=None):
    """True iff the checkpoint at ``path`` is intact; never raises.

    Cheap by design: when the file carries a footer (or the caller
    supplies a manifest digest), a streamed hash comparison IS the
    integrity proof and nothing is unpickled — resume scans over
    dozens of retained multi-hundred-MB checkpoints stay hash-bound.
    Only legacy footer-less files without an expected digest fall
    back to unpickle-verification."""
    try:
        with open(path, "rb") as f:
            footer, _ = _verify_open(f, path, expect_digest)
            if footer is not None or expect_digest:
                return True  # digest(s) checked above
            f.seek(0)
            pickle.load(f)  # legacy: only unpickling can vouch
            return True
    except Exception:  # garbage pickle streams raise a zoo; any of
        return False   # them means "not a valid checkpoint"


class CheckpointManifest:
    """``manifest.json``: the durable index of landed checkpoints.

    One JSON document, rewritten transactionally (tmp + fsync +
    rename) on every commit: ``entries`` maps epoch -> {path, digest,
    steps, wall_time}, and ``latest`` points at the newest resume
    point — normally the newest entry, but an emergency (SIGTERM
    grace-window) save re-points it at ``latest.ckpt`` with
    ``emergency: true`` so auto-resume picks up the mid-epoch state.
    A missing or corrupt manifest degrades to empty (resume then falls
    back to ``latest.ckpt`` scanning, see :func:`resolve_restart`)."""

    def __init__(self, models_dir):
        self.models_dir = models_dir
        self.path = os.path.join(models_dir, MANIFEST_NAME)

    def load(self):
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return {"version": 1, "entries": {}, "latest": None}
        data.setdefault("entries", {})
        data.setdefault("latest", None)
        return data

    def _write(self, data):
        os.makedirs(self.models_dir, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def commit(self, epoch, path, digest, steps,
               train_state_digest="", emergency=False):
        """Record one landed checkpoint and re-point ``latest``."""
        data = self.load()
        entry = {
            "path": path,
            "digest": digest,
            "steps": int(steps),
            "wall_time": time.time(),
            # the train-state digest AS OF this commit: restore uses
            # it to prove the single train_state.ckpt on disk is the
            # one that pairs with THIS epoch's params (an epoch number
            # alone cannot — an emergency save reuses the epoch tag)
            "train_state_digest": train_state_digest,
        }
        if not emergency:
            data["entries"][str(int(epoch))] = entry
        data["latest"] = {
            "epoch": int(epoch),
            "path": path,
            "digest": digest,
            "steps": int(steps),
            "train_state_digest": train_state_digest,
            "emergency": bool(emergency),
        }
        self._write(data)

    def forget(self, epochs):
        """Drop pruned epochs from the index (checkpoint retention)."""
        epochs = {str(int(e)) for e in epochs}
        data = self.load()
        kept = {e: v for e, v in data["entries"].items()
                if e not in epochs}
        if len(kept) != len(data["entries"]):
            data["entries"] = kept
            self._write(data)

    def valid_entries(self):
        """Lazily yield (epoch, entry) pairs newest-first whose
        on-disk files still match their recorded digests — the
        fallback ordering.  A generator on purpose: ``newest_valid``
        usually wants only the first hit, and verification reads the
        whole file (hash-only, but still I/O)."""
        data = self.load()
        for epoch_str, entry in sorted(
                data["entries"].items(), key=lambda kv: -int(kv[0])):
            path = os.path.join(self.models_dir,
                                os.path.basename(entry["path"]))
            if verify_file(path, entry.get("digest")):
                yield int(epoch_str), dict(entry, path=path)

    def newest_valid(self, below=None):
        """Newest (epoch, entry) that verifies, optionally restricted
        to epochs strictly below ``below``; None when nothing does."""
        for epoch, entry in self.valid_entries():
            if below is not None and epoch >= below:
                continue
            return epoch, entry
        return None


class ResumePoint:
    """Resolved restart decision: the epoch to resume as, the model
    file to load (None = fresh init), where the decision came from
    (``fresh`` / ``requested`` / ``manifest`` / ``emergency`` /
    ``latest`` / ``fallback``), and the manifest-recorded digest of
    the train state that PAIRS with these params ("" = unknown: the
    restore falls back to the epoch-match heuristic alone)."""

    __slots__ = ("epoch", "model_file", "source", "train_state_digest")

    def __init__(self, epoch, model_file, source,
                 train_state_digest=""):
        self.epoch = int(epoch)
        self.model_file = model_file
        self.source = source
        self.train_state_digest = train_state_digest or ""

    def __repr__(self):
        return (f"ResumePoint(epoch={self.epoch}, "
                f"source={self.source!r})")


def resolve_restart(models_dir, requested, latest_name="latest.ckpt"):
    """Turn ``restart_epoch`` (int or "auto") into a verified
    :class:`ResumePoint`, falling back LOUDLY when the preferred
    checkpoint is corrupt or missing.

    * ``auto``: the manifest's ``latest`` pointer (including emergency
      saves) if its file verifies, else the newest valid manifest
      entry, else a verifiable ``latest.ckpt`` (manifest lost), else a
      fresh start.
    * explicit epoch N: ``models/N.ckpt`` if it verifies; a corrupt or
      missing file falls back to the newest valid manifest entry below
      N (raising only when NOTHING valid exists for an explicit
      request — an unsatisfiable ask should fail, not silently train
      from scratch).
    """
    manifest = CheckpointManifest(models_dir)
    if requested in (0, "0", None, ""):
        return ResumePoint(0, None, "fresh")

    def _entry_point(epoch, entry, source):
        print(f"resume: epoch {epoch} from {entry['path']} "
              f"({source}, steps {entry.get('steps', '?')})")
        return ResumePoint(
            epoch, entry["path"], source,
            train_state_digest=entry.get("train_state_digest", ""))

    if requested == "auto":
        data = manifest.load()
        latest = data.get("latest")
        if latest:
            path = os.path.join(models_dir,
                                os.path.basename(latest["path"]))
            if verify_file(path, latest.get("digest")):
                source = ("emergency" if latest.get("emergency")
                          else "manifest")
                return _entry_point(latest["epoch"],
                                    dict(latest, path=path), source)
            print(f"WARNING: manifest latest (epoch "
                  f"{latest.get('epoch')}) failed verification; "
                  "falling back to older entries")
        newest = manifest.newest_valid()
        if newest is not None:
            return _entry_point(*newest, "manifest")
        # manifest gone/empty: a bare latest.ckpt is still a resume
        # (ONE read+unpickle: the load is its own verification)
        latest_path = os.path.join(models_dir, latest_name)
        try:
            state = read_verified(latest_path)
        except (OSError, CorruptCheckpointError):
            state = None
        if state is not None:
            epoch = int(state.get("epoch", 0) or 0)
            if epoch > 0:
                print(f"resume: epoch {epoch} from {latest_path} "
                      "(no manifest)")
                return ResumePoint(epoch, latest_path, "latest")
        print("restart_epoch: auto — no valid checkpoint found; "
              "starting fresh")
        return ResumePoint(0, None, "fresh")

    epoch = int(requested)
    path = os.path.join(models_dir, f"{epoch}.ckpt")
    # verify against the manifest-recorded digest when the epoch is
    # indexed (same contract as the auto path: a self-consistent file
    # that is NOT the committed bytes — e.g. restored from a backup of
    # a different run — must not silently impersonate the epoch);
    # unindexed legacy epochs verify standalone
    entry = manifest.load()["entries"].get(str(epoch)) or {}
    if verify_file(path, entry.get("digest") or None):
        return ResumePoint(
            epoch, path, "requested",
            train_state_digest=entry.get("train_state_digest", ""))
    print(f"WARNING: checkpoint for restart_epoch {epoch} is corrupt "
          f"or missing ({path})")
    newest = manifest.newest_valid(below=epoch)
    if newest is not None:
        fallback_epoch, entry = newest
        print(f"WARNING: falling back to the newest valid checkpoint, "
              f"epoch {fallback_epoch} (optimizer state for epoch "
              f"{epoch} will cold-start unless it matches)")
        return _entry_point(fallback_epoch, entry, "fallback")
    raise CorruptCheckpointError(
        f"restart_epoch {epoch}: no valid checkpoint at {path} and "
        "no valid manifest entry to fall back to")


class EpisodeWAL:
    """Segmented, checksummed write-ahead log of admitted episodes.

    Appends happen on the learner's server thread at intake, BEFORE
    the episode enters the replay buffer (write-ahead).  Each record
    is framed ``(len, crc32, seq)`` and written with ONE ``write()``
    call so a signal handler (or a preemption) can interleave only at
    record boundaries; fsync happens on the ``flush_interval`` cadence
    (0 = every append).  ``roll()`` cuts the active segment when a
    checkpoint lands, and ``retire(keep_episodes)`` drops the oldest
    sealed segments once the newer ones alone cover the replay
    buffer's capacity.

    Replay (:meth:`replay`) verifies every record's crc: a torn or
    corrupt record ends THAT segment's replay with a loud notice (the
    tail of a segment after a bad record is untrusted) and continues
    with the next segment.  The per-record ``seq`` makes replay
    idempotent — pass one ``seen`` set across calls and each episode
    is yielded once however many times its segment is scanned."""

    def __init__(self, wal_dir, segment_bytes=8 << 20,
                 flush_interval=1.0, clock=time.monotonic):
        self.dir = wal_dir
        self.segment_bytes = max(1, int(segment_bytes))
        self.flush_interval = max(0.0, float(flush_interval))
        self.clock = clock
        self._f = None
        self._f_path = None
        self._f_bytes = 0
        self._f_count = 0
        self._dirty = False
        self._last_flush = 0.0
        # metrics (cumulative for this process)
        self.appended = 0
        self.flushes = 0
        # per-segment episode counts for retirement; scanned at open
        self._seg_counts = {}
        self.seq = 0
        self._scan_existing()

    # -- bookkeeping --------------------------------------------------
    def _scan_existing(self):
        """Recover the sequence counter and per-segment episode counts
        from whatever segments a previous incarnation left behind.
        Header-only (frames + crc, no unpickling): on a resume the
        replay pass deserializes every record anyway, and paying that
        twice at startup would double the cost of exactly the restart
        this log exists to speed up."""
        for path in self.segments():
            count = 0
            for seq, _ in _iter_records(path, notice=False,
                                        payloads=False):
                self.seq = max(self.seq, seq)
                count += 1
            self._seg_counts[path] = count

    def segments(self):
        """Segment paths, oldest first (index-ordered filenames)."""
        try:
            names = [n for n in os.listdir(self.dir)
                     if n.endswith(_WAL_SUFFIX)]
        except OSError:
            return []
        return [os.path.join(self.dir, n)
                for n in sorted(names, key=_seg_index)]

    def episode_count(self):
        # dict(...) snapshot: the status endpoint's handler thread
        # reads this while the (single-writer) server thread may be
        # mid-roll/retire — iterating the live dict there would raise
        # "dictionary changed size during iteration"
        return sum(dict(self._seg_counts).values()) + self._f_count

    # -- append path --------------------------------------------------
    def _open_segment(self):
        os.makedirs(self.dir, exist_ok=True)
        segs = self.segments()
        index = _seg_index(os.path.basename(segs[-1])) + 1 if segs else 0
        self._f_path = os.path.join(
            self.dir, f"seg-{index:06d}{_WAL_SUFFIX}")
        self._f = open(self._f_path, "ab")
        self._f_bytes = 0
        self._f_count = 0

    def append(self, episode):
        """Log one admitted episode; returns its sequence number."""
        if self._f is None:
            self._open_segment()
        self.seq += 1
        payload = pickle.dumps(episode,
                               protocol=pickle.HIGHEST_PROTOCOL)
        record = _WAL_REC.pack(
            len(payload), zlib.crc32(payload), self.seq) + payload
        self._f.write(record)  # ONE write: interleave-safe boundary
        self._f_bytes += len(record)
        self._f_count += 1
        self.appended += 1
        self._dirty = True
        if self._f_bytes >= self.segment_bytes:
            self.roll()
        else:
            self.maybe_flush()
        return self.seq

    def maybe_flush(self, now=None):
        """fsync the active segment if the cadence says so."""
        if not self._dirty or self._f is None:
            return False
        if now is None:
            now = self.clock()
        if (self.flush_interval > 0
                and now - self._last_flush < self.flush_interval):
            return False
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = False
        self._last_flush = now
        self.flushes += 1
        return True

    def seal(self):
        """Force-fsync the active segment (SIGTERM grace window)."""
        if self._f is None:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = False
        self.flushes += 1

    def roll(self):
        """Cut the active segment (a checkpoint landed): it becomes a
        sealed, retirable unit and the next append opens a fresh one.
        No-op while the active segment is empty."""
        if self._f is None or self._f_count == 0:
            return
        self.seal()
        self._f.close()
        self._seg_counts[self._f_path] = self._f_count
        self._f = None
        self._f_path = None
        self._f_bytes = 0
        self._f_count = 0

    def retire(self, keep_episodes):
        """Drop the oldest SEALED segments whose episodes the newer
        ones already cover: a segment retires only when the segments
        after it hold >= ``keep_episodes`` episodes (the replay-buffer
        capacity — anything older has rotated out of the buffer and
        was consumed into a landed checkpoint).  Returns the paths
        removed."""
        keep_episodes = max(0, int(keep_episodes))
        sealed = [p for p in self.segments() if p in self._seg_counts
                  and p != self._f_path]
        removed = []
        for i, path in enumerate(sealed):
            newer = sum(self._seg_counts[p] for p in sealed[i + 1:])
            newer += self._f_count
            if newer < keep_episodes:
                break
            try:
                os.remove(path)
            except OSError:
                break
            removed.append(path)
            del self._seg_counts[path]
        if removed:
            print(f"wal: retired {len(removed)} segment(s) "
                  f"({self.episode_count()} episodes retained)")
        return removed

    def checkpoint_landed(self, keep_episodes):
        """Epoch-boundary hook: roll the active segment, then retire
        what the landed checkpoint made dead weight."""
        self.roll()
        self.retire(keep_episodes)

    def close(self):
        if self._f is not None:
            self.seal()
            self._f.close()
            self._f = None

    # -- replay -------------------------------------------------------
    def replay(self, seen=None):
        """Yield ``(seq, episode)`` for every intact logged record,
        oldest first, deduplicated against ``seen`` (a set of seqs the
        caller keeps across calls — double replay of a sealed segment
        admits each episode once)."""
        if seen is None:
            seen = set()
        for path in self.segments():
            for seq, episode in _iter_records(path, notice=True):
                if seq in seen:
                    continue
                seen.add(seq)
                yield seq, episode

    def stats(self):
        return {
            "wal_appended": self.appended,
            "wal_flushes": self.flushes,
            "wal_segments": len(self.segments()),
            "wal_episodes": self.episode_count(),
        }


def _seg_index(name):
    base = os.path.basename(name)
    try:
        return int(base[len("seg-"):-len(_WAL_SUFFIX)])
    except ValueError:
        return -1


def _iter_records(path, notice=True, payloads=True):
    """Records of one segment; stops at the first torn/corrupt record
    (the rest of that segment is untrusted).  ``payloads=False`` walks
    frames and checks crcs without unpickling (yielding ``(seq,
    None)``) — the cheap scan the open-time recovery uses."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return
    offset = 0
    while offset + _WAL_REC.size <= len(data):
        length, crc, seq = _WAL_REC.unpack_from(data, offset)
        start = offset + _WAL_REC.size
        payload = data[start:start + length]
        if len(payload) < length:
            if notice:
                print(f"wal: {os.path.basename(path)}: torn record at "
                      f"byte {offset} (crash tail); replay of this "
                      "segment stops here")
            return
        if zlib.crc32(payload) != crc:
            if notice:
                print(f"WARNING: wal: {os.path.basename(path)}: crc "
                      f"mismatch at byte {offset}; dropping the "
                      "segment's remaining records")
            return
        if payloads:
            try:
                episode = pickle.loads(payload)
            except Exception:
                if notice:
                    print(f"WARNING: wal: {os.path.basename(path)}: "
                          f"unpicklable record at byte {offset}; "
                          "dropping the segment's remaining records")
                return
        else:
            episode = None
        yield seq, episode
        offset = start + length
    if offset < len(data) and notice:
        print(f"wal: {os.path.basename(path)}: {len(data) - offset} "
              "trailing bytes (torn header) ignored")
