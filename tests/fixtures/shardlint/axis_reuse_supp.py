"""Fixture: suppressed axis-reuse."""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), ("dp", "tp"))


def weird_spec():
    # jaxlint: disable=axis-reuse -- documenting the invalid form in a repr test
    return P("dp", "dp")
