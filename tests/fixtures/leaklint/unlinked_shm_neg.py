"""Negative: the creator unlinks on teardown; a pure ATTACHER
(create=True absent) owes only close() — the segment belongs to its
creator."""

from multiprocessing import shared_memory


def scratch(size):
    seg = shared_memory.SharedMemory(create=True, size=size)
    try:
        seg.buf[0] = 1
    finally:
        seg.close()
        seg.unlink()
    return True


class Board:
    def __init__(self, size):
        self._seg = shared_memory.SharedMemory(create=True, size=size)

    def close(self):
        self._seg.close()
        self._seg.unlink()


class View:
    def __init__(self, name):
        self._seg = shared_memory.SharedMemory(name=name)

    def close(self):
        self._seg.close()
