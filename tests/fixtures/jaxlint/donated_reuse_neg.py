"""Fixture: the correct donate pattern — rebind from the outputs."""

import jax
import jax.numpy as jnp


def make_step():
    return jax.jit(lambda p, o, b: (p, o), donate_argnums=(0, 1))


def rebind_each_step(params, opt_state, batches):
    step = make_step()
    for batch in batches:
        params, opt_state = step(params, opt_state, batch)
    return params, opt_state


def norm_before_donate(params, opt_state, batch):
    step = make_step()
    norm = jnp.linalg.norm(params)  # read BEFORE donation: fine
    params, opt_state = step(params, opt_state, batch)
    return params, opt_state, norm
