"""Fixture: call-site layouts agree with the jit's in_shardings (or
are unknown, which stays quiet)."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), ("dp", "tp"))


def train_step(mesh, params, batch):
    rep = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("dp"))
    step = jax.jit(lambda p, b: (p, b.sum()), in_shardings=(rep, dp),
                   donate_argnums=(0,))
    params = jax.device_put(params, rep)  # matches in_shardings[0]
    return step(params, batch)            # batch layout unknown: quiet


class InferShardings:
    def __init__(self, params, obs):
        self.params = params
        self.obs = obs


def infer_shardings(mesh):
    return InferShardings(params=NamedSharding(mesh, P()),
                          obs=NamedSharding(mesh, P("dp")))


def serve_step(mesh, params, obs):
    # struct-builder fields resolve AND agree with the call site —
    # the quiet twin of the pos fixture's serve_step
    shards = infer_shardings(mesh)
    fwd = jax.jit(lambda p, o: (p * o).sum(),
                  in_shardings=(shards.params, shards.obs))
    obs = jax.device_put(obs, shards.obs)  # matches in_shardings[1]
    return fwd(params, obs)


def trailing_none_equivalence(mesh, params, batch):
    # P() and P(None, None) are the same fully-replicated spec: jax
    # normalizes trailing Nones, so no copy happens and none is flagged
    rep2 = NamedSharding(mesh, P(None, None))
    plain = NamedSharding(mesh, P())
    step = jax.jit(lambda p, b: (p, b.sum()), in_shardings=(rep2, None),
                   donate_argnums=(0,))
    params = jax.device_put(params, plain)
    return step(params, batch)
