"""Suppressed: the unhandled send carries a reasoned suppression."""


def client(conn):
    conn.send(("ping", 1))
    # jaxlint: disable=unhandled-verb -- consumed by an external monitoring sidecar outside this package
    conn.send(("zap", 2))


def server(hub):
    while True:
        conn, (verb, payload) = hub.recv(timeout=0.3)
        if verb == "ping":
            hub.send(conn, payload)
