"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import os
import re

import numpy as np
import pytest

import jax

from handyrl_tpu.parallel import (
    MeshSpec,
    inference_shardings,
    make_mesh,
    make_sharded_update_step,
)
from handyrl_tpu.parallel.mesh import batch_sharding, param_sharding


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")


def test_mesh_spec_from_config():
    spec = MeshSpec.from_config({"dp": 4, "tp": 2})
    assert spec.size == 8 and spec.shape() == (4, 1, 2)
    with pytest.raises(ValueError):
        MeshSpec.from_config({"bogus": 2})


def test_runtime_package_is_pmap_free():
    """ROADMAP item 2 closeout gate: ``jit`` + ``NamedSharding`` is
    the ONE mainline path.  The runtime package must carry no ``pmap``
    call and no fixed-device-count assumption — only ``analysis/`` may
    mention pmap, as a construct its rules lint.  A repo gate so the
    retired API cannot creep back in a refactor."""
    import handyrl_tpu

    root = os.path.dirname(os.path.abspath(handyrl_tpu.__file__))
    offenders = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        rel = os.path.relpath(dirpath, root)
        if rel == "analysis" or rel.startswith("analysis" + os.sep):
            continue  # the linter may NAME pmap; nothing may USE it
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path) as f:
                text = f.read()
            if re.search(r"\bpmap\b", text):
                offenders.append((os.path.relpath(path, root), "pmap"))
            if re.search(r"device_count\(\)\s*==\s*\d", text):
                offenders.append((os.path.relpath(path, root),
                                  "fixed device-count equality"))
    assert not offenders, f"GSPMD regression: {offenders}"


def test_make_mesh_oversized_spec_error_names_the_config_key():
    _need_devices(2)
    with pytest.raises(ValueError, match=r"`mesh:` config"):
        make_mesh(MeshSpec(dp=4), devices=jax.devices()[:2])


def test_make_mesh_nondividing_spec_warns(capsys):
    """A mesh shape that does not tile the device count used to eat
    the remainder silently; now it says which devices idle and names
    the config key."""
    _need_devices(8)
    mesh = make_mesh(MeshSpec(dp=3), devices=jax.devices()[:8])
    assert mesh.shape["dp"] == 3
    out = capsys.readouterr().out
    assert "3 of 8 devices" in out and "`mesh:`" in out
    # a dividing subset is a sanctioned choice: no warning
    make_mesh(MeshSpec(dp=4), devices=jax.devices()[:8])
    assert "WARNING" not in capsys.readouterr().out


def test_inference_shardings_contract():
    """params per the tp/fsdp rules, obs/out batch rows on dp — and a
    single-device mesh collapses everything to replication (the
    bit-identical guarantee's structural half)."""
    _need_devices(8)
    P = jax.sharding.PartitionSpec
    mesh = make_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices()[:8])
    params = {"wide": np.zeros((64, 256)), "bias": np.zeros((256,))}
    sh = inference_shardings(mesh, params)
    # jaxlint: disable=unknown-axis -- expected-value literal; tp is declared by parallel.mesh.AXES
    assert sh.params["wide"].spec == P(None, "tp")
    assert sh.params["bias"].spec == P()
    assert sh.obs.spec == P("dp")
    assert sh.out.spec == P("dp")
    fsdp = inference_shardings(mesh, params, fsdp=True)
    assert "dp" in tuple(fsdp.params["wide"].spec)
    one = make_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
    sh1 = inference_shardings(one, params)
    assert all(s.is_fully_replicated
               for s in jax.tree.leaves(sh1.params))


def test_make_mesh_default_all_dp():
    _need_devices(8)
    mesh = make_mesh()
    assert mesh.shape["dp"] == len(jax.devices())
    assert mesh.shape["tp"] == 1


def test_param_sharding_tp_rule():
    _need_devices(8)
    mesh = make_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices()[:8])
    params = {
        "dense": {"kernel": np.zeros((64, 256)), "bias": np.zeros((256,))},
        "conv": {"kernel": np.zeros((3, 3, 32, 128))},
        "head": {"kernel": np.zeros((32, 9))},
    }
    shardings = param_sharding(mesh, params)
    # wide kernels shard output features over tp.  (The expected-spec
    # literals name the tp axis make_mesh declares inside the package;
    # a tests-only lint scan cannot see that declaration.)
    # jaxlint: disable=unknown-axis -- expected-value literal; tp is declared by parallel.mesh.AXES
    assert shardings["dense"]["kernel"].spec == jax.sharding.PartitionSpec(None, "tp")
    conv_spec = shardings["conv"]["kernel"].spec
    # jaxlint: disable=unknown-axis -- expected-value literal; tp is declared by parallel.mesh.AXES
    assert conv_spec == jax.sharding.PartitionSpec(None, None, None, "tp")
    # biases and narrow heads replicate
    assert shardings["dense"]["bias"].spec == jax.sharding.PartitionSpec()
    assert shardings["head"]["kernel"].spec == jax.sharding.PartitionSpec()


def test_param_sharding_tp_boundaries():
    """The tp rule's edges: dim == min_tp_dim (128) is the smallest
    dim that shards; non-divisible dims and rank-1 params fall back to
    replication WITHOUT raising — an odd head size must degrade, not
    crash the learner at mesh build."""
    _need_devices(8)
    P = jax.sharding.PartitionSpec
    mesh = make_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices()[:8])
    params = {
        "at_floor": np.zeros((64, 128)),     # == min_tp_dim: shards
        "below_floor": np.zeros((64, 126)),  # divisible but < 128
        "indivisible": np.zeros((64, 129)),  # 129 % 2 != 0
        "rank1": np.zeros((256,)),           # bias-like: replicates
        "scalar": np.zeros(()),              # rank-0: replicates
    }
    shardings = param_sharding(mesh, params)
    assert shardings["at_floor"].spec == P(None, "tp")
    assert shardings["below_floor"].spec == P()
    assert shardings["indivisible"].spec == P()
    assert shardings["rank1"].spec == P()
    assert shardings["scalar"].spec == P()
    # the shardings are actually placeable (no deferred errors)
    placed = jax.device_put(params, shardings)
    assert jax.tree.structure(placed) == jax.tree.structure(params)


def test_param_sharding_min_tp_dim_is_tunable():
    _need_devices(8)
    P = jax.sharding.PartitionSpec
    mesh = make_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices()[:8])
    params = {"small": np.zeros((8, 32))}
    assert param_sharding(mesh, params)["small"].spec == P()
    lowered = param_sharding(mesh, params, min_tp_dim=32)
    assert lowered["small"].spec == P(None, "tp")


@pytest.mark.slow
def test_sharded_update_step_dp():
    """Full training step, batch sharded dp=4: compiles, runs, finite."""
    _need_devices(4)
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import _build_model_and_batch

    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer

    mesh = make_mesh(MeshSpec(dp=4), devices=jax.devices()[:4])
    model, batch, cfg = _build_model_and_batch(batch_size=4)
    loss_cfg = LossConfig.from_config(cfg)
    optimizer = make_optimizer(1e-3)
    params, opt_state = model.params, None
    opt_state = optimizer.init(params)

    update = make_sharded_update_step(model, loss_cfg, optimizer, mesh, params)
    params2, opt_state, metrics = update(params, opt_state, batch)
    assert np.isfinite(float(metrics["total"]))
    # params changed and stayed replicated
    leaf = jax.tree.leaves(params2)[0]
    assert leaf.sharding.is_fully_replicated


@pytest.mark.slow
def test_sharded_update_step_dp_sp():
    """Sequence parallelism: batch sharded dp=2 AND time sharded sp=2.

    The update step contains a reverse time-scan (targets) and a time
    matmul stream (forward); sharding T over ``sp`` forces XLA to
    insert the cross-slice collectives — this must still compile, run,
    and agree numerically with the unsharded step."""
    _need_devices(4)
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import _build_model_and_batch

    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer, make_update_step

    mesh = make_mesh(MeshSpec(dp=2, sp=2), devices=jax.devices()[:4])
    model, batch, cfg = _build_model_and_batch(batch_size=2)
    loss_cfg = LossConfig.from_config(cfg)

    optimizer = make_optimizer(1e-3)
    params_ref = jax.tree.map(jax.numpy.array, model.params)
    opt_ref = optimizer.init(params_ref)
    ref_step = make_update_step(model, loss_cfg, optimizer)
    params_ref, opt_ref, ref_metrics = ref_step(params_ref, opt_ref, batch)

    optimizer2 = make_optimizer(1e-3)
    params_sp = jax.tree.map(jax.numpy.array, model.params)
    opt_sp = optimizer2.init(params_sp)
    sp_step = make_sharded_update_step(
        model, loss_cfg, optimizer2, mesh, params_sp, shard_time=True)
    params_sp, opt_sp, sp_metrics = sp_step(params_sp, opt_sp, batch)

    # the sp-sharded step computes the same math
    assert float(sp_metrics["total"]) == pytest.approx(
        float(ref_metrics["total"]), rel=1e-4)
    ref_leaves = jax.tree.leaves(params_ref)
    sp_leaves = jax.tree.leaves(params_sp)
    for a, b in zip(ref_leaves, sp_leaves):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_sharded_update_step_bf16():
    """bf16 compute under a dp mesh: compiles, runs, finite metrics."""
    _need_devices(4)
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import _build_model_and_batch

    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer

    mesh = make_mesh(MeshSpec(dp=4), devices=jax.devices()[:4])
    model, batch, cfg = _build_model_and_batch(batch_size=4)
    loss_cfg = LossConfig.from_config(cfg)
    optimizer = make_optimizer(1e-3)
    params = jax.tree.map(jax.numpy.array, model.params)
    opt_state = optimizer.init(params)

    update = make_sharded_update_step(
        model, loss_cfg, optimizer, mesh, params, compute_dtype="bfloat16")
    params, opt_state, metrics = update(params, opt_state, batch)
    assert np.isfinite(float(metrics["total"]))
    # master params stay float32 under bf16 compute
    assert all(l.dtype == np.float32 for l in jax.tree.leaves(params))


@pytest.mark.slow
def test_multichip_infer_dryrun_8():
    """The GSPMD inference dry run (scripts/multichip_infer_dryrun.py,
    the CI slow-job artifact): dp4xtp2+fsdp serves with tp-sharded
    leaves, dp legs bit-match the unsharded forward, snapshots never
    recompile, zero resharding copies."""
    _need_devices(8)
    import json
    import pathlib
    import subprocess
    import sys

    script = (pathlib.Path(__file__).resolve().parents[1]
              / "scripts" / "multichip_infer_dryrun.py")
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    last = [line for line in proc.stdout.splitlines()
            if line.strip().startswith("{")][-1]
    rec = json.loads(last)
    assert rec["ok"] and rec["tp_sharded_leaves"] > 0
    assert rec["dp8_bitwise"] and rec["single_device_bitwise"]
    assert rec["infer_resharding_copies"] == 0


@pytest.mark.slow
def test_dryrun_multichip_8():
    _need_devices(8)
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_impact_target_params_shard_like_live_params():
    """``update_algorithm: impact`` threads the target net through the
    sharded step's trailing slot: target params must come back laid
    out EXACTLY like the live params (same pytree, same shardings),
    and the Adam moments must inherit the param layout structurally —
    under fsdp, where the layouts are actually non-trivial."""
    _need_devices(4)
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import _build_model_and_batch

    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer

    mesh = make_mesh(MeshSpec(dp=4), devices=jax.devices()[:4])
    model, batch, cfg = _build_model_and_batch(
        batch_size=4, env_name="TicTacToe")
    cfg = dict(cfg, update_algorithm="impact",
               target_update_interval=16)
    loss_cfg = LossConfig.from_config(cfg)
    optimizer = make_optimizer(1e-3)
    params = jax.tree.map(jax.numpy.array, model.params)
    target = jax.tree.map(jax.numpy.array, model.params)
    opt_state = optimizer.init(params)

    step = make_sharded_update_step(
        model, loss_cfg, optimizer, mesh, params, fsdp=True)
    params, opt_state, metrics, target = step(
        params, opt_state, batch, target)
    assert np.isfinite(float(metrics["total"]))

    p_leaves = jax.tree.leaves(params)
    t_leaves = jax.tree.leaves(target)
    assert jax.tree.structure(params) == jax.tree.structure(target)
    for p, t in zip(p_leaves, t_leaves):
        assert p.sharding == t.sharding, (p.sharding, t.sharding)
    # fsdp engaged for real: some param AND its moment shard over dp,
    # and the target leaf at the same position carries the same spec
    def dp_sharded(tree):
        return [l for l in jax.tree.leaves(tree)
                if "dp" in tuple(l.sharding.spec)]
    assert dp_sharded(params), "fsdp never sharded a param"
    assert dp_sharded(target), "target missed the param layout"
    assert dp_sharded(opt_state), "Adam moments missed the layout"


def test_param_sharding_fsdp_rule():
    _need_devices(8)
    mesh = make_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices()[:8])
    P = jax.sharding.PartitionSpec
    params = {
        "conv": {"kernel": np.zeros((3, 3, 64, 64)),   # big, no tp match
                 "bias": np.zeros((64,))},             # small: replicate
        "wide": {"kernel": np.zeros((64, 256))},       # tp takes last dim
    }
    shardings = param_sharding(mesh, params, fsdp=True)
    # fsdp shards the last free dim of large tensors over dp
    assert shardings["conv"]["kernel"].spec == P(None, None, None, "dp")
    # tp keeps the last dim; fsdp then takes the next free one
    assert shardings["wide"]["kernel"].spec == P("dp", "tp")
    # small tensors stay replicated (all-gather would cost more than it saves)
    assert shardings["conv"]["bias"].spec == P()
    assert MeshSpec.from_config({"dp": 4, "fsdp": True}).fsdp is True


@pytest.mark.slow
def test_fsdp_update_step_matches_replicated():
    """ZeRO sharding must not change the math: params + Adam moments
    shard over dp, and one update step agrees with the replicated run."""
    _need_devices(4)
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import _build_model_and_batch

    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer, make_update_step

    mesh = make_mesh(MeshSpec(dp=4), devices=jax.devices()[:4])
    model, batch, cfg = _build_model_and_batch(batch_size=4)
    loss_cfg = LossConfig.from_config(cfg)

    optimizer = make_optimizer(1e-3)
    params_ref = jax.tree.map(jax.numpy.array, model.params)
    opt_ref = optimizer.init(params_ref)
    ref_step = make_update_step(model, loss_cfg, optimizer)
    params_ref, opt_ref, ref_metrics = ref_step(params_ref, opt_ref, batch)

    optimizer2 = make_optimizer(1e-3)
    params_z = jax.tree.map(jax.numpy.array, model.params)
    opt_z = optimizer2.init(params_z)
    z_step = make_sharded_update_step(
        model, loss_cfg, optimizer2, mesh, params_z, fsdp=True)
    params_z, opt_z, z_metrics = z_step(params_z, opt_z, batch)

    # at least one param leaf AND its Adam moment actually sharded
    def dp_sharded(tree):
        return [l for l in jax.tree.leaves(tree)
                if "dp" in tuple(l.sharding.spec)]
    assert dp_sharded(params_z), "no param sharded over dp"
    assert dp_sharded(opt_z), "no optimizer moment sharded over dp"

    assert float(z_metrics["total"]) == pytest.approx(
        float(ref_metrics["total"]), rel=1e-4)
    for a, b in zip(jax.tree.leaves(params_ref),
                    jax.tree.leaves(params_z)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_tp_actually_partitions_wide_net():
    """With a 128-filter GeeseNet, the tp rule must shard real conv
    kernels and the update step must run end to end on a dp x tp mesh
    (VERDICT r3: the bundled 32-filter nets never engaged tp)."""
    _need_devices(8)
    import sys, pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from __graft_entry__ import _build_model_and_batch

    from handyrl_tpu.models import TPUModel
    from handyrl_tpu.models.geese_net import GeeseNet
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.update import make_optimizer

    mesh = make_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices()[:8])
    _, batch, cfg = _build_model_and_batch(batch_size=4)
    wide = TPUModel(GeeseNet(filters=128, blocks=2))
    obs_leaf = jax.tree.leaves(batch["observation"])[0]
    wide.init_params(np.asarray(obs_leaf[0, 0, 0], np.float32), seed=0)

    shardings = param_sharding(mesh, wide.params)
    tp_kernels = [l for l in jax.tree.leaves(shardings)
                  if "tp" in tuple(l.spec)]
    assert tp_kernels, "128-filter net must engage the tp rule"

    loss_cfg = LossConfig.from_config(cfg)
    optimizer = make_optimizer(1e-3)
    params = wide.params
    opt_state = optimizer.init(params)
    update = make_sharded_update_step(
        wide, loss_cfg, optimizer, mesh, params)
    params, opt_state, metrics = update(params, opt_state, batch)
    assert np.isfinite(float(metrics["total"]))
    # a tp-sharded kernel went through the step still tp-sharded
    sharded_after = [l for l in jax.tree.leaves(params)
                     if "tp" in tuple(l.sharding.spec)]
    assert sharded_after, "tp sharding lost through the update step"
