"""Geister: partial-observability 2-player board game (the RNN workload).

Behavioral parity with /root/reference/handyrl/envs/geister.py:169-553:
6x6 board, 8 pieces per side (4 blue "good" + 4 red "bad") with types
hidden from the opponent, a setup phase choosing one of C(8,4)=70
layouts, win by reaching a goal corner with a blue piece / capturing all
opponent blues / forcing the opponent to capture all your reds; 200-turn
draw, per-step reward -0.01, and a delta-sync protocol that discloses a
captured piece's type only to the capturing player.

Action space (214):
  moves:  a = d * 36 + x * 6 + y  (four directions over 36 cells,
          encoded in the mover's own rotated frame)    [0, 144)
  setup:  a = 144 + layout_index                        [144, 214)

Observation (channel-last for TPU convs): ``{"scalar": (18,),
"board": (6, 6, 7)}`` — turn flags + remaining-piece-count one-hots,
and board planes (zone, own pieces, opponent pieces, own blue/red,
opponent blue/red — opponent types zeroed for players).
"""

import itertools
import random

import numpy as np

from ..environment import BaseEnvironment

BLACK, WHITE = 0, 1
BLUE, RED = 0, 1
EMPTY = -1
NUM_MOVE_ACTIONS = 4 * 36
NUM_SET_ACTIONS = 70

X_NAMES, Y_NAMES = "ABCDEF", "123456"
COLOR_NAMES, TYPE_NAMES = "BW", "BR"
PIECE_GLYPH = {EMPTY: "_", 0: "B", 1: "R", 2: "b", 3: "r", 4: "*"}

# four move directions in (x, y): up, left, right, down
DIRECTIONS = np.array([(-1, 0), (0, -1), (0, 1), (1, 0)], dtype=np.int32)

# initial placement squares per color (owner's two home rows)
HOME_SQUARES = [
    ["B2", "C2", "D2", "E2", "B1", "C1", "D1", "E1"],
    ["E5", "D5", "C5", "B5", "E6", "D6", "C6", "B6"],
]

# goal (exit) squares just off-board, per color
GOALS = np.array([[(-1, 5), (6, 5)], [(-1, 0), (6, 0)]], dtype=np.int32)

# all 70 ways to pick which 4 of the 8 home squares get blue pieces
LAYOUTS = list(itertools.combinations(range(8), 4))


def piece_of(color, ptype):
    return color * 2 + ptype


def color_of(piece):
    return EMPTY if piece == EMPTY else piece // 2


def type_of(piece):
    return EMPTY if piece == EMPTY else piece % 2


class Environment(BaseEnvironment):
    def __init__(self, args=None):
        super().__init__(args)
        self.args = args if args is not None else {}
        self.reset()

    def reset(self, args=None):
        self.board = np.full((6, 6), EMPTY, dtype=np.int32)
        self.piece_cnt = np.zeros(4, dtype=np.int32)
        self.color = BLACK
        self.turn_count = -2  # two setup actions precede the first move
        self.win_color = None
        self.record = []
        self.captured_type = None
        self.layouts = {}

    # -- coordinate helpers -----------------------------------------
    @staticmethod
    def _onboard(pos):
        return 0 <= pos[0] < 6 and 0 <= pos[1] < 6

    @staticmethod
    def _rotate(pos):
        return np.array((5 - pos[0], 5 - pos[1]), dtype=np.int32)

    @staticmethod
    def _goal(color, pos):
        return any(g[0] == pos[0] and g[1] == pos[1] for g in GOALS[color])

    def position2str(self, pos):
        if self._onboard(pos):
            return X_NAMES[pos[0]] + Y_NAMES[pos[1]]
        return "**"

    def str2position(self, s):
        if s == "**":
            return None
        return np.array((X_NAMES.find(s[0]), Y_NAMES.find(s[1])),
                        dtype=np.int32)

    # -- action encoding (mover's own rotated frame) -----------------
    def _encode_move(self, pos_from, d, color):
        if color == WHITE:
            pos_from = self._rotate(pos_from)
            d = 3 - d
        return d * 36 + pos_from[0] * 6 + pos_from[1]

    def action2from(self, a, color):
        pos1d = a % 36
        pos = np.array((pos1d // 6, pos1d % 6), dtype=np.int32)
        return self._rotate(pos) if color == WHITE else pos

    def action2direction(self, a, color):
        d = a // 36
        return 3 - d if color == WHITE else d

    def action2to(self, a, color):
        return self.action2from(a, color) + DIRECTIONS[
            self.action2direction(a, color)]

    def action2str(self, a, player=None):
        if a >= NUM_MOVE_ACTIONS:
            return "s" + str(a - NUM_MOVE_ACTIONS)
        c = player
        return (self.position2str(self.action2from(a, c))
                + self.position2str(self.action2to(a, c)))

    def str2action(self, s, player=None):
        if s[0] == "s":
            return NUM_MOVE_ACTIONS + int(s[1:])
        c = player
        pos_from = self.str2position(s[:2])
        pos_to = self.str2position(s[2:])
        if pos_to is None:
            # off-board: the unique adjacent goal square
            d = 0
            for g in GOALS[c]:
                if ((pos_from - g) ** 2).sum() == 1:
                    diff = g - pos_from
                    for d, dd in enumerate(DIRECTIONS):
                        if np.array_equal(dd, diff):
                            break
                    break
        else:
            diff = pos_to - pos_from
            for d, dd in enumerate(DIRECTIONS):
                if np.array_equal(dd, diff):
                    break
        return self._encode_move(pos_from, d, c)

    # -- transitions -------------------------------------------------
    def _set_pieces(self, color, layout):
        self.layouts[color] = layout
        if layout < 0:
            layout = random.randrange(NUM_SET_ACTIONS)
        blues = LAYOUTS[layout]
        for idx in range(8):
            ptype = BLUE if idx in blues else RED
            piece = piece_of(color, ptype)
            pos = self.str2position(HOME_SQUARES[color][idx])
            self.board[pos[0], pos[1]] = piece
            self.piece_cnt[piece] += 1
        self.color = BLACK + WHITE - self.color
        self.turn_count += 1

    def play(self, action, player=None):
        if self.turn_count < 0:
            return self._set_pieces(self.color, action - NUM_MOVE_ACTIONS)

        pos_from = self.action2from(action, self.color)
        pos_to = self.action2to(action, self.color)
        piece = self.board[pos_from[0], pos_from[1]]
        self.captured_type = None

        if not self._onboard(pos_to):
            # a blue piece exits through the goal: immediate win
            self.board[pos_from[0], pos_from[1]] = EMPTY
            self.piece_cnt[piece] -= 1
            self.win_color = self.color
        else:
            captured = self.board[pos_to[0], pos_to[1]]
            if captured != EMPTY:
                self.piece_cnt[captured] -= 1
                if self.piece_cnt[captured] == 0:
                    if type_of(captured) == BLUE:
                        # captured every opponent blue: win
                        self.win_color = self.color
                    else:
                        # captured every opponent red: loss
                        self.win_color = BLACK + WHITE - self.color
                self.captured_type = type_of(captured)
            self.board[pos_to[0], pos_to[1]] = piece
            self.board[pos_from[0], pos_from[1]] = EMPTY

        self.color = BLACK + WHITE - self.color
        self.turn_count += 1
        self.record.append(action)

        if self.turn_count >= 200 and self.win_color is None:
            self.win_color = 2  # draw

    # -- delta-sync protocol -----------------------------------------
    def diff_info(self, player=None):
        color = player
        played_color = (self.turn_count - 1) % 2
        info = {}
        if len(self.record) == 0:
            if self.turn_count > -2:
                # setup: disclose the layout only to its owner
                info["set"] = (self.layouts[played_color]
                               if color == played_color else -1)
        else:
            info["move"] = self.action2str(self.record[-1], played_color)
            if color == played_color and self.captured_type is not None:
                # the capturer learns the captured piece's type
                info["captured"] = TYPE_NAMES[self.captured_type]
        return info

    def update(self, info, reset):
        if reset:
            self.reset(info)
        elif "set" in info:
            self._set_pieces(self.color, info["set"])
        elif "move" in info:
            action = self.str2action(info["move"], self.color)
            if "captured" in info:
                # reveal the captured piece's type on the mirror board
                pos_to = self.action2to(action, self.color)
                t = TYPE_NAMES.index(info["captured"])
                self.board[pos_to[0], pos_to[1]] = piece_of(
                    BLACK + WHITE - self.color, t)
            self.play(action)

    # -- framework interface -----------------------------------------
    def turn(self):
        return self.players()[self.turn_count % 2]

    def terminal(self):
        return self.win_color is not None

    def reward(self):
        # small constant time pressure (reference geister.py:435-437)
        return {p: -0.01 for p in self.players()}

    def outcome(self):
        outcomes = [0, 0]
        if self.win_color == BLACK:
            outcomes = [1, -1]
        elif self.win_color == WHITE:
            outcomes = [-1, 1]
        return {p: outcomes[i] for i, p in enumerate(self.players())}

    def _legal_dest(self, color, ptype, pos_to):
        if self._onboard(pos_to):
            return color_of(self.board[pos_to[0], pos_to[1]]) != color
        return ptype == BLUE and self._goal(color, pos_to)

    def legal(self, action):
        if self.turn_count < 0:
            return 0 <= action - NUM_MOVE_ACTIONS < NUM_SET_ACTIONS
        if not 0 <= action < NUM_MOVE_ACTIONS:
            return False
        pos_from = self.action2from(action, self.color)
        piece = self.board[pos_from[0], pos_from[1]]
        if color_of(piece) != self.color:
            return False
        return self._legal_dest(
            self.color, type_of(piece), self.action2to(action, self.color))

    def legal_actions(self, player=None):
        if self.turn_count < 0:
            return [NUM_MOVE_ACTIONS + i for i in range(NUM_SET_ACTIONS)]
        actions = []
        for x in range(6):
            for y in range(6):
                piece = self.board[x, y]
                if piece == EMPTY or color_of(piece) != self.color:
                    continue
                pos = np.array((x, y), dtype=np.int32)
                for d in range(4):
                    if self._legal_dest(self.color, type_of(piece),
                                        pos + DIRECTIONS[d]):
                        actions.append(self._encode_move(pos, d, self.color))
        return actions

    def players(self):
        return [0, 1]

    def observation(self, player=None):
        turn_view = player is None or player == self.turn()
        color = self.color if turn_view else BLACK + WHITE - self.color
        opponent = BLACK + WHITE - color

        counts = []
        for c, t in ((color, BLUE), (color, RED),
                     (opponent, BLUE), (opponent, RED)):
            n = self.piece_cnt[piece_of(c, t)]
            counts.extend([1.0 if n == i else 0.0 for i in range(1, 5)])

        scalar = np.array(
            [1.0 if color == BLACK else 0.0, 1.0 if turn_view else 0.0]
            + counts, dtype=np.float32)

        blue_c = self.board == piece_of(color, BLUE)
        red_c = self.board == piece_of(color, RED)
        blue_o = self.board == piece_of(opponent, BLUE)
        red_o = self.board == piece_of(opponent, RED)
        zeros = np.zeros_like(self.board, dtype=bool)

        planes = np.stack([
            np.ones((6, 6), dtype=bool),
            blue_c | red_c,
            blue_o | red_o,
            blue_c,
            red_c,
            # opponent piece types are hidden from players
            blue_o if player is None else zeros,
            red_o if player is None else zeros,
        ], axis=-1).astype(np.float32)  # (6, 6, C) channel-last

        if color == WHITE:
            planes = np.rot90(planes, k=2, axes=(0, 1)).copy()
        return {"scalar": scalar, "board": planes}

    def net(self):
        from ..models.geister_net import GeisterNet

        return GeisterNet()

    def __str__(self):
        def glyph(piece):
            if piece == EMPTY:
                return PIECE_GLYPH[EMPTY]
            if self.layouts.get(color_of(piece), 0) < 0:
                return PIECE_GLYPH[4]
            return PIECE_GLYPH[piece]

        s = "  " + " ".join(Y_NAMES) + "\n"
        for x in range(6):
            s += X_NAMES[x] + " " + " ".join(
                glyph(self.board[x, y]) for y in range(6)) + "\n"
        s += "remained = B:%d R:%d b:%d r:%d\n" % tuple(self.piece_cnt)
        s += ("turn = " + str(self.turn_count).ljust(3)
              + " color = " + COLOR_NAMES[self.color])
        return s


if __name__ == "__main__":
    e = Environment()
    for _ in range(3):
        e.reset()
        while not e.terminal():
            e.play(random.choice(e.legal_actions()))
        print(e)
        print(e.outcome())
