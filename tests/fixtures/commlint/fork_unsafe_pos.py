"""Positive: forks after threads started, under a held lock, and a raw
os.fork — the child inherits locks whose owners do not exist."""

import multiprocessing as mp
import os
import threading


def spawn_after_threads(target):
    t = threading.Thread(target=target, daemon=True)
    t.start()
    proc = mp.Process(target=target)     # fork after threads started
    proc.start()
    return proc


def fork_under_lock(target):
    lock = threading.Lock()
    with lock:
        proc = mp.Process(target=target)  # fork while a lock is held
        proc.start()
    return proc


def raw_fork(handler):
    t = threading.Thread(target=handler, daemon=True)
    t.start()
    pid = os.fork()                      # os.fork after threads
    return pid
