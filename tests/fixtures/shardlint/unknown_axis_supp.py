"""Fixture: suppressed unknown-axis (spec belongs to an external mesh
the analyzer cannot see)."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "tp")


def make_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1, 1), AXES)


def batch_sharding(mesh):
    # jaxlint: disable=unknown-axis -- spec targets the caller's externally built mesh
    return NamedSharding(mesh, P("data"))
